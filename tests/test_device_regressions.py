"""Device-engine regressions: host and tpu engines must agree.

Each case was a reproduced divergence (code review round 1): empty global
aggregate, NULL-vs--1 group key collision, first_row NULL preservation."""

import time

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database devreg")
    tk.must_exec("use devreg")
    tk.must_exec("create table t (a bigint, b bigint)")
    tk.must_exec("insert into t values (-1, 1), (null, 2), (5, 3)")
    tk.must_exec("create table t2 (g bigint, b bigint)")
    tk.must_exec("insert into t2 values (1, null), (1, 7)")
    return tk


def both_engines(tk, sql):
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    tpu = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert host == tpu, f"\nhost: {host}\ntpu:  {tpu}"
    return host


def test_empty_global_agg(tk):
    rows = both_engines(
        tk, "select count(*), sum(b), min(b) from t where a > 100")
    assert rows == [("0", None, None)]


def test_null_key_not_merged_with_minus_one(tk):
    rows = both_engines(
        tk, "select a, count(*) from t group by a order by a is null, a")
    assert rows == [("-1", "1"), ("5", "1"), (None, "1")]


def test_first_row_keeps_null(tk):
    rows = both_engines(tk, "select g, b from t2 group by g")
    assert rows == [("1", None)]


def test_min_max_with_nulls_and_negatives(tk):
    rows = both_engines(
        tk, "select a, min(b), max(b), avg(b) from t group by a "
            "order by a is null, a")
    assert rows == [("-1", "1", "1", "1.0000"),
                    ("5", "3", "3", "3.0000"),
                    (None, "2", "2", "2.0000")]


class TestCountDistinctDevice:
    """COUNT(DISTINCT) on the device kernel: value-runs per group in a
    value-extended sort (ops/device.py cnt_dist), with collation-aware
    parity against the host engine (which dedups _ci strings by sort
    key — 'abc' and 'ABC' are ONE distinct value, MySQL semantics)."""

    @pytest.fixture()
    def dtk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table cdt (g bigint, v bigint, "
                     "sv varchar(8) collate utf8mb4_general_ci)")
        vals = ",".join(
            f"({i % 4}, {(i * 7) % 23}, "
            f"'{'AbC' if i % 3 else 'aBc'}{i % 5}')" for i in range(3000))
        tk.must_exec(f"insert into cdt values {vals}")
        tk.must_exec("insert into cdt values (1, null, null)")
        return tk

    def _parity(self, tk, sql):
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(sql).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        dev = tk.must_query(sql).rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert host == dev, (host[:4], dev[:4])
        return host

    def test_int_count_distinct(self, dtk):
        rows = self._parity(dtk, "select g, count(distinct v), count(v) "
                                 "from cdt group by g order by g")
        assert len(rows) == 4

    def test_ci_string_count_distinct(self, dtk):
        rows = self._parity(dtk, "select g, count(distinct sv) from cdt "
                                 "group by g order by g")
        # 5 suffixes; AbC/aBc collate equal under _ci → 5 distinct
        assert all(r[1] == "5" for r in rows), rows

    def test_global_count_distinct(self, dtk):
        self._parity(dtk, "select count(distinct v), count(distinct sv), "
                          "count(*) from cdt")

    def test_nulls_excluded(self, dtk):
        rows = self._parity(dtk, "select count(distinct v) from cdt "
                                 "where g = 1")
        assert rows  # the injected NULL row never counts

    def test_null_group_key_with_garbage_data(self, dtk):
        """Rows in a NULL-keyed group carry arbitrary underlying data
        (join gathers clip to real rows); the group sort must mask the
        key under the null flag or distinct runs splinter (review r4)."""
        tk = dtk
        tk.must_exec("create table ng (k bigint, v bigint)")
        vals = ",".join(
            (f"(null, {i % 6})" if i % 2 else f"({i % 3}, {i % 6})")
            for i in range(2000))
        tk.must_exec(f"insert into ng values {vals}")
        self._parity(tk, "select k, count(distinct v), count(*) from ng "
                         "group by k order by k")


def test_engine_hint_survives_nested_subquery_eval():
    """Advisor r4 (medium): a correlated/EXISTS subquery executed
    mid-statement goes through Session.run_query -> build_executor, which
    resets the statement-scoped READ_FROM_STORAGE pin on the shared
    session; the outer statement's pin must be restored so fragments built
    after the first subquery evaluation still honor the hint."""
    from tidb_tpu.testkit import TestKit
    tk = TestKit()
    tk.must_exec("create table eh (a int, b int)")
    tk.must_exec("insert into eh values (1, 10), (2, 20)")
    sess = tk.session
    sess.stmt_engine_hint = "host"  # outer statement's pin
    from tidb_tpu.parser import parse_one
    stmt = parse_one("select min(a) from eh")
    rows, _fts = sess._expr_ctx.eval_subquery(stmt)
    assert rows
    assert sess.stmt_engine_hint == "host"
    # and the built-plan path (uncorrelated subquery reuse)
    plan = sess.plan_query(parse_one("select max(a) from eh"))
    sess.stmt_engine_hint = "host"
    rows, _fts = sess._expr_ctx.eval_built_plan(plan)
    assert rows
    assert sess.stmt_engine_hint == "host"


class TestTopkCacheGuard:
    """Regression (ISSUE 11 guarded-state): _TOPK_CACHE was a bare dict.
    The fence path (supervisor._reinit_backend) cleared it UNLOCKED
    while executor threads installed kernels into it, so an install
    racing the clear could re-publish a kernel pinning the torn-down
    PJRT client.  Structural access now happens under _PIPE_LOCK."""

    @staticmethod
    def _topk(device_exec, vals, k=2):
        import jax.numpy as jnp
        keys = [jnp.asarray(vals, dtype=jnp.int64)]
        nulls = [jnp.zeros(len(vals), dtype=bool)]
        return device_exec._topk_indices(
            keys, nulls, [], [], len(vals) - 1, len(vals),
            (("key", 0, False),), k)

    def test_lookup_and_install_hold_pipe_lock(self, monkeypatch):
        from tidb_tpu.executor import device_exec

        class AssertingDict(dict):
            def get(self, *a, **k):
                assert device_exec._PIPE_LOCK.locked()
                return dict.get(self, *a, **k)

            def setdefault(self, *a, **k):
                assert device_exec._PIPE_LOCK.locked()
                return dict.setdefault(self, *a, **k)

        monkeypatch.setattr(device_exec, "_TOPK_CACHE", AssertingDict())
        # cold install, then a cache hit: both sides locked
        for _ in range(2):
            idx = self._topk(device_exec, [3, 1, 2, 0])
            assert [int(i) for i in idx] == [1, 2]
        assert len(device_exec._TOPK_CACHE) == 1

    def test_fence_clear_runs_under_pipe_lock(self, monkeypatch):
        import jax
        from tidb_tpu.executor import device_exec, supervisor

        cleared = []

        class AssertingDict(dict):
            def clear(self):
                cleared.append(device_exec._PIPE_LOCK.locked())
                return dict.clear(self)

        monkeypatch.setattr(device_exec, "_TOPK_CACHE",
                            AssertingDict(stale="kernel"))
        # pretend off-CPU so the fence takes the real clear path, but
        # neutralize the client teardown (the in-process CPU client must
        # survive for the rest of the suite)
        monkeypatch.setattr(jax, "default_backend", lambda: "faketpu")
        monkeypatch.setattr(jax, "clear_caches", lambda: None)
        be = getattr(getattr(jax, "extend", None), "backend", None)
        if be is not None and hasattr(be, "clear_backends"):
            monkeypatch.setattr(be, "clear_backends", lambda: None)
        if hasattr(jax, "clear_backends"):
            monkeypatch.setattr(jax, "clear_backends", lambda: None)
        supervisor._reinit_backend()
        assert cleared == [True]
        assert dict(device_exec._TOPK_CACHE) == {}

    def test_concurrent_install_and_clear_consistent(self):
        """Threaded chaos assertion: installs racing clears corrupt
        nothing — every call returns the right indices and the cache
        ends structurally sound."""
        import threading
        from tidb_tpu.executor import device_exec

        errs = []

        def hammer(vals, want):
            try:
                for _ in range(12):
                    idx = self._topk(device_exec, vals)
                    assert [int(i) for i in idx] == want
            except Exception as e:  # pragma: no cover - fail loudly
                errs.append(e)

        def clearer():
            try:
                for _ in range(30):
                    with device_exec._PIPE_LOCK:
                        device_exec._TOPK_CACHE.clear()
                    time.sleep(0.002)
            except Exception as e:  # pragma: no cover - fail loudly
                errs.append(e)

        threads = [
            threading.Thread(target=hammer, args=([3, 1, 2, 0], [1, 2])),
            threading.Thread(target=hammer, args=([9, 5, 7, 0], [1, 2])),
            threading.Thread(target=clearer),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
