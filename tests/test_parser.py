"""Parser tests (reference test model: parser/parser_test.go)."""

import pytest

from tidb_tpu.errors import ParseError
from tidb_tpu.parser import ast, digest, normalize, parse, parse_one


def test_simple_select():
    s = parse_one("SELECT a, b+1 AS c FROM t WHERE a > 10 ORDER BY b DESC LIMIT 5")
    assert isinstance(s, ast.SelectStmt)
    assert len(s.fields) == 2
    assert s.fields[1].as_name == "c"
    assert isinstance(s.where, ast.BinaryOp) and s.where.op == ">"
    assert s.order_by[0].desc
    assert s.limit.count.val == 5


def test_select_star_and_qualified():
    s = parse_one("select *, t.*, db.t.* from db.t")
    assert isinstance(s.fields[0].expr, ast.StarExpr)
    assert s.fields[1].expr.table == "t"
    assert s.fields[2].expr.schema == "db"


def test_operator_precedence():
    s = parse_one("select 1 + 2 * 3 = 7 and 2 < 3 or not 1")
    e = s.fields[0].expr
    assert isinstance(e, ast.BinaryOp) and e.op == "or"
    land = e.left
    assert land.op == "and"
    eq = land.left
    assert eq.op == "="
    assert eq.left.op == "+"
    assert eq.left.right.op == "*"


def test_predicates():
    s = parse_one("select * from t where a between 1 and 10 and b not in (1,2,3) "
                  "and c like 'x%' and d is not null and e in (select f from u)")
    w = s.where
    # and-chain; just check restore round-trips through parse again
    parse_one(s.restore())


def test_joins():
    s = parse_one("select * from a join b on a.x=b.x left join c on b.y=c.y, d")
    f = s.from_
    assert isinstance(f, ast.Join) and f.kind == "cross"
    lj = f.left
    assert lj.kind == "left"
    assert lj.left.kind == "inner"


def test_join_using():
    s = parse_one("select * from a join b using (x, y)")
    assert s.from_.using == ["x", "y"]


def test_subquery_table():
    s = parse_one("select * from (select a from t) s where s.a > 1")
    assert isinstance(s.from_, ast.SubqueryTable)
    assert s.from_.as_name == "s"


def test_union():
    s = parse_one("select a from t union all select b from u union select c from v "
                  "order by 1 limit 10")
    assert isinstance(s, ast.SetOprStmt)
    assert s.ops == ["union all", "union"]
    assert s.limit.count.val == 10
    assert len(s.order_by) == 1


def test_aggregates():
    s = parse_one("select count(*), count(distinct a), sum(b*c), avg(d), "
                  "group_concat(e separator ',') from t group by f having count(*) > 1")
    assert s.fields[0].expr.name == "count" and not s.fields[0].expr.args
    assert s.fields[1].expr.distinct
    assert isinstance(s.having, ast.BinaryOp)


def test_case_when():
    s = parse_one("select case when a=1 then 'x' else 'y' end, "
                  "case a when 1 then 2 when 3 then 4 end from t")
    c0 = s.fields[0].expr
    assert isinstance(c0, ast.CaseExpr) and c0.operand is None and c0.else_ is not None
    c1 = s.fields[1].expr
    assert c1.operand is not None and len(c1.whens) == 2


def test_funcs_special():
    parse_one("select extract(year from d), substring(s, 1, 3), substring(s from 2 for 4), "
              "trim(leading 'x' from s), position('a' in s), cast(a as signed), "
              "cast(b as decimal(10,2)), convert(c, char(5)) from t")


def test_date_literals_and_interval():
    s = parse_one("select date '1995-01-01', date_add(d, interval 3 month) from t")
    lit = s.fields[0].expr
    assert lit.kind == "date"
    fc = s.fields[1].expr
    assert isinstance(fc.args[1], ast.IntervalExpr) and fc.args[1].unit == "month"


def test_exists_and_scalar_subquery():
    parse_one("select (select max(a) from t) from u where exists (select 1 from v) "
              "and x > all (select y from w)")


def test_insert():
    s = parse_one("insert into t (a, b) values (1, 'x'), (2, 'y')")
    assert s.columns == ["a", "b"]
    assert len(s.values) == 2
    s2 = parse_one("insert into t select * from u")
    assert s2.select is not None
    s3 = parse_one("replace into t values (1)")
    assert s3.is_replace
    s4 = parse_one("insert into t set a=1, b=2")
    assert s4.columns == ["a", "b"]
    s5 = parse_one("insert into t values (1) on duplicate key update a=a+1")
    assert len(s5.on_duplicate) == 1


def test_update_delete():
    s = parse_one("update t set a=1, b=b+1 where c=2 limit 3")
    assert len(s.assignments) == 2
    assert s.limit.count.val == 3
    d = parse_one("delete from t where a=1")
    assert d.where is not None


def test_create_table():
    s = parse_one("""
        CREATE TABLE IF NOT EXISTS t (
            id BIGINT NOT NULL AUTO_INCREMENT,
            name VARCHAR(64) DEFAULT 'x',
            price DECIMAL(15,2) NOT NULL,
            d DATE,
            ts DATETIME(6),
            PRIMARY KEY (id),
            UNIQUE KEY uk (name),
            KEY idx_price (price, d)
        ) ENGINE=InnoDB CHARSET=utf8mb4
    """)
    assert isinstance(s, ast.CreateTableStmt)
    assert s.if_not_exists
    assert len(s.columns) == 5
    assert s.columns[0].options.get("auto_increment")
    assert s.columns[1].options["default"].val == "x"
    assert len(s.constraints) == 3
    assert s.constraints[0].kind == "primary"
    assert s.constraints[1].kind == "unique"


def test_ddl_misc():
    parse_one("create database if not exists db1")
    parse_one("drop database if exists db1")
    parse_one("drop table if exists a, b")
    parse_one("create unique index i on t (a, b(10))")
    parse_one("drop index i on t")
    parse_one("truncate table t")
    parse_one("rename table a to b")
    a = parse_one("alter table t add column c int not null default 0 after b, drop column d")
    assert a.specs[0][0] == "add_column"
    assert a.specs[1][0] == "drop_column"
    a2 = parse_one("alter table t add index idx (a), add unique key uk (b), modify column c bigint")
    assert [sp[0] for sp in a2.specs] == ["add_index", "add_index", "modify_column"]


def test_simple_stmts():
    parse_one("use test")
    s = parse_one("set @@session.sql_mode='', global max_connections=100, @u=5")
    assert [i[0] for i in s.items] == ["session", "global", "user"]
    parse_one("set names utf8mb4")
    parse_one("show databases")
    parse_one("show tables from db like 't%'")
    parse_one("show create table t")
    parse_one("show variables like 'a%'")
    parse_one("begin")
    parse_one("start transaction")
    parse_one("commit")
    parse_one("rollback")
    parse_one("analyze table t")
    e = parse_one("explain analyze select 1")
    assert e.analyze
    d = parse_one("desc t")
    assert isinstance(d, ast.ShowStmt) and d.kind == "columns"
    parse_one("admin show ddl jobs")
    parse_one("kill 42")


def test_multi_statement():
    stmts = parse("select 1; select 2;")
    assert len(stmts) == 2


def test_parse_errors():
    with pytest.raises(ParseError):
        parse_one("select from where")
    with pytest.raises(ParseError):
        parse_one("create table t")
    with pytest.raises(ParseError):
        parse_one("select * from t limit")


def test_string_escapes():
    s = parse_one(r"select 'a\'b', 'c''d', 'x' 'y'")
    assert s.fields[0].expr.val == "a'b"
    assert s.fields[1].expr.val == "c'd"
    assert s.fields[2].expr.val == "xy"


def test_comments():
    s = parse_one("select 1 -- comment\n + 2 /* inline */ , 3 # end\n from t")
    assert len(s.fields) == 2


def test_normalize_digest():
    n1 = normalize("SELECT * FROM t WHERE a = 10 AND b IN (1, 2, 3)")
    n2 = normalize("select * from t where a = 99 and b in (4,5)")
    assert n1 == n2
    assert digest(n1) == digest(n2)


TPCH_Q1 = """
select
    l_returnflag, l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval 90 day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

TPCH_Q3 = """
select
    l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""

TPCH_Q5 = """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1994-01-01' + interval '1' year
group by n_name
order by revenue desc
"""

TPCH_Q18 = """
select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
        select l_orderkey
        from lineitem
        group by l_orderkey
        having sum(l_quantity) > 300)
  and c_custkey = o_custkey
  and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate
limit 100
"""


@pytest.mark.parametrize("q", [TPCH_Q1, TPCH_Q3, TPCH_Q5, TPCH_Q18],
                         ids=["q1", "q3", "q5", "q18"])
def test_tpch_queries_parse(q):
    s = parse_one(q)
    assert isinstance(s, ast.SelectStmt)
    # restore must itself re-parse to the same restored text (fixpoint)
    r1 = s.restore()
    assert parse_one(r1).restore() == r1
