"""The four confinement lints migrated from their test-file copies
(tests/test_compile_service.py, test_residency.py, test_scheduler.py,
test_supervisor.py) into registry rules sharing the engine's one parse.

Each rule carries its sanctioned-layer file set as rule config (these
are permanent architecture facts, not burn-down debt — the allowlist
file is reserved for entries that are supposed to shrink).
"""

from __future__ import annotations

import ast

from ..engine import Rule, register
from ._util import call_name


@register
class JitConfinement(Rule):
    """Raw ``jax.jit`` (or AOT ``.lower()``/``.compile()`` chained off a
    jit call) outside the compile layer bypasses async compilation, the
    compile breaker and trace accounting: every query pipeline must build
    through device_exec.acquire_pipeline -> compile_service.obtain, and
    every kernel jit through ops/device.observed_jit."""

    name = "jit-confinement"
    allowlistable = False
    title = "raw jax.jit confined to the compile layer"

    #: the sanctioned compile layer (device_exec routes through these;
    #: fabric/compile_client.py is the separated compile server's
    #: export wrapper — the jit there exists to TRACE for the server)
    ALLOWED = ("executor/compile_service.py", "ops/device.py",
               "fabric/compile_client.py")

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            if sf.rel in self.ALLOWED:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                if (node.attr == "jit" and isinstance(node.value, ast.Name)
                        and node.value.id == "jax"):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"jax.jit@{sf.qualname(node)}",
                        "raw jax.jit outside the compile layer (use "
                        "acquire_pipeline / observed_jit)"))
                # AOT chain: jax.jit(...).lower(...) / .compile()
                if (node.attr in ("lower", "compile")
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "jit"):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"jit-aot-{node.attr}@{sf.qualname(node)}",
                        f"AOT .{node.attr}() chained off a raw jit outside "
                        "the compile layer"))
        return out


@register
class DeviceSlotConfinement(Rule):
    """Any direct read/write of ``._device`` outside ops/residency.py is
    unaccounted HBM caching — the ledger (budget, epoch, OOM eviction)
    only works if every cached upload goes through the manager.  The
    ``self._device = None`` slot inits in the Column constructors
    (NONE_INIT_ALLOWED) are the one sanctioned exception."""

    name = "device-slot-confinement"
    allowlistable = False
    title = "._device access confined to the residency manager"

    #: the residency manager owns the slot; the Column constructors may
    #: initialize it to None (a fresh column has no cache to account)
    ALLOWED = ("ops/residency.py",)
    NONE_INIT_ALLOWED = ("utils/chunk.py",)

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            if sf.rel in self.ALLOWED:
                continue
            none_inits = set()
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is None):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and tgt.attr == "_device"):
                            none_inits.add(id(tgt))
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Attribute)
                        and node.attr == "_device"):
                    continue
                if id(node) in none_inits:
                    if sf.rel in self.NONE_INIT_ALLOWED:
                        continue
                    ident = f"_device=None@{sf.qualname(node)}"
                    msg = ("._device = None slot init outside "
                           "ops/residency.py")
                else:
                    ident = f"_device@{sf.qualname(node)}"
                    msg = ("._device accessed outside ops/residency.py "
                           "(unaccounted HBM caching)")
                out.append(self.finding(sf.rel, node.lineno, ident, msg))
        return out


@register
class SupervisedConfinement(Rule):
    """Every device dispatch must pass the admission queue: direct
    ``call_supervised`` / ``supervised_call`` use is confined to
    run_device (which admits first), the scheduler, and the compile
    service's bounded worker pool — a new dispatch path must not silently
    bypass per-tenant scheduling."""

    name = "supervised-confinement"
    allowlistable = False
    title = "supervised dispatch confined to the admission layer"

    #: the admission layer: run_device admits before dispatching, the
    #: scheduler/supervisor are the mechanism itself, mpp.py's embedder
    #: hook admits per dist_* step, and the compile service's bounded
    #: worker pool is the bg builds' own admission
    ALLOWED = ("executor/supervisor.py", "executor/device_exec.py",
               "executor/scheduler.py", "parallel/mpp.py",
               "executor/compile_service.py")

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            if sf.rel in self.ALLOWED:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node).rsplit(".", 1)[-1]
                if name in ("call_supervised", "supervised_call"):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"{name}@{sf.qualname(node)}",
                        "direct supervised dispatch bypasses the admission "
                        "queue (route through device_exec.run_device)"))
        return out


@register
class SharedMemoryConfinement(Rule):
    """Direct ``multiprocessing.shared_memory`` use outside
    ``tidb_tpu/fabric/`` bypasses the fleet coordination layer: the
    segment's struct layout, the flock critical sections, the lease
    reclaim and the drain invariant only hold if every cross-process
    byte goes through fabric/coord.py.  Any other layer coordinates via
    the typed hooks fabric/state.py installs (scheduler.set_fleet,
    residency.set_fleet, dedup_handle) — the same pattern as the
    ``._device`` confinement to the residency manager."""

    name = "shared-memory-confinement"
    allowlistable = False
    title = "multiprocessing.shared_memory confined to tidb_tpu/fabric/"

    ALLOWED_PREFIX = "fabric/"

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            if sf.rel.startswith(self.ALLOWED_PREFIX):
                continue
            for node in ast.walk(sf.tree):
                hit = None
                if isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    if mod.endswith("shared_memory") or (
                            mod == "multiprocessing" and any(
                            a.name == "shared_memory"
                            for a in node.names)):
                        hit = "import"
                elif isinstance(node, ast.Import):
                    if any(a.name.endswith(".shared_memory")
                           for a in node.names):
                        hit = "import"
                elif (isinstance(node, ast.Attribute)
                        and node.attr == "shared_memory"):
                    hit = "attr"
                elif (isinstance(node, ast.Call)
                        and call_name(node).rsplit(".", 1)[-1]
                        == "SharedMemory"):
                    hit = "ctor"
                if hit is not None:
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"shm-{hit}@{sf.qualname(node)}",
                        "multiprocessing.shared_memory used outside "
                        "tidb_tpu/fabric/ (coordinate through the "
                        "fabric/state.py hooks)"))
        return out


@register
class SocketConfinement(Rule):
    """Raw ``socket``/``socketserver`` use for COORDINATION is confined
    to ``tidb_tpu/fabric/``: the length-prefixed frame codec
    (fabric/codec.py), its torn-frame discipline, the down-window retry
    budgets and the drain invariants only hold if every coordination
    byte rides the fabric's transports (coord_net, compile server,
    fleet port reservation, the bench wire client).  The ONE other
    sanctioned socket owner is ``server/`` — the MySQL wire protocol IS
    a socket listener; that is its job, not coordination.  A new layer
    that wants cross-process bytes goes through fabric/state.py hooks
    or a fabric service, never its own ad-hoc socket."""

    name = "socket-confinement"
    allowlistable = False
    title = "raw socket use confined to fabric/ (and the MySQL wire in server/)"

    ALLOWED_PREFIXES = ("fabric/", "server/")

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            if sf.rel.startswith(self.ALLOWED_PREFIXES):
                continue
            for node in ast.walk(sf.tree):
                hit = None
                if isinstance(node, ast.Import):
                    if any(a.name in ("socket", "socketserver")
                           for a in node.names):
                        hit = "import"
                elif isinstance(node, ast.ImportFrom):
                    if (node.module or "") in ("socket", "socketserver"):
                        hit = "import"
                elif (isinstance(node, ast.Call)
                        and call_name(node) in (
                            "socket.socket", "socket.create_connection")):
                    hit = "ctor"
                if hit is not None:
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"socket-{hit}@{sf.qualname(node)}",
                        "raw socket use outside fabric/ and server/ "
                        "(coordination goes through a fabric transport)"))
        return out


@register
class SnapshotConfinement(Rule):
    """Every read view over the durable fleet store must acquire its
    timestamp through the frontier-waiting entry points —
    kv/store.Storage.begin / get_snapshot routing ``_fresh_read_ts()``
    — so the ts is fenced above every live peer's durable commit
    frontier and the replica has applied through it.  A ``Snapshot``
    constructed anywhere else inside kv/ would mint a read view that
    skips that wait: exactly the silent stale read the consistency
    contract forbids.  Layers above kv/ may build snapshots only from
    an already-acquired ts (AS OF / stale-read paths own their
    staleness explicitly)."""

    name = "snapshot-confinement"
    allowlistable = False
    title = "Snapshot construction confined to the frontier-waiting entry point"

    #: Storage.begin/get_snapshot (and Transaction, same file) are the
    #: sanctioned constructors — both route _fresh_read_ts
    ALLOWED = ("kv/store.py",)

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            if not sf.rel.startswith("kv/") or sf.rel in self.ALLOWED:
                continue
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.Call)
                        and call_name(node).rsplit(".", 1)[-1]
                        == "Snapshot"):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"Snapshot@{sf.qualname(node)}",
                        "Snapshot constructed outside kv/store.py "
                        "(bypasses the fleet-frontier freshness wait)"))
        return out


@register
class RunDeviceShape(Rule):
    """A run_device call without ``shape=`` silently shares the 'agg'
    breaker — a new fragment class must never piggyback unnoticed.
    Direct calls AND the ``_with_pipe_stats(run_device, ...)``
    indirection both count."""

    name = "run-device-shape"
    allowlistable = False
    title = "run_device call sites name their breaker shape"

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node).rsplit(".", 1)[-1]
                direct = name == "run_device"
                indirect = (name == "_with_pipe_stats" and node.args
                            and isinstance(node.args[0], ast.Name)
                            and node.args[0].id == "run_device")
                if not (direct or indirect):
                    continue
                if not any(kw.arg == "shape" for kw in node.keywords):
                    out.append(self.finding(
                        sf.rel, node.lineno,
                        f"{name}@{sf.qualname(node)}",
                        "run_device call site missing explicit shape= "
                        "(breaker scoping)"))
        return out
