"""Shared AST helpers for the rule modules."""

from __future__ import annotations

import ast


def dotted(node) -> str:
    """Best-effort dotted-name rendering of an expression: ``jax.jit``,
    ``self._lock``, ``failpoint.inject`` — "" when the expression is not
    a plain name/attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


def const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def string_literals(tree) -> set:
    """Every string constant in the tree (docstrings included — they name
    gauges/keys often enough that excluding them only creates noise)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, ast.JoinedStr):
            # f-string: keep the literal fragments (the static prefix of
            # "sched_degradations:{g}" is what surfacing checks match on)
            for v in node.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(v.value)
    return out


def import_map(tree, current_rel: str, package: str = "tidb_tpu") -> dict:
    """local-name -> package-relative module path ("executor/scheduler")
    for every intra-package import in the module.  Names imported FROM a
    module map to "module::name".  Used for best-effort cross-module call
    resolution; anything outside the package maps to nothing."""
    # current module's package path, "/"-separated, no trailing file
    cur_parts = current_rel.rsplit(".py", 1)[0].split("/")
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == package or a.name.startswith(package + "."):
                    mod = "/".join(a.name.split(".")[1:])
                    out[(a.asname or a.name.split(".")[-1])] = mod
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                if not (node.module or "").startswith(package):
                    continue
                base = (node.module or package).split(".")[1:]
            else:
                # relative: level 1 = current package dir, 2 = parent, ...
                base = cur_parts[:-(node.level)] if node.level <= \
                    len(cur_parts) else []
                if node.module:
                    base = base + node.module.split(".")
            mod = "/".join(base)
            for a in node.names:
                local = a.asname or a.name
                # could be a submodule (from ..executor import scheduler)
                # or a symbol (from .engine import run) — record both
                # interpretations; resolution tries module-first
                out[local] = f"{mod}/{a.name}" if mod else a.name
                out[local + "::sym"] = f"{mod}::{a.name}" if mod \
                    else f"::{a.name}"
    return out
