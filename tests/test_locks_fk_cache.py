"""LOCK/UNLOCK TABLES, FK metadata, cached tables (reference:
ddl/table_lock.go, ddl/foreign_key.go, table/cache.go)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (a int)")
    tk.must_exec("insert into t values (1)")
    return tk


class TestTableLocks:
    def test_write_lock_excludes_other_sessions(self, tk):
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk.must_exec("lock tables t write")
        assert tk2.exec_error("select * from t").code == 8020
        assert tk2.exec_error("insert into t values (2)").code == 8020
        tk.must_query("select a from t").check([("1",)])  # owner reads
        tk.must_exec("insert into t values (2)")          # owner writes
        tk.must_exec("unlock tables")
        tk2.must_query("select count(*) from t").check([("2",)])

    def test_read_lock_allows_foreign_reads_blocks_writes(self, tk):
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk.must_exec("lock tables t read")
        tk2.must_query("select a from t").check([("1",)])
        assert tk2.exec_error("update t set a = 9").code == 8020
        # the lock owner cannot write through its own READ lock
        assert tk.exec_error("insert into t values (3)").code == 1099
        tk.must_exec("unlock tables")

    def test_locked_session_cannot_touch_unlocked_tables(self, tk):
        tk.must_exec("create table other (b int)")
        tk.must_exec("lock tables t read")
        assert tk.exec_error("select * from other").code == 1100
        tk.must_exec("unlock tables")
        tk.must_query("select count(*) from other").check([("0",)])

    def test_session_close_releases_locks(self, tk):
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("lock tables t write")
        assert tk.exec_error("select * from t").code == 8020
        tk2.session.close()
        tk.must_query("select a from t").check([("1",)])

    def test_insert_select_reads_read_locked_source(self, tk):
        """Regression: source tables of INSERT...SELECT are reads, not
        writes — a READ lock must not block them."""
        tk.must_exec("create table src (a int)")
        tk.must_exec("insert into src values (7)")
        tk.must_exec("lock tables t write, src read")
        tk.must_exec("insert into t select a from src")
        tk.must_exec("unlock tables")
        tk.must_query("select count(*) from t").check([("2",)])
        # foreign READ lock on the source also permits the copy
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("lock tables src read")
        tk.must_exec("insert into t select a from src")
        tk2.must_exec("unlock tables")

    def test_ddl_blocked_by_foreign_write_lock(self, tk):
        """Regression: DROP/ALTER/CREATE INDEX respect LOCK TABLES."""
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("lock tables t write")
        assert tk.exec_error("drop table t").code == 8020
        assert tk.exec_error("alter table t add column z int").code == 8020
        assert tk.exec_error("create index ia on t (a)").code == 8020
        tk2.must_exec("unlock tables")
        tk.must_exec("alter table t add column z int")

    def test_conflicting_lock_acquisition_rejected(self, tk):
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk.must_exec("lock tables t read")
        # a second READ lock coexists; WRITE does not
        tk2.must_exec("lock tables t read")
        tk2.must_exec("unlock tables")
        assert tk2.exec_error("lock tables t write").code == 8020
        tk.must_exec("unlock tables")


class TestForeignKeyMetadata:
    def test_fk_stored_and_rendered(self, tk):
        tk.must_exec("create table parent (id int primary key)")
        tk.must_exec(
            "create table child (id int primary key, pid int, "
            "constraint fk_p foreign key (pid) references parent (id) "
            "on delete cascade on update set null)")
        info = tk.session.infoschema().table_by_name("test", "child")
        assert info.foreign_keys == [{
            "name": "fk_p", "cols": ["pid"], "ref_table": "parent",
            "ref_cols": ["id"], "on_delete": "cascade",
            "on_update": "set null"}]
        ddl = tk.must_query("show create table child").rows[0][1]
        assert "FOREIGN KEY (`pid`) REFERENCES `parent` (`id`)" in ddl
        assert "ON DELETE CASCADE" in ddl and "ON UPDATE SET NULL" in ddl

    def test_fk_not_enforced_like_reference(self, tk):
        """v5.x reference parity: FKs are metadata, not checks."""
        tk.must_exec("create table p2 (id int primary key)")
        tk.must_exec("create table c2 (pid int, "
                     "foreign key (pid) references p2 (id))")
        tk.must_exec("insert into c2 values (999)")  # no parent: accepted
        tk.must_query("select count(*) from c2").check([("1",)])


class TestCachedTables:
    def test_cache_flag_and_ddl_guard(self, tk):
        tk.must_exec("alter table t cache")
        info = tk.session.infoschema().table_by_name("test", "t")
        assert info.cached
        assert tk.exec_error("alter table t add column c int").code == 8242
        tk.must_exec("alter table t nocache")
        tk.must_exec("alter table t add column c int")
        # reads/writes work in both states
        tk.must_exec("alter table t cache")
        tk.must_exec("insert into t values (5, 6)")
        tk.must_query("select count(*) from t").check([("2",)])
