"""Case-insensitive collation (utf8mb4_general_ci) — comparisons, GROUP BY,
DISTINCT, ORDER BY, joins, LIKE (reference: util/collate/collate.go)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec(
        "create table ci (id int primary key, "
        "s varchar(20) collate utf8mb4_general_ci, b varchar(20))")
    tk.must_exec(
        "insert into ci values (1,'Apple','Apple'), (2,'APPLE','APPLE'), "
        "(3,'banana','banana'), (4,'Banana','Banana'), (5,'cherry','cherry')")
    return tk


def test_ci_equality(tk):
    tk.must_query("select id from ci where s = 'apple' order by id").check(
        [("1",), ("2",)])
    # the binary column stays exact
    tk.must_query("select id from ci where b = 'apple'").check([])


def test_ci_group_by_merges_case_variants(tk):
    r = tk.must_query("select count(*) from ci group by s order by 1")
    assert [row[0] for row in r.rows] == ["1", "2", "2"]
    # binary column keeps them apart
    r = tk.must_query("select count(*) from ci group by b order by 1")
    assert [row[0] for row in r.rows] == ["1"] * 5


def test_ci_distinct(tk):
    r = tk.must_query("select distinct s from ci")
    assert len(r.rows) == 3


def test_ci_order_by(tk):
    r = tk.must_query("select id from ci order by s, id")
    # case-insensitive: Apple/APPLE < banana/Banana < cherry
    assert [row[0] for row in r.rows] == ["1", "2", "3", "4", "5"]


def test_ci_join_keys(tk):
    tk.must_exec("create table ref (s varchar(20) collate utf8mb4_general_ci,"
                 " v int)")
    tk.must_exec("insert into ref values ('APPLE', 100), ('BANANA', 200)")
    r = tk.must_query(
        "select ci.id, ref.v from ci, ref where ci.s = ref.s order by ci.id")
    assert [tuple(x) for x in r.rows] == [
        ("1", "100"), ("2", "100"), ("3", "200"), ("4", "200")]


def test_ci_like(tk):
    tk.must_query("select id from ci where s like 'app%' order by id").check(
        [("1",), ("2",)])
    tk.must_query("select id from ci where b like 'app%'").check([])


def test_ci_comparison_operators(tk):
    tk.must_query(
        "select count(*) from ci where s < 'BANANA'").check([("2",)])


def test_ci_show_and_binary_defaults(tk):
    # unspecified collation stays binary-compatible default
    r = tk.must_query("select count(distinct b) from ci")
    assert r.rows[0][0] == "5"


def test_ci_device_fallback_parity(tk):
    """Force the device engine: _ci columns must fall back to host and
    still produce case-insensitive results."""
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    r = tk.must_query("select count(*) from ci group by s order by 1")
    assert [row[0] for row in r.rows] == ["1", "2", "2"]
    tk.must_exec("set tidb_executor_engine = 'auto'")


class TestWeightTables:
    """Real collator semantics (reference: util/collate/unicode_ci_data.go;
    MySQL docs' documented cases: general_ci ß=s, unicode_ci ß=ss, Ä=A
    for both)."""

    @pytest.fixture()
    def wtk(self):
        tk = TestKit()
        tk.must_exec(
            "create table w (id int primary key, "
            "g varchar(20) collate utf8mb4_general_ci, "
            "u varchar(20) collate utf8mb4_unicode_ci)")
        tk.must_exec(
            "insert into w values (1,'straße','straße'), "
            "(2,'STRASSE','STRASSE'), (3,'Åpple','Åpple'), "
            "(4,'apple','apple'), (5,'résumé','résumé')")
        return tk

    def test_general_ci_sharp_s_equals_s_not_ss(self, wtk):
        # general_ci: ß weighs as S (no expansion) → straße = strase
        wtk.must_query("select id from w where g = 'strase'").check([("1",)])
        wtk.must_query("select id from w where g = 'STRASSE'").check([("2",)])

    def test_unicode_ci_sharp_s_expands_to_ss(self, wtk):
        # unicode_ci: ß = ss → straße = strasse = STRASSE
        wtk.must_query("select id from w where u = 'strasse' order by id"
                       ).check([("1",), ("2",)])
        wtk.must_query("select id from w where u = 'strase'").check([])

    def test_accent_fold_A_ring(self, wtk):
        # Å = A in both collations
        wtk.must_query("select id from w where g = 'APPLE' order by id"
                       ).check([("3",), ("4",)])
        wtk.must_query("select id from w where u = 'APPLE' order by id"
                       ).check([("3",), ("4",)])

    def test_accent_fold_e_acute(self, wtk):
        wtk.must_query("select id from w where g = 'RESUME'").check([("5",)])
        wtk.must_query("select id from w where u = 'RESUME'").check([("5",)])

    def test_group_by_merges_weight_equal(self, wtk):
        r = wtk.must_query("select count(*) from w group by u order by 1 desc")
        assert [row[0] for row in r.rows] == ["2", "2", "1"]


class TestDeviceCI:
    """_ci columns on the device engine: collation-class dictionary codes
    (ops/device.py to_device_col via dict_encode_ci) — the round-2 host
    fallback removed per VERDICT item 7."""

    @pytest.fixture()
    def dtk(self):
        tk = TestKit()
        tk.must_exec(
            "create table dc (s varchar(20) collate utf8mb4_general_ci, "
            "v int)")
        tk.must_exec(
            "insert into dc values ('Apple',1),('APPLE',2),('banana',3),"
            "('Banana',4),('cherry',5),('straße',6),('STRASE',7)")
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        return tk

    def test_ci_group_by_on_device(self, dtk):
        txt = "\n".join(
            " ".join(map(str, r)) for r in dtk.must_query(
                "explain analyze select s, count(*), sum(v) from dc "
                "group by s order by s").rows)
        assert "engine:tpu" in txt  # the fragment really ran on-device
        r = dtk.must_query(
            "select count(*), sum(v) from dc group by s order by 1, 2")
        assert [tuple(x) for x in r.rows] == [
            ("1", "5"), ("2", "3"), ("2", "7"), ("2", "13")]

    def test_ci_eq_filter_on_device(self, dtk):
        r = dtk.must_query(
            "select sum(v) from dc where s = 'apple'")
        assert r.rows == [("3",)]
        r = dtk.must_query("select sum(v) from dc where s = 'STRASE'")
        assert r.rows == [("13",)]  # straße(6) + STRASE(7) under general_ci

    def test_ci_range_compare_on_device(self, dtk):
        # class codes are ordered by sort key → ordering comparisons valid
        r = dtk.must_query("select count(*) from dc where s < 'BANANA'")
        assert r.rows == [("2",)]

    def test_ci_like_on_device(self, dtk):
        r = dtk.must_query("select count(*) from dc where s like 'app%'")
        assert r.rows == [("2",)]

    def test_ci_in_on_device(self, dtk):
        r = dtk.must_query(
            "select count(*) from dc where s in ('APPLE', 'Cherry')")
        assert r.rows == [("3",)]

    def test_device_host_parity(self, dtk):
        q = ("select s, count(*) c, min(v), max(v) from dc "
             "group by s order by s, c")
        dev_rows = dtk.must_query(q).rows
        dtk.must_exec("set tidb_executor_engine = 'host'")
        host_rows = dtk.must_query(q).rows
        # group keys may differ by class representative; compare ci-folded
        from tidb_tpu.utils.collate import sort_key
        fold = lambda rows: [(sort_key(r[0].encode(),
                                       "utf8mb4_general_ci"),) + tuple(r[1:])
                             for r in rows]
        assert fold(dev_rows) == fold(host_rows)


def test_mixed_ci_collation_join_keys():
    """Both join sides must fold under ONE canonical collation (review
    regression: general_ci ⋈ unicode_ci on 'straße' returned 0 rows)."""
    tk = TestKit()
    tk.must_exec("create table ja (g varchar(20) collate utf8mb4_general_ci)")
    tk.must_exec("create table jb (u varchar(20) collate utf8mb4_unicode_ci)")
    tk.must_exec("insert into ja values ('straße'), ('Apple')")
    tk.must_exec("insert into jb values ('straße'), ('APPLE')")
    tk.must_query(
        "select count(*) from ja join jb on ja.g = jb.u").check([("2",)])
