"""Fleet-wide fragment dedup: identical concurrent fragments anywhere in
the fleet dispatch ONE device call.

PR 6's batch-key coalescing already merges identical queued fragments
onto one scheduling grant INSIDE a process; this module extends the idea
across the process boundary, and further: followers do not even
dispatch.  The claim table lives in the coordination segment
(fabric/coord.py); the winning process (the LEADER) runs the dispatch
and publishes the assembled result chunk to a per-fragment page file,
which followers map back in (``mmap`` read) instead of admitting,
uploading and dispatching their own device call.

Soundness — the dedup key is ``blake2b(batch key ‖ data signature)``:

* the BATCH KEY (device_exec.agg_batch_key) pins the fragment's
  structural identity — plan/cond expression signatures and the padded
  row bucket — exactly the compiled-pipeline identity prefix;
* the DATA SIGNATURE hashes the input chunk's column contents (dtype,
  shape, value bytes, null mask, dictionary codes).  Worker Domains are
  independent stores, so structural identity alone is NOT result
  identity; hashing the content makes it so — two workers seeded with
  the same data dedup, a worker that took an INSERT diverges to a new
  key on its next dispatch and can never be served a stale page.

The claim happens BEFORE admission (device_exec.run_device), so a
follower consumes no device slot while it waits.  Every wait is bounded
and KILL-polled; a leader that dies mid-build is detected by its lease
(coord.BUILD_LEASE_S) and the waiter falls back to a local dispatch —
dedup can delay a fragment by at most the wait bound, never wedge it.

Results ship as pickled Chunks with process-local caches stripped
(utils/chunk.py ``__getstate__`` drops the HBM ``_device`` slot and
host-side index caches), so a page can never smuggle another process's
device handles.

On top of the in-flight coalescer sits the **version-stamped result
cache** (``claim_versioned`` / ``publish_versioned``, driven by
executor/agg_cache.py): pages whose claim carries a non-zero ``vv_hash``
are stamped with the (table → fleet version) vector they were computed
under and keep serving for as long as every referenced table's CURRENT
fleet version still matches — across statements, sessions and workers,
with the TTL demoted to a backstop (coord.VERSIONED_EVICT_S).  A version
advance (any committed write to a referenced table, tailed fleet-wide by
kv/shared_store) invalidates the entry on its next claim; the holder of
the invalidated claim receives the SUPERSEDED page back and may fold
just the WAL delta through the cached aggregate partials instead of
recomputing.  Every versioned hit re-verifies the vector INSIDE the
page against the one the claim matched — a stale page (hash collision,
or the ``cache-stale-read`` failpoint) is a loud ``cache_stale_reads``
error and a local recompute, never a wrong answer.
"""

from __future__ import annotations

import hashlib
import logging
import mmap
import os
import pickle
import time

import numpy as np

log = logging.getLogger("tidb_tpu.fabric.dedup")

#: a DONE result page serves followers for this long — the
#: "concurrent identical fragments" window.  Content-hashed keys make a
#: reuse inside the window SOUND for any length, but the window is kept
#: short deliberately: this is in-flight coalescing (one device call for
#: fragments racing each other).  The RESULT CACHE with a real
#: invalidation story is the version-stamped tier (claim_versioned):
#: its pages ignore this TTL and live on version-vector match, with
#: coord.VERSIONED_EVICT_S as the backstop.  Override with
#: TIDB_TPU_FABRIC_DEDUP_TTL (seconds).
TTL_S = float(os.environ.get("TIDB_TPU_FABRIC_DEDUP_TTL", "0.2") or 0.2)
#: bound on a follower's wait for a building leader
WAIT_S = 5.0
#: poll period while waiting (KILL answers within ~a tick)
POLL_S = 0.01
#: fragments with more input bytes than this skip dedup (hashing cost
#: would rival the dispatch; big fragments rarely collide anyway)
MAX_ARG_BYTES = 64 << 20
#: result pages larger than this are not published (the follower's win
#: would not cover serializing + writing a giant result set)
MAX_PAGE_BYTES = 16 << 20


class Dedup:
    """The per-process dedup handle (fabric/state.py holds one)."""

    def __init__(self, coordinator, slot: int):
        self._c = coordinator
        self._slot = slot

    # -- keying ---------------------------------------------------------------

    def key_hash(self, batch_key, args) -> "bytes | None":
        """16-byte dedup key, or None when the dispatch carries no
        hashable input chunk (no data identity -> no dedup) or the
        inputs exceed MAX_ARG_BYTES.  The size gate runs on CHEAP
        estimates BEFORE any hashing: a paged chunk's columns are
        memmap-backed, and touching their bytes first would materialize
        the very data the paging layer exists to keep on disk."""
        from ..utils.chunk import Chunk
        chunks = [a for a in args if isinstance(a, Chunk)]
        if not chunks:
            return None
        if sum(_col_est_bytes(col) for a in chunks
               for col in a.columns) > MAX_ARG_BYTES:
            return None
        h = hashlib.blake2b(repr(batch_key).encode(), digest_size=16)
        # region-sharded stores fold the owned-region epoch vector into
        # the key: a page computed before a region failover can never
        # serve after it (defense-in-depth — the data signature below
        # already changes with the data, but an epoch bump is the
        # cheaper, earlier invalidation signal)
        from . import state
        rs = state.region_store()
        if rs is not None:
            h.update(repr(sorted(rs.epochs.items())).encode())
        for a in args:
            if isinstance(a, Chunk):
                for col in a.columns:
                    _hash_column(h, col)
            elif isinstance(a, (int, float, np.generic)):
                h.update(repr(a).encode())
        return h.digest()

    # -- the coalesce wrapper -------------------------------------------------

    def coalesce(self, ctx, shape: str, key_hash: bytes, compute):
        """Run `compute` as the fleet leader for this fragment, or serve
        the result another process computed.  `compute` is the full
        admitted dispatch (admission + supervisor + breaker + residency);
        followers never call it."""
        from ..session import tracing
        from . import state
        kind, idx, rid = self._c.dedup_claim(key_hash, TTL_S)
        if kind == "hit":
            res = self._load(rid)
            if res is not None:
                state.bump("fabric_dedup_hits")
                tracing.event("fabric.dedup", role="hit", slot=self._slot)
                return res
            kind = "miss"  # page vanished (TTL race): dispatch locally
        if kind == "wait":
            state.bump("fabric_dedup_waits")
            res = self._wait(ctx, idx, key_hash)
            if res is not None:
                state.bump("fabric_dedup_hits")
                tracing.event("fabric.dedup", role="wait_hit",
                              slot=self._slot)
                return res
            state.bump("fabric_dedup_timeouts")
            self._c.bump("fabric_dedup_timeouts")
            return compute()
        if kind != "lead":
            return compute()
        state.bump("fabric_dedup_leads")
        tracing.event("fabric.dedup", role="lead", slot=self._slot)
        try:
            res = compute()
        except BaseException:
            # degrade/KILL/fault: free the slot so waiters fall back fast
            self._c.dedup_fail(idx, key_hash)
            raise
        self._publish(idx, key_hash, res)
        return res

    # -- the version-stamped result cache ------------------------------------

    def claim_versioned(self, ctx, key_hash: bytes, vv_hash: int,
                        vv: dict):
        """Probe/claim the versioned cache for a fragment computed under
        version vector ``vv`` (whose 64-bit digest is ``vv_hash``).

        Returns one of::

            ("hit", payload)        page dict, vector verified in-page
            ("lead", idx)           caller computes, then
                                    publish_versioned(...) or fail(...)
            ("lead_delta", idx, old_payload)
                                    entry invalidated by a version
                                    advance; the superseded page is
                                    handed back for a delta fold (the
                                    caller still publishes or fails)
            ("none", None)          serve/claim nothing — run uncached

        A ``cache-stale-read`` failpoint skips the claim-time vector
        check; the in-page verify below then catches the mismatch
        loudly (cache_stale_reads) and degrades to a local compute."""
        from ..session import tracing
        from ..utils import failpoint
        from . import state
        check_vv = not failpoint.inject("cache-stale-read")
        try:
            kind, idx, rid = self._c.dedup_claim(
                key_hash, TTL_S, vv_hash=vv_hash, check_vv=check_vv)
        except Exception as e:  # noqa: BLE001 — coordinator down/unlinked:
            #   the cache degrades to "no cache", never to a failed query
            log.debug("versioned claim unavailable: %s", e)
            return ("none", None)
        if kind == "hit":
            payload = self._load(rid)
            payload = self._verify_payload(payload, vv)
            if payload is not None:
                state.bump("fabric_dedup_hits")
                state.bump("cache_hits")
                tracing.event("fabric.cache", role="hit",
                              slot=self._slot,
                              **_leader_tag(payload))
                return ("hit", payload)
            return ("none", None)
        if kind == "wait":
            state.bump("fabric_dedup_waits")
            payload = self._verify_payload(
                self._wait(ctx, idx, key_hash), vv)
            if payload is not None:
                state.bump("fabric_dedup_hits")
                state.bump("cache_hits")
                tracing.event("fabric.cache", role="wait_hit",
                              slot=self._slot,
                              **_leader_tag(payload))
                return ("hit", payload)
            state.bump("fabric_dedup_timeouts")
            return ("none", None)
        if kind == "lead_delta":
            state.bump("cache_invalidations")
            tracing.event("fabric.cache", role="invalidated",
                          slot=self._slot)
            old = self._load(rid)
            if not isinstance(old, dict):
                old = None
            return ("lead_delta", idx, old)
        if kind == "lead":
            state.bump("fabric_dedup_leads")
            return ("lead", idx)
        return ("none", None)

    def _verify_payload(self, payload, vv: dict):
        """The in-page vector must equal the one the claim matched —
        the last line of defense against a stale serve (claim-level
        hash collision, or the cache-stale-read failpoint)."""
        from . import state
        if not isinstance(payload, dict):
            return None
        if payload.get("vv") != vv:
            log.error(
                "STALE CACHE PAGE refused: page vector %s != current %s "
                "(recomputing locally)", payload.get("vv"), vv)
            state.bump("cache_stale_reads")
            try:
                self._c.bump("fabric_cache_stale_reads")
            except Exception as e:  # noqa: BLE001 — counter only
                log.debug("stale-read counter bump failed: %s", e)
            return None
        return payload

    def publish_versioned(self, idx: int, key_hash: bytes,
                          payload: dict, vv_hash: int) -> bool:
        """Publish a version-stamped page ``{"chunk":, "vv":,
        "partial":}`` under an owned claim.  False → the slot was freed
        (waiters compute locally) and nothing was cached.

        The page is stamped with the publishing statement's trace
        context (when one is active): a follower's hit on another
        worker's page names the LEADER's fleet-global trace id in its
        own timeline — the publisher→follower half of cross-process
        stitching (there is no RPC response to piggyback on here; the
        page itself is the message)."""
        from ..session import tracing
        ctx = tracing.wire_ctx()
        if ctx is not None:
            payload = {**payload, "trace": ctx}
        try:
            blob = pickle.dumps(payload, protocol=4)
        except Exception as e:  # noqa: BLE001 — unshippable payload
            log.warning("versioned page not serializable: %s", e)
            self.fail(idx, key_hash)
            return False
        if len(blob) > MAX_PAGE_BYTES:
            self.fail(idx, key_hash)
            return False
        rid = self._c.next_result_id()
        path = self._c.result_page_path(rid)
        try:
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            self.fail(idx, key_hash)
            return False
        self._c.dedup_publish(idx, key_hash, rid, vv_hash=vv_hash)
        return True

    def fail(self, idx: int, key_hash: bytes):
        """Free an owned claim (compute failed / result not cacheable)
        so waiters fall back to local dispatch."""
        try:
            self._c.dedup_fail(idx, key_hash)
        except Exception as e:  # noqa: BLE001 — lease reclaim covers it
            log.debug("dedup_fail failed (lease will reclaim): %s", e)

    # -- pages ----------------------------------------------------------------

    def _publish(self, idx: int, key_hash: bytes, res):
        from ..utils.chunk import Chunk
        if not isinstance(res, Chunk):
            # only assembled result chunks ship; anything else frees the
            # slot so waiters compute locally
            self._c.dedup_fail(idx, key_hash)
            return
        try:
            blob = pickle.dumps(res, protocol=4)
        except Exception as e:  # noqa: BLE001 — unshippable result shape
            log.warning("dedup result not serializable (slot freed, "
                        "waiters compute locally): %s", e)
            self._c.dedup_fail(idx, key_hash)
            return
        if len(blob) > MAX_PAGE_BYTES:
            self._c.dedup_fail(idx, key_hash)
            return
        rid = self._c.next_result_id()
        path = self._c.result_page_path(rid)
        try:
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            self._c.dedup_fail(idx, key_hash)
            return
        self._c.dedup_publish(idx, key_hash, rid)

    def _load(self, result_id: int):
        """Map a result page back in (mmap read; the page is written
        atomically via rename, so a mapped page is always complete)."""
        path = self._c.result_page_path(result_id)
        try:
            with open(path, "rb") as f:
                with mmap.mmap(f.fileno(), 0,
                               access=mmap.ACCESS_READ) as mm:
                    return pickle.loads(mm)
        except (OSError, ValueError, pickle.UnpicklingError, EOFError):
            return None

    def _wait(self, ctx, idx: int, key_hash: bytes):
        from ..session import tracing
        check = getattr(ctx, "check_killed", None)
        deadline = time.monotonic() + WAIT_S
        while time.monotonic() < deadline:
            st, rid = self._c.dedup_poll(idx, key_hash)
            if st == "done":
                return self._load(rid)
            if st == "gone":
                # the leader died mid-build (lease reclaim freed the
                # slot): the hop lands in the trace as a PEER-LOST
                # marker, never a hang or a silently dropped wait
                tracing.event("fabric.dedup", status="peer-lost",
                              slot=self._slot)
                return None
            if check is not None:
                check()
            time.sleep(POLL_S)
        return None


def _leader_tag(payload) -> dict:
    """Event tags naming the worker/trace that PUBLISHED a served page
    (empty when the leader ran unsampled)."""
    t = payload.get("trace") if isinstance(payload, dict) else None
    if not isinstance(t, dict) or not t.get("gid"):
        return {}
    out = {"leader_gid": t["gid"]}
    if t.get("proc"):
        out["leader"] = t["proc"]
    return out


def _col_est_bytes(col) -> int:
    """Cheap size estimate WITHOUT touching the column's bytes: len()
    and .nbytes read metadata only, so a memmap-backed paged column
    costs nothing to size (materializing it is exactly what the
    MAX_ARG_BYTES gate exists to avoid)."""
    try:
        if getattr(col, "is_object", lambda: False)():
            return len(col) * 64  # codes + dictionary ballpark
        return int(col.data.nbytes)
    except Exception as e:  # noqa: BLE001 — unsizable must mean skip
        log.debug("column unsizable for dedup gate (skipping): %s", e)
        return MAX_ARG_BYTES + 1


def _hash_column(h, col) -> int:
    """Feed one column's identity into the running hash; returns the
    approximate byte count consumed (diagnostics only — the size gate
    already ran on estimates in key_hash)."""
    # branch on the column's LAYOUT, never on a lazily-populated cache:
    # two processes holding identical data must hash identically even
    # when only one of them has warmed its dict_encode cache
    dict_pair = None
    if getattr(col, "is_object", lambda: False)():
        try:
            dict_pair = col.dict_encode()
        except Exception as e:  # noqa: BLE001 — raw-bytes path below
            log.debug("dict_encode failed for data sig (raw path): %s", e)
            dict_pair = None
    if dict_pair is not None:
        codes, uniques = dict_pair
        codes = np.asarray(codes)
        h.update(b"D")
        h.update(str(codes.dtype).encode())
        h.update(codes.tobytes())
        ub = pickle.dumps(list(np.asarray(uniques, dtype=object)),
                          protocol=4)
        h.update(ub)
        h.update(np.asarray(col.nulls).tobytes())
        return codes.nbytes + len(ub)
    data = col.data
    h.update(b"C")
    h.update(str(getattr(data, "dtype", "?")).encode())
    h.update(str(getattr(data, "shape", len(data))).encode())
    if getattr(data, "dtype", None) is not None and data.dtype != object:
        h.update(np.ascontiguousarray(data).tobytes())
        n = data.nbytes
    else:
        b = pickle.dumps(list(data), protocol=4)
        h.update(b)
        n = len(b)
    h.update(np.asarray(col.nulls).tobytes())
    return n
