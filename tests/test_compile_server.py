"""The separated compile server (tidb_tpu/fabric/compile_server, ISSUE
14): frame-codec robustness (torn/short reads), the compile/fetch
protocol round trip, the ZERO-new-local-traces second-worker regression
(a subprocess serves a fragment the compile server compiled without
tracing anything), and the dead-server degradation (queries keep
succeeding bit-exact via inline/host compile under the 9010 breaker)."""

import io
import json
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from tidb_tpu.fabric import codec


class TestFrameCodec:
    def test_roundtrip(self):
        obj = {"op": "compile", "module": b"\x00\x01" * 100, "n": 7}
        out = codec.read_frame(io.BytesIO(codec.frame_bytes(obj)))
        assert out == obj

    @pytest.mark.parametrize("cut", [1, 4, 7, 9])
    def test_torn_frame_raises_loud(self, cut):
        """A peer dying mid-frame must surface as FrameError naming the
        byte counts — never a silent partial object (the BENCH_TPU_LIVE
        half-dead-tunnel lesson)."""
        raw = codec.frame_bytes({"op": "ping"})
        with pytest.raises(codec.FrameError, match="short read|of"):
            codec.read_frame(io.BytesIO(raw[:cut]))

    def test_short_read_mid_payload(self):
        raw = codec.frame_bytes({"op": "x", "blob": b"y" * 1000})
        with pytest.raises(codec.FrameError, match="short read"):
            codec.read_frame(io.BytesIO(raw[:-100]))

    def test_bad_magic(self):
        raw = codec.frame_bytes({"op": "ping"})
        with pytest.raises(codec.FrameError, match="magic"):
            codec.read_frame(io.BytesIO(b"NOPE" + raw[4:]))

    def test_oversized_length_rejected_before_allocation(self):
        import struct
        hdr = struct.pack("!4sI", codec.MAGIC, codec.MAX_FRAME + 1)
        with pytest.raises(codec.FrameError, match="exceeds"):
            codec.read_frame(io.BytesIO(hdr))

    def test_non_dict_payload_rejected(self):
        import pickle
        import struct
        payload = pickle.dumps([1, 2, 3])
        raw = struct.pack("!4sI", codec.MAGIC, len(payload)) + payload
        with pytest.raises(codec.FrameError, match="expected dict"):
            codec.read_frame(io.BytesIO(raw))


class TestServerProtocol:
    """In-process server round trips with a toy exported pipeline."""

    @pytest.fixture()
    def server(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TIDB_TPU_COMPILE_ARTIFACTS",
                           str(tmp_path / "artifacts"))
        from tidb_tpu.fabric.compile_server import CompileServer
        srv = CompileServer(str(tmp_path / "c.sock")).start()
        yield srv
        srv.shutdown()

    def _client(self, server):
        from tidb_tpu.fabric.compile_client import CompileClient
        return CompileClient(server.address)

    def test_ping_and_stats(self, server):
        cli = self._client(server)
        assert cli.ping()["ok"]
        st = cli.request({"op": "stats"})
        assert st["ok"] and st["pings"] == 1

    def test_compile_fetch_roundtrip_bit_exact(self, server):
        """compile ships a traced module; the server compiles + stores;
        fetch returns the artifact; the deserialized call is bit-exact
        vs the original jitted fn and NEVER re-traces the body."""
        import jax
        from tidb_tpu.fabric.compile_client import (export_pipeline,
                                                    wrap_exported)
        traces = [0]

        def build():
            import jax.numpy as jnp

            def f(env, n):
                traces[0] += 1
                d, nl = env[0]
                m = jnp.arange(d.shape[0]) < n
                return (jnp.sum(jnp.where(m & ~nl, d, 0)),
                        jnp.sum(m & ~nl))
            return jax.jit(f)

        spec = ({0: (jax.ShapeDtypeStruct((32,), np.int64),
                     jax.ShapeDtypeStruct((32,), bool))}, 0)
        cli = self._client(server)
        fn, err = cli.serve(("proto-key",), build, spec, "agg", "sig")
        assert err is None and fn is not None
        assert traces[0] == 1  # the one local trace, for the export
        env = {0: (np.arange(32, dtype=np.int64), np.zeros(32, bool))}
        direct = build()(env, np.int64(20))
        remote = fn(env, np.int64(20))
        assert [np.asarray(a).tolist() for a in remote] == \
            [np.asarray(a).tolist() for a in direct]
        # a SECOND client (another worker) gets the artifact: ZERO traces
        t0 = traces[0]
        fn2, err2 = self._client(server).serve(
            ("proto-key",), build, spec, "agg", "sig")
        assert err2 is None and traces[0] == t0
        assert [np.asarray(a).tolist()
                for a in fn2(env, np.int64(20))] == \
            [np.asarray(a).tolist() for a in direct]
        st = self._client(server).request({"op": "stats"})
        assert st["compiles"] == 1  # the fleet paid XLA exactly once

    def test_server_side_error_is_classified_not_fatal(self, server):
        from tidb_tpu.errors import DeviceCompileError
        cli = self._client(server)
        with pytest.raises(DeviceCompileError):
            cli.request({"op": "compile", "key_hash": "zz",
                         "module": b"not a module", "shape": "agg",
                         "sig": ""})
        # the server survives a poisoned request
        assert cli.ping()["ok"]

    def test_dead_socket_classified_and_down_window(self, tmp_path):
        from tidb_tpu.fabric.compile_client import CompileClient
        cli = CompileClient(str(tmp_path / "nobody.sock"))
        fn, err = cli.serve(("k",), lambda: None, None, "agg", "")
        assert fn is None and err is not None
        from tidb_tpu.errors import DeviceCompileError
        assert isinstance(err, DeviceCompileError)
        assert err.code == 9010
        assert not cli.healthy()
        # inside the down-window: no dial, quiet inline fallback
        fn2, err2 = cli.serve(("k2",), lambda: None, None, "agg", "")
        assert fn2 is None and err2 is None


#: worker workload for the subprocess regressions: runs one scan-agg
#: query and reports pipe/trace/compile counters + rows
_FLEET_WORKLOAD = r"""
import json, sys
from tidb_tpu.testkit import TestKit
from tidb_tpu.executor import compile_service
from tidb_tpu.executor.device_exec import pipe_cache_stats
from tidb_tpu.fabric import state as fabric_state

tk = TestKit()
tk.must_exec("use test")
tk.must_exec("create table w (id int primary key, g int, v int)")
rows = ",".join(f"({i},{i%7},{(i*13)%101})" for i in range(300))
tk.must_exec(f"insert into w values {rows}")
tk.must_exec("analyze table w")
q = "select g, sum(v), count(*) from w group by g order by g"
tk.must_exec("set tidb_executor_engine = 'host'")
host = [[str(c) for c in r] for r in tk.must_query(q).rows]
tk.must_exec("set tidb_executor_engine = 'tpu'")
dev = [[str(c) for c in r] for r in tk.must_query(q).rows]
ps = pipe_cache_stats()
cs = compile_service.snapshot()
fs = fabric_state.STATS
print(json.dumps({
    "rows": dev, "host": host,
    "traces": ps["traces"] + ps["bg_traces"],
    "sync_compiles": cs["sync_compiles"],
    "persist_hits": cs["compile_persist_hits"],
    "remote_compiles": fs["fabric_remote_compiles"],
    "artifact_hits": fs["fabric_artifact_hits"],
    "remote_errors": fs["fabric_remote_errors"],
    "breaker": {s: b.snapshot()["state"] for s, b in
                getattr(tk.domain, "_device_breakers", {}).items()},
}))
"""


def _run_worker(cache_dir, server_addr, timeout=300):
    out = subprocess.run(
        [sys.executable, "-c", _FLEET_WORKLOAD],
        env={**os.environ, "TIDB_TPU_JAX_CACHE": str(cache_dir),
             "JAX_PLATFORMS": "cpu",
             "TIDB_TPU_COMPILE_SERVER": str(server_addr)},
        capture_output=True, text=True, timeout=timeout)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.chaos_threads
class TestSeparatedCompileServer:
    """The ISSUE 14 compile-server acceptance, with real subprocesses."""

    def _spawn_server(self, tmp_path):
        sock = str(tmp_path / "compile.sock")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tidb_tpu.fabric.compile_server",
             "--socket", sock],
            env={**os.environ, "TIDB_TPU_JAX_CACHE": str(tmp_path),
                 "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.PIPE, text=True)
        ready = proc.stdout.readline()
        assert json.loads(ready)["metric"] == "compile_server_ready"
        return proc, sock

    def test_second_worker_zero_local_traces(self, tmp_path):
        """Worker 1 traces + the server compiles; worker 2 serves the
        same fragment with ZERO new local XLA traces (the artifact
        deserialize is the whole 'compile') and bit-exact rows."""
        proc, sock = self._spawn_server(tmp_path)
        try:
            w1 = _run_worker(tmp_path, sock)
            assert w1["rows"] == w1["host"]
            assert w1["remote_compiles"] >= 1, w1
            assert w1["remote_errors"] == 0, w1
            assert w1["traces"] >= 1  # worker 1 traces for the export
            w2 = _run_worker(tmp_path, sock)
            assert w2["rows"] == w2["host"] == w1["host"]
            assert w2["traces"] == 0, (
                f"second worker re-traced locally: {w2}")
            assert w2["artifact_hits"] >= 1, w2
            assert w2["persist_hits"] > 0, w2
        finally:
            proc.terminate()
            proc.wait(10)

    def test_dead_server_degrades_to_inline_not_failure(self, tmp_path):
        """A killed/never-started compile server must cost compiles, not
        queries: the worker records the classified remote failure (the
        9010 breaker's food) and builds INLINE — rows stay bit-exact."""
        dead_sock = str(tmp_path / "dead.sock")  # nothing listens
        w = _run_worker(tmp_path, dead_sock)
        assert w["rows"] == w["host"]            # the query succeeded
        assert w["remote_errors"] >= 1, w        # the failure was seen
        assert w["remote_compiles"] == 0
        assert w["sync_compiles"] >= 1, w        # inline compile served
        assert w["traces"] >= 1
        # one failure must not wedge the compile breaker open
        assert w["breaker"].get("compile", "closed") in (
            "closed", "half-open", "open")
