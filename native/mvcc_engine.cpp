// Native MVCC storage engine — the performance-critical core of the
// embedded store (the reference's TiKV/unistore role is native Rust/Go;
// here C++ behind a C ABI consumed via ctypes).
//
// Semantics mirror tidb_tpu/kv/mvcc.py exactly (which in turn mirrors
// store/mockstore/unistore/tikv/mvcc.go: Prewrite :596, Commit :907):
// Percolator 2PC with primary locks, write-conflict detection against
// newer commits, rollback markers, pessimistic locks with wait-for-graph
// deadlock detection (unistore/tikv/detector.go), snapshot reads/scans
// that surface foreign locks, and safepoint GC (store/gcworker).
//
// Status codes shared with the Python wrapper:
//   0 ok | 1 locked | 2 write conflict | 3 deadlock
//   4 txn rolled back | 5 not found

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

enum Op : int32_t { OP_PUT = 0, OP_DEL = 1, OP_LOCK = 2, OP_ROLLBACK = 3 };

// Flag bit OR'd onto a prewrite op: skip the write-conflict check for this
// key. Used by the schema amender's injected index mutations — they are
// logically sequenced AFTER the concurrent ADD INDEX backfill the
// transaction just observed (the amendment reads the post-DDL schema), so
// "committed after my start_ts" on exactly these keys is not a conflict
// (reference: the amended-mutation commit path of session/schema_amender.go
// + client-go's special handling for amended keys).
static const int32_t OP_AMEND_FLAG = 16;

enum Status : int32_t {
  ST_OK = 0,
  ST_LOCKED = 1,
  ST_CONFLICT = 2,
  ST_DEADLOCK = 3,
  ST_ROLLED_BACK = 4,
  ST_NOT_FOUND = 5,
};

struct Version {
  uint64_t commit_ts;
  uint64_t start_ts;
  int32_t op;
  bool has_value;
  std::string value;
};

struct LockRec {
  uint64_t start_ts;
  int32_t op;
  bool has_value;
  std::string primary;
  std::string value;
};

struct Engine {
  std::mutex mu;
  // key -> version chain, newest (highest commit_ts) first
  std::map<std::string, std::vector<Version>> chains;
  std::unordered_map<std::string, LockRec> locks;
  std::unordered_map<uint64_t, uint64_t> waits;  // waiter -> holder

  void insert_version(const std::string& key, uint64_t commit_ts,
                      uint64_t start_ts, int32_t op, bool has_value,
                      const char* val, int vlen) {
    auto& chain = chains[key];
    // strictly descending commit_ts; rollback markers carry an old
    // start_ts and must not land above newer commits
    size_t i = 0;
    while (i < chain.size() && chain[i].commit_ts > commit_ts) i++;
    Version v;
    v.commit_ts = commit_ts;
    v.start_ts = start_ts;
    v.op = op;
    v.has_value = has_value;
    if (has_value && vlen > 0) v.value.assign(val, vlen);
    chain.insert(chain.begin() + i, std::move(v));
  }

  // newest non-rollback version with commit_ts <= ts; nullptr if none
  const Version* read(const std::string& key, uint64_t ts) {
    auto it = chains.find(key);
    if (it == chains.end()) return nullptr;
    for (const auto& v : it->second) {
      if (v.commit_ts <= ts && v.op != OP_ROLLBACK) return &v;
    }
    return nullptr;
  }

  uint64_t has_commit_after(const std::string& key, uint64_t ts) {
    auto it = chains.find(key);
    if (it == chains.end()) return 0;
    for (const auto& v : it->second) {
      if (v.commit_ts <= ts) break;
      if (v.op != OP_ROLLBACK) return v.commit_ts;
    }
    return 0;
  }

  bool has_rollback(const std::string& key, uint64_t start_ts) {
    auto it = chains.find(key);
    if (it == chains.end()) return false;
    for (const auto& v : it->second) {
      if (v.start_ts == start_ts && v.op == OP_ROLLBACK) return true;
    }
    return false;
  }
};

std::string mkstr(const char* p, int n) {
  return std::string(p, p + (n > 0 ? n : 0));
}

// output buffer: caller frees via mvcc_buf_free
char* alloc_out(const std::string& data, int64_t* out_len) {
  *out_len = (int64_t)data.size();
  char* buf = (char*)malloc(data.size() ? data.size() : 1);
  if (!data.empty()) memcpy(buf, data.data(), data.size());
  return buf;
}

void put_u32(std::string& s, uint32_t v) { s.append((char*)&v, 4); }

}  // namespace

extern "C" {

void* mvcc_new() { return new Engine(); }

void mvcc_delete(void* h) { delete (Engine*)h; }

void mvcc_buf_free(char* p) { free(p); }

// mutations: parallel arrays; vlens[i] < 0 means "no value" (DEL/LOCK)
int32_t mvcc_prewrite(void* h, int32_t n, const char** keys,
                      const int32_t* klens, const int32_t* ops,
                      const char** vals, const int32_t* vlens,
                      uint64_t start_ts, const char* primary, int32_t plen,
                      uint64_t* out_ts, int32_t* out_idx) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  for (int32_t i = 0; i < n; i++) {
    std::string key = mkstr(keys[i], klens[i]);
    auto it = e->locks.find(key);
    if (it != e->locks.end() && it->second.start_ts != start_ts) {
      *out_ts = it->second.start_ts;
      *out_idx = i;
      return ST_LOCKED;
    }
    if (it != e->locks.end() && it->second.op == OP_LOCK) {
      // own pessimistic lock: conflict was checked against for_update_ts
      // at lock-acquisition time (TiKV pessimistic-prewrite semantics)
      continue;
    }
    if (ops[i] & OP_AMEND_FLAG) continue;  // amended key: no ts conflict
    uint64_t conflict = e->has_commit_after(key, start_ts);
    if (conflict) {
      *out_ts = conflict;
      *out_idx = i;
      return ST_CONFLICT;
    }
    if (e->has_rollback(key, start_ts)) {
      *out_idx = i;
      return ST_ROLLED_BACK;
    }
  }
  for (int32_t i = 0; i < n; i++) {
    LockRec l;
    l.start_ts = start_ts;
    l.op = ops[i] & ~OP_AMEND_FLAG;  // store the base op
    l.primary = mkstr(primary, plen);
    l.has_value = vlens[i] >= 0;
    if (l.has_value && vlens[i] > 0) l.value.assign(vals[i], vlens[i]);
    e->locks[mkstr(keys[i], klens[i])] = std::move(l);
  }
  return ST_OK;
}

int32_t mvcc_commit(void* h, int32_t n, const char** keys,
                    const int32_t* klens, uint64_t start_ts,
                    uint64_t commit_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  for (int32_t i = 0; i < n; i++) {
    std::string key = mkstr(keys[i], klens[i]);
    auto it = e->locks.find(key);
    if (it == e->locks.end() || it->second.start_ts != start_ts) {
      // already committed (idempotent) or rolled back
      if (e->has_rollback(key, start_ts)) return ST_ROLLED_BACK;
      continue;
    }
    LockRec l = std::move(it->second);
    e->locks.erase(it);
    if (l.op != OP_LOCK) {
      e->insert_version(key, commit_ts, start_ts, l.op, l.has_value,
                        l.value.data(), (int)l.value.size());
    }
  }
  return ST_OK;
}

void mvcc_rollback(void* h, int32_t n, const char** keys,
                   const int32_t* klens, uint64_t start_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  for (int32_t i = 0; i < n; i++) {
    std::string key = mkstr(keys[i], klens[i]);
    auto it = e->locks.find(key);
    if (it != e->locks.end() && it->second.start_ts == start_ts)
      e->locks.erase(it);
    e->insert_version(key, start_ts, start_ts, OP_ROLLBACK, false, nullptr, 0);
  }
  e->waits.erase(start_ts);
}

int32_t mvcc_pessimistic_lock(void* h, int32_t n, const char** keys,
                              const int32_t* klens, uint64_t start_ts,
                              uint64_t for_update_ts, const char* primary,
                              int32_t plen, uint64_t* out_ts,
                              int32_t* out_idx) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  for (int32_t i = 0; i < n; i++) {
    std::string key = mkstr(keys[i], klens[i]);
    auto it = e->locks.find(key);
    if (it != e->locks.end() && it->second.start_ts != start_ts) {
      uint64_t holder = it->second.start_ts;
      // wait-for-graph cycle check (unistore/tikv/detector.go)
      e->waits[start_ts] = holder;
      std::unordered_set<uint64_t> seen{start_ts};
      uint64_t cur = holder;
      while (e->waits.count(cur)) {
        cur = e->waits[cur];
        if (seen.count(cur)) {
          e->waits.erase(start_ts);
          *out_ts = holder;
          *out_idx = i;
          return ST_DEADLOCK;
        }
        seen.insert(cur);
      }
      *out_ts = holder;
      *out_idx = i;
      return ST_LOCKED;
    }
    uint64_t conflict = e->has_commit_after(key, for_update_ts);
    if (conflict) {
      *out_ts = conflict;
      *out_idx = i;
      return ST_CONFLICT;
    }
  }
  for (int32_t i = 0; i < n; i++) {
    std::string key = mkstr(keys[i], klens[i]);
    if (!e->locks.count(key)) {
      LockRec l;
      l.start_ts = start_ts;
      l.op = OP_LOCK;
      l.has_value = false;
      l.primary = mkstr(primary, plen);
      e->locks[key] = std::move(l);
    }
  }
  return ST_OK;
}

void mvcc_clear_wait(void* h, uint64_t start_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  e->waits.erase(start_ts);
}

// 1 if locked (fills *start_ts), else 0
int32_t mvcc_lock_info(void* h, const char* key, int32_t klen,
                       uint64_t* start_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->locks.find(mkstr(key, klen));
  if (it == e->locks.end()) return 0;
  *start_ts = it->second.start_ts;
  return 1;
}

int32_t mvcc_get(void* h, const char* key, int32_t klen, uint64_t ts,
                 uint64_t own_start_ts, char** out, int64_t* out_len,
                 uint64_t* lock_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  std::string k = mkstr(key, klen);
  auto it = e->locks.find(k);
  if (it != e->locks.end() && it->second.start_ts != own_start_ts &&
      it->second.op != OP_LOCK && it->second.start_ts < ts) {
    *lock_ts = it->second.start_ts;
    return ST_LOCKED;
  }
  const Version* v = e->read(k, ts);
  if (v == nullptr || v->op != OP_PUT) return ST_NOT_FOUND;
  *out = alloc_out(v->value, out_len);
  return ST_OK;
}

// scan result buffer: repeated [u32 klen][key][u32 vlen][value]
int32_t mvcc_scan(void* h, const char* start, int32_t slen, const char* end,
                  int32_t elen, uint64_t ts, int64_t limit,
                  uint64_t own_start_ts, char** out, int64_t* out_len,
                  int64_t* out_n, uint64_t* lock_ts, char** lock_key,
                  int64_t* lock_key_len) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  std::string s = mkstr(start, slen);
  std::string en = mkstr(end, elen);
  std::string buf;
  int64_t n = 0;
  auto it = e->chains.lower_bound(s);
  for (; it != e->chains.end(); ++it) {
    if (elen > 0 && it->first >= en) break;
    auto lk = e->locks.find(it->first);
    if (lk != e->locks.end() && lk->second.start_ts != own_start_ts &&
        lk->second.op != OP_LOCK && lk->second.start_ts < ts) {
      *lock_ts = lk->second.start_ts;
      *lock_key = alloc_out(it->first, lock_key_len);
      return ST_LOCKED;
    }
    const Version* v = e->read(it->first, ts);
    if (v != nullptr && v->op == OP_PUT) {
      put_u32(buf, (uint32_t)it->first.size());
      buf.append(it->first);
      put_u32(buf, (uint32_t)v->value.size());
      buf.append(v->value);
      if (++n >= limit && limit > 0) break;
    }
  }
  *out = alloc_out(buf, out_len);
  *out_n = n;
  return ST_OK;
}

void mvcc_raw_put(void* h, const char* key, int32_t klen, const char* val,
                  int32_t vlen, uint64_t commit_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  e->insert_version(mkstr(key, klen), commit_ts, commit_ts, OP_PUT, true,
                    val, vlen);
}

// whole batch under one lock: a concurrent snapshot either sees the full
// batch or none of it (the Python engine holds its RLock across the batch)
void mvcc_raw_batch_put(void* h, int32_t n, const char** keys,
                        const int32_t* klens, const char** vals,
                        const int32_t* vlens, uint64_t commit_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  for (int32_t i = 0; i < n; i++) {
    e->insert_version(mkstr(keys[i], klens[i]), commit_ts, commit_ts,
                      OP_PUT, true, vals[i], vlens[i]);
  }
}

// check-then-commit/rollback of an orphan lock atomically (GC worker
// resolveLocks); composing lock_info + commit from Python races with
// concurrent rollbacks
int32_t mvcc_resolve_lock(void* h, const char* key, int32_t klen,
                          int32_t committed, uint64_t commit_ts) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  std::string k = mkstr(key, klen);
  auto it = e->locks.find(k);
  if (it == e->locks.end()) return ST_OK;
  uint64_t start_ts = it->second.start_ts;
  if (committed) {
    LockRec l = std::move(it->second);
    e->locks.erase(it);
    if (l.op != OP_LOCK) {
      e->insert_version(k, commit_ts, start_ts, l.op, l.has_value,
                        l.value.data(), (int)l.value.size());
    }
  } else {
    e->locks.erase(it);
    e->insert_version(k, start_ts, start_ts, OP_ROLLBACK, false, nullptr, 0);
    e->waits.erase(start_ts);
  }
  return ST_OK;
}

void mvcc_raw_delete_range(void* h, const char* start, int32_t slen,
                           const char* end, int32_t elen) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  std::string s = mkstr(start, slen);
  auto lo = e->chains.lower_bound(s);
  auto hi = elen > 0 ? e->chains.lower_bound(mkstr(end, elen))
                     : e->chains.end();
  e->chains.erase(lo, hi);
}

void mvcc_gc(void* h, uint64_t safe_point) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->chains.begin();
  while (it != e->chains.end()) {
    std::vector<Version> keep;
    bool kept_visible = false;
    for (auto& v : it->second) {
      if (v.commit_ts > safe_point) {
        keep.push_back(std::move(v));
      } else if (v.op == OP_ROLLBACK) {
        continue;  // stale marker: never the visible version
      } else if (!kept_visible) {
        kept_visible = true;
        if (v.op == OP_PUT) keep.push_back(std::move(v));
      }
      // older than first visible-at-safepoint: drop
    }
    if (keep.empty()) {
      it = e->chains.erase(it);
    } else {
      it->second = std::move(keep);
      ++it;
    }
  }
}

// chain introspection (reference: server/http_handler.go MVCC API):
// repeated [u64 commit_ts][u64 start_ts][i32 op][u32 vlen][value]
int32_t mvcc_chain_dump(void* h, const char* key, int32_t klen, char** out,
                        int64_t* out_len, int64_t* out_n) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  std::string buf;
  int64_t n = 0;
  auto it = e->chains.find(mkstr(key, klen));
  if (it != e->chains.end()) {
    for (const auto& v : it->second) {
      buf.append((char*)&v.commit_ts, 8);
      buf.append((char*)&v.start_ts, 8);
      buf.append((char*)&v.op, 4);
      uint32_t vlen = v.has_value ? (uint32_t)v.value.size() : 0;
      put_u32(buf, vlen);
      buf.append(v.value.data(), vlen);
      n++;
    }
  }
  *out = alloc_out(buf, out_len);
  *out_n = n;
  return ST_OK;
}

int64_t mvcc_key_count(void* h) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  return (int64_t)e->chains.size();
}

// Locks whose start_ts <= max_ts, serialized as
// [start_ts u64][klen u32][key][plen u32][primary] per entry — the GC
// worker's resolveLocks scan (store/gcworker/gc_worker.go:1015).
int32_t mvcc_scan_locks(void* h, uint64_t max_ts, char** out,
                        int64_t* out_len, int64_t* out_n) {
  Engine* e = (Engine*)h;
  std::lock_guard<std::mutex> g(e->mu);
  std::string buf;
  int64_t n = 0;
  for (const auto& kv : e->locks) {
    if (kv.second.start_ts > max_ts) continue;
    buf.append((char*)&kv.second.start_ts, 8);
    put_u32(buf, (uint32_t)kv.first.size());
    buf.append(kv.first);
    put_u32(buf, (uint32_t)kv.second.primary.size());
    buf.append(kv.second.primary);
    n++;
  }
  *out = alloc_out(buf, out_len);
  *out_n = n;
  return ST_OK;
}

}  // extern "C"
