"""HTTP status API (reference: server/http_status.go:194-240 routes +
http_handler.go introspection): /status, /schema, /ddl/history, /metrics
(Prometheus text format), /settings, /regions."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..meta import Meta
from ..model import JobState, SchemaState


def _mpp_snapshot() -> dict:
    """MPP mesh-path gauges for /status and /metrics (process-wide, like
    the supervisor/residency gauges)."""
    from ..executor import mpp_exec
    return mpp_exec.snapshot()


def _compiler_snapshot() -> dict:
    """Compile-service gauges for /status and /metrics (process-wide)."""
    from ..executor import compile_service
    return compile_service.snapshot()


def _hybrid_join_snapshot() -> dict:
    """Lazy import: the hybrid join pulls the executor stack in."""
    from ..executor import hybrid_join
    return hybrid_join.snapshot()


def _tracing_snapshot() -> dict:
    """Span-tracer ring stats for /status (process-wide)."""
    from ..session import tracing
    return tracing.snapshot()


def _fabric_snapshot() -> dict:
    """Serving-fabric gauges (tidb_tpu/fabric/state.py): this worker's
    slot + dedup/remote-compile counters, and the fleet-global view
    (live workers, respawns) when a coordination segment is attached."""
    from ..fabric import state
    return state.snapshot()


def _wal_snapshot(domain) -> dict:
    """Durable-store gauges (kv/wal.py + kv/shared_store.py): append /
    fsync / group-commit / recovery / torn-truncation counters, plus
    this replica's applied-vs-end LSN when the store is durable — WAL
    lag and recovery history diagnosable from the status port."""
    from ..kv import wal as wal_mod
    out = wal_mod.snapshot()
    status = getattr(domain.store.mvcc, "wal_status", None)
    if status is not None:
        out.update(status())
    return out


class StatusServer:
    def __init__(self, domain, sql_server=None, host="127.0.0.1", port=10080):
        self.domain = domain
        self.sql_server = sql_server
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    outer._route(self)
                except Exception as e:  # introspection must not kill the server
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()

    # -- routing -------------------------------------------------------------

    def _route(self, req):
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/status":
            return self._json(req, self._status())
        if path == "/metrics":
            return self._text(req, self._metrics())
        if path == "/schema":
            return self._json(req, list(self.domain.infoschema().schema_names()))
        if path.startswith("/schema/"):
            return self._schema(req, path[len("/schema/"):])
        if path == "/ddl/history":
            return self._json(req, self._ddl_history())
        if path == "/settings":
            return self._json(req, dict(self.domain.global_vars))
        if path == "/regions":
            return self._json(req, [
                {"id": r.id, "start": r.start.hex(), "end": r.end.hex()}
                for r in self.domain.store.mvcc.regions])
        req.send_response(404)
        req.end_headers()

    def _json(self, req, obj):
        body = json.dumps(obj, indent=1, default=str).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _text(self, req, s: str):
        body = s.encode()
        req.send_response(200)
        req.send_header("Content-Type", "text/plain; version=0.0.4")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # -- payloads ------------------------------------------------------------

    def _status(self):
        from ..executor import scheduler, supervisor
        from ..ops import residency
        return {
            "version": "8.0.11-tpu-htap",
            "connections": len(self.domain.sessions),
            "kv_engine": self.domain.store.backend,
            # device-runtime supervision (executor/supervisor.py): the
            # abandoned-calls gauge plus hang/fence counters, so a hung
            # backend is diagnosable from the status port alone
            "device_abandoned_calls": supervisor.abandoned_calls(),
            "device_supervisor": supervisor.snapshot(),
            # HBM residency (ops/residency.py): cached-bytes ledger,
            # budget, epoch and the eviction / OOM-recovery counters —
            # device memory pressure diagnosable from the status port
            "device_residency": residency.snapshot(),
            # serving scheduler (executor/scheduler.py): admission queue
            # depth, per-tenant running counts / degradations, WFQ state
            "device_scheduler": scheduler.snapshot(),
            # MPP mesh path (executor/mpp_exec.py): fragments, retries
            # (capacity growth / transport / radix-exchange overflow),
            # placement-cache entries + residency-ledgered bytes
            "device_mpp": _mpp_snapshot(),
            # compile service (executor/compile_service.py): background
            # queue depth, worker pool, sync/bg compile counters, the
            # persistent-index hits and the last classified compile error
            # — a flaky remote-compile tunnel is diagnosable from the
            # status port alone (the BENCH_TPU_LIVE Q5 failure mode)
            "device_compiler": _compiler_snapshot(),
            # breaker stat lines keyed by (shape, resource group)
            "device_breakers": {
                shape: br.snapshot() for shape, br in
                getattr(self.domain, "_device_breakers", {}).items()},
            # span tracing (session/tracing.py): finished-trace ring
            # occupancy, started/finished/outstanding trace counts and
            # the per-trace span-bound drop counter — whether the
            # recorder is keeping up is diagnosable from the status port
            "device_tracing": _tracing_snapshot(),
            # hybrid hash join (executor/hybrid_join.py): partition
            # fanout, spilled partitions/bytes, co-processed host rows
            # and the open-spill-set drain gauge — whether a build side
            # is spilling (and leaking) is diagnosable from the port
            "device_hybrid_join": _hybrid_join_snapshot(),
            # serving fabric (tidb_tpu/fabric): worker slot, live fleet
            # size, respawns, fragment-dedup hits/waits, compile-server
            # RTT + remote errors — which worker this is and whether the
            # fleet is whole, diagnosable from any worker's status port
            "device_fabric": _fabric_snapshot(),
            # durable shared store (kv/wal.py): appends, fsync policy +
            # counts, group commits, recoveries, torn-tail truncations,
            # and this replica's applied WAL frontier
            "storage_wal": _wal_snapshot(self.domain),
        }

    def _metrics(self):
        """Prometheus text exposition of the domain counters (reference:
        metrics/metrics.go registry served on the status port)."""
        from ..executor import supervisor
        lines = []
        for name, val in sorted(self.domain.observe.counters.items()):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {val}")
        # span-ring eviction pressure: finished traces aged out of the
        # bounded ring before a reader pulled them — when this moves, the
        # cluster memtables / TRACE post-mortems are losing history and
        # RING_CAP (session/tracing.py) needs a look
        ts = _tracing_snapshot()
        lines.append("# TYPE trace_ring_dropped_total counter")
        lines.append(
            f"trace_ring_dropped_total {ts.get('ring_dropped', 0)}")
        gauges = dict(self.domain.observe.gauge_snapshot())
        # the supervisor/residency gauges are process-wide; surface them
        # even when no device dispatch has registered this domain's sink
        from ..ops import residency
        rs = residency.snapshot()
        gauges.setdefault("device_abandoned_calls",
                          supervisor.abandoned_calls())
        gauges.setdefault("hbm_bytes_cached", rs["hbm_bytes_cached"])
        gauges.setdefault("hbm_evictions", rs["hbm_evictions"])
        gauges.setdefault("hbm_oom_recoveries", rs["hbm_oom_recoveries"])
        from ..executor import scheduler
        ss = scheduler.snapshot()
        gauges.setdefault("sched_queue_depth", ss["sched_queue_depth"])
        gauges.setdefault("sched_admission_waits_ms",
                          ss["sched_admission_waits_ms"])
        gauges.setdefault("sched_batched_fragments",
                          ss["sched_batched_fragments"])
        ms = _mpp_snapshot()
        gauges.setdefault("mpp_place_bytes", ms["mpp_place_bytes"])
        gauges.setdefault("mpp_fragments", ms["fragments"])
        gauges.setdefault("mpp_retries", ms["retries"])
        gauges.setdefault("mpp_exchange_overflow_retries",
                          ms["exchange_overflow_retries"])
        cs = _compiler_snapshot()
        gauges.setdefault("compile_queue_depth", cs["compile_queue_depth"])
        gauges.setdefault("compile_pending_fragments",
                          cs["compile_pending_fragments"])
        gauges.setdefault("compile_bg_seconds", cs["compile_bg_seconds"])
        gauges.setdefault("compile_persist_hits",
                          cs["compile_persist_hits"])
        hs = _hybrid_join_snapshot()
        gauges.setdefault("hj_partitions", hs["hj_partitions"])
        gauges.setdefault("hj_spilled_partitions",
                          hs["hj_spilled_partitions"])
        gauges.setdefault("hj_spill_bytes", hs["hj_spill_bytes"])
        gauges.setdefault("hj_coproc_host_rows", hs["hj_coproc_host_rows"])
        fs = _fabric_snapshot()
        gauges.setdefault("fabric_workers", fs.get("fabric_workers", 0))
        gauges.setdefault("fabric_respawns", fs.get("fabric_respawns", 0))
        gauges.setdefault("fabric_dedup_hits", fs["fabric_dedup_hits"])
        gauges.setdefault("fabric_compile_rtt_ms",
                          fs["fabric_compile_rtt_ms"])
        # versioned result cache (executor/agg_cache.py): this worker's
        # share + the fleet-global segment counters when attached
        gauges.setdefault("cache_hits", fs.get("cache_hits", 0))
        gauges.setdefault("cache_invalidations",
                          fs.get("cache_invalidations", 0))
        gauges.setdefault("cache_delta_folds",
                          fs.get("cache_delta_folds", 0))
        gauges.setdefault("cache_stale_reads",
                          fs.get("cache_stale_reads", 0))
        gauges.setdefault("fleet_cache_hits",
                          fs.get("fleet_cache_hits", 0))
        # fleet-frontier freshness (kv/shared_store.fresh_read_ts):
        # waits that blocked, budget blowups (9011 refusals) and
        # explicit stale_ok downgrades — the zero-silent-staleness
        # contract's scrapeable evidence
        gauges.setdefault("freshness_waits",
                          fs.get("freshness_waits", 0))
        gauges.setdefault("freshness_timeouts",
                          fs.get("freshness_timeouts", 0))
        gauges.setdefault("freshness_stale_ok",
                          fs.get("freshness_stale_ok", 0))
        # shared fragment-perf store (fabric/perf.py + the segment's
        # TPUFAB4 PERF section): fleet row/sample totals when attached,
        # this process's feed counters always
        gauges.setdefault("fabric_perf_rows",
                          fs.get("fabric_perf_rows", 0))
        gauges.setdefault("fabric_perf_samples",
                          fs.get("fabric_perf_samples", 0))
        ps = fs.get("perf_store", {})
        gauges.setdefault("perf_notes", ps.get("perf_notes", 0))
        gauges.setdefault("perf_merged", ps.get("perf_merged", 0))
        ws = _wal_snapshot(self.domain)
        gauges.setdefault("wal_appends", ws["wal_appends"])
        gauges.setdefault("wal_fsyncs", ws["wal_fsyncs"])
        gauges.setdefault("wal_group_commits", ws["wal_group_commits"])
        gauges.setdefault("wal_replayed_records",
                          ws["wal_replayed_records"])
        gauges.setdefault("wal_truncated_records",
                          ws["wal_truncated_records"])
        gauges.setdefault("wal_tail_records", ws["wal_tail_records"])
        # per-tenant degradations as ONE labeled series (a single TYPE
        # header — duplicate TYPE lines are invalid text exposition and
        # fail the whole scrape); the observe-sink mirror keys them
        # "sched_degradations:<group>", folded in here
        per_group = dict(ss["degradations_by_group"])
        for name in [k for k in gauges if
                     k.startswith("sched_degradations:")]:
            per_group.setdefault(name.split(":", 1)[1], gauges[name])
            del gauges[name]
        if per_group:
            lines.append("# TYPE sched_degradations gauge")
            for g, n in sorted(per_group.items()):
                # label escaping per the exposition format: the group
                # name is a free-form session sysvar, and one raw quote
                # or newline would invalidate the WHOLE scrape
                esc = (str(g).replace("\\", r"\\").replace('"', r'\"')
                       .replace("\n", r"\n"))
                lines.append(
                    f'sched_degradations{{resource_group="{esc}"}} {n}')
        for name, val in sorted(gauges.items()):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {val}")
        # per-layer latency histograms (session/observe.py HIST_BUCKETS)
        # as proper Prometheus cumulative `_bucket`/`_sum`/`_count`
        # series — statement / admission-wait / sync-compile / dispatch
        # p99s are scrapeable without bench.py
        for name, (bounds, counts, hsum, _cnt) in sorted(
                self.domain.observe.hist_snapshot().items()):
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for b, c in zip(bounds, counts):
                cum += c
                lines.append(f'{name}_bucket{{le="{b:g}"}} {cum}')
            cum += counts[-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{name}_sum {hsum:g}")
            lines.append(f"{name}_count {cum}")
        lines.append("# TYPE server_connections gauge")
        lines.append(f"server_connections {len(self.domain.sessions)}")
        return "\n".join(lines) + "\n"

    def _schema(self, req, rest: str):
        infos = self.domain.infoschema()
        parts = rest.split("/")
        if len(parts) == 1:
            if infos.schema_by_name(parts[0]) is None:
                req.send_response(404)
                req.end_headers()
                return
            tables = [t.name for t in infos.tables_in_schema(parts[0])]
            return self._json(req, tables)
        tbl = infos.table_by_name(parts[0], parts[1])
        if tbl is None:
            req.send_response(404)
            req.end_headers()
            return
        payload = tbl.to_json()
        if isinstance(payload, str):
            payload = json.loads(payload)
        return self._json(req, payload)

    def _ddl_history(self):
        txn = self.domain.store.begin()
        try:
            jobs = Meta(txn).history_jobs()[-50:]
        finally:
            txn.rollback()
        return [{
            "id": j.id, "type": j.type,
            "state": JobState.NAMES.get(j.state, "?"),
            "schema_state": SchemaState.NAMES.get(j.schema_state, "?"),
            "table_id": j.table_id, "row_count": j.row_count,
            "err": j.error,
        } for j in jobs]
