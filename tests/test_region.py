"""Region-sharded multi-host fleet (ISSUE 16): the RegionMap keyspace
partition, the object-store-shaped blob API (rename-last uploads, torn
uploads invisible), per-region WAL replication (checkpoint + committed
tail + MANIFEST-last), region failover (survivor claims an expired
lease, restores from blobs alone, replays, resolves orphans), epoch
fencing (a zombie host's appender can never write into a failed-over
region), and the network coordinator's parity / degrade discipline."""

import contextlib
import os
import time

import pytest

from tidb_tpu.fabric.blob import (BlobError, LocalDirBlobStore,
                                  open_blob_store)
from tidb_tpu.fabric.coord import Coordinator
from tidb_tpu.fabric.coord_net import (CoordRemoteError, CoordServer,
                                       CoordUnavailableError,
                                       NetCoordinator)
from tidb_tpu.fabric.region import (RegionEpochError, RegionMap,
                                    RegionStore,
                                    verify_region_invariants)
from tidb_tpu.kv import wal as wal_mod
from tidb_tpu.kv.store import OP_PUT, Storage

NREGIONS = 4


@pytest.fixture()
def coord(tmp_path):
    c = Coordinator.create(str(tmp_path / "coord"), nregions=NREGIONS)
    yield c
    with contextlib.suppress(Exception):
        c.unlink()


@pytest.fixture()
def blob(tmp_path):
    return LocalDirBlobStore(str(tmp_path / "blob"))


def rkey(rid: int, suffix: bytes = b"k", n: int = NREGIONS) -> bytes:
    """A key guaranteed to land in region ``rid`` of an n-region map."""
    return ((rid << 64) // n).to_bytes(8, "big") + suffix


# -- keyspace partition -------------------------------------------------------

class TestRegionMap:
    def test_regions_partition_the_keyspace(self):
        m = RegionMap(NREGIONS)
        for rid in range(NREGIONS):
            assert m.region_of(rkey(rid)) == rid
        assert m.region_of(b"") == 0
        assert m.region_of(b"\xff" * 16) == NREGIONS - 1

    def test_bounds_are_contiguous_and_open_ended(self):
        m = RegionMap(NREGIONS)
        assert m.bounds(0)[0] == b""
        assert m.bounds(NREGIONS - 1)[1] == b""
        for rid in range(NREGIONS - 1):
            assert m.bounds(rid)[1] == m.bounds(rid + 1)[0]
        with pytest.raises(IndexError):
            m.bounds(NREGIONS)

    def test_split_range_fans_out_and_clamps(self):
        m = RegionMap(NREGIONS)
        spans = m.split_range(b"", b"")
        assert [s[0] for s in spans] == list(range(NREGIONS))
        # a range inside one region stays one span with its own bounds
        one = m.split_range(rkey(2, b"a"), rkey(2, b"z"))
        assert one == [(2, rkey(2, b"a"), rkey(2, b"z"))]
        # a straddling range clamps each span to the region grid
        two = m.split_range(rkey(1, b"x"), rkey(2, b"x"))
        assert [s[0] for s in two] == [1, 2]
        assert two[0][1] == rkey(1, b"x") and two[1][2] == rkey(2, b"x")


# -- blob store (satellite 3) -------------------------------------------------

class TestBlobStore:
    def test_upload_list_fetch_round_trip(self, blob):
        blob.put("region-0/a.bin", b"alpha")
        blob.put("region-0/b.bin", b"beta")
        blob.put("region-1/c.bin", b"gamma")
        assert blob.get("region-0/a.bin") == b"alpha"
        assert blob.list("region-0/") == ["region-0/a.bin",
                                          "region-0/b.bin"]
        assert blob.exists("region-1/c.bin")
        blob.delete("region-0/a.bin")
        assert not blob.exists("region-0/a.bin")
        with pytest.raises(BlobError):
            blob.get("region-0/a.bin")

    def test_torn_upload_invisible(self, blob, tmp_path):
        """rename-last: a crash mid-upload leaves only a tmp file, which
        list() skips and get() refuses — a reader can never fetch half
        an object."""
        blob.put("region-0/whole.bin", b"x" * 64)
        torn = os.path.join(str(tmp_path / "blob"), "region-0",
                            ".tmp-crashed")
        with open(torn, "wb") as f:
            f.write(b"half an uplo")
        assert blob.list("region-0/") == ["region-0/whole.bin"]
        # and a COMPLETED put leaves no tmp residue behind
        names = os.listdir(os.path.join(str(tmp_path / "blob"),
                                        "region-0"))
        assert [n for n in names if n.startswith(".tmp-")] == \
            [".tmp-crashed"]

    def test_open_blob_store_schemes(self, tmp_path):
        d = str(tmp_path / "x")
        assert isinstance(open_blob_store(d), LocalDirBlobStore)
        assert isinstance(open_blob_store("file://" + d),
                          LocalDirBlobStore)
        with pytest.raises(NotImplementedError):
            open_blob_store("gs://bucket/prefix")


# -- coordination-segment region cells ----------------------------------------

class TestRegionCells:
    def test_claim_fences_foreign_live_lease(self, coord):
        coord.claim_slot(0)
        coord.claim_slot(1)
        e1 = coord.region_claim(0, 0)
        assert e1 > 0
        # a live foreign lease is not up for grabs
        assert coord.region_claim(0, 1) == 0
        assert coord.region_heartbeat(0, 0, e1)
        assert coord.region_check(0, e1)
        # release -> next claim bumps the epoch (fencing token)
        coord.region_release(0, 0)
        e2 = coord.region_claim(0, 1)
        assert e2 > e1
        assert not coord.region_check(0, e1)
        assert not coord.region_heartbeat(0, 0, e1)
        assert not coord.region_set_committed(0, e1, 128)
        assert coord.region_set_committed(0, e2, 128)
        assert coord.region_committed_len(0) == 128

    def test_expiry_and_drain_listing(self, coord):
        coord.claim_slot(0)
        e = coord.region_claim(2, 0, lease_timeout_s=0.05)
        assert e > 0
        assert coord.regions_expired(60.0) == []
        time.sleep(0.08)
        assert 2 in coord.regions_expired(0.05)
        d = coord.verify_drained()
        assert not d["ok"] and 2 in d["region_leases"]
        coord.region_release_all(0)
        coord.release_slot(0)
        assert coord.verify_drained()["ok"]


# -- the router ---------------------------------------------------------------

class TestRegionStoreRouting:
    def test_cross_region_txn_and_ordered_scan(self, tmp_path, coord):
        coord.claim_slot(0)
        rs = RegionStore(str(tmp_path / "h0"), coord, 0)
        assert rs.open_regions() == list(range(NREGIONS))
        st = Storage(mvcc=rs)
        # ONE txn spanning three regions: Percolator primary in region 0
        t = st.begin()
        for rid in (0, 1, 3):
            t.put(rkey(rid, b"row"), b"v%d" % rid)
        t.commit()
        ts = rs.tso.next_ts()
        assert rs.get(rkey(1, b"row"), ts) == b"v1"
        # full-range scan fans out per region and concatenates ordered
        rows = rs.scan(b"", b"", ts)
        assert [v for _k, v in rows] == [b"v0", b"v1", b"v3"]
        assert [k for k, _v in rows] == sorted(k for k, _v in rows)
        assert rs.scan(b"", b"", ts, limit=2) == rows[:2]
        rs.close()

    def test_unowned_region_raises_not_serves(self, tmp_path, coord):
        coord.claim_slot(0)
        rs = RegionStore(str(tmp_path / "h0"), coord, 0)
        rs.open_regions([0, 1])   # regions 2,3 belong to nobody here
        with pytest.raises(RegionEpochError):
            rs.raw_put(rkey(3), b"x")
        rs.close()


# -- replication + failover ---------------------------------------------------

class TestReplicationFailover:
    def test_restore_is_bit_equal(self, tmp_path, coord, blob):
        coord.claim_slot(0)
        rs = RegionStore(str(tmp_path / "h0"), coord, 0, blob=blob)
        rs.open_regions()
        st = Storage(mvcc=rs)
        for i in range(8):
            t = st.begin()
            t.put(rkey(i % NREGIONS, b"k%03d" % i), b"v%d" % i)
            t.commit()
        rs.checkpoint_region(0)   # one region restores via checkpoint
        manifests = rs.replicate()
        assert sorted(manifests) == list(range(NREGIONS))
        ts = rs.tso.next_ts()
        before = rs.scan(b"", b"", ts)
        rs.close()
        coord.release_slot(0)
        # cold restart from the blob store ALONE: fresh segment + dirs
        c2 = Coordinator.create(str(tmp_path / "coord2"),
                                nregions=NREGIONS)
        try:
            c2.claim_slot(0)
            cold = RegionStore(str(tmp_path / "cold"), c2, 0, blob=blob)
            cold.open_regions(restore=True)
            assert cold.scan(b"", b"", ts) == before
            cold.close(replicate=False)
        finally:
            with contextlib.suppress(Exception):
                c2.unlink()

    def test_failover_fences_zombie_and_rolls_back_orphan(
            self, tmp_path, coord, blob):
        coord.claim_slot(0)
        coord.claim_slot(1)
        dead = RegionStore(str(tmp_path / "h0"), coord, 0, blob=blob)
        dead.open_regions()
        st = Storage(mvcc=dead)
        t = st.begin(); t.put(rkey(1, b"acked"), b"safe"); t.commit()
        dead.replicate()
        # the mid-kill crash window: prewrite in the replicated log,
        # commit never written
        t2 = st.begin()
        orphan = rkey(1, b"orphan")
        dead.prewrite([(orphan, OP_PUT, b"doomed")], orphan, t2.start_ts)
        dead.replicate()
        ts = dead.tso.next_ts()
        # the survivor treats the leases as expired and takes over from
        # the blob store alone
        surv = RegionStore(str(tmp_path / "h1"), coord, 1, blob=blob,
                           lease_timeout_s=0.0)
        assert sorted(surv.failover_expired()) == list(range(NREGIONS))
        assert surv.get(rkey(1, b"acked"), ts) == b"safe"
        assert surv.get(orphan, surv.tso.next_ts()) is None  # rolled back
        # the zombie is epoch-fenced before any byte hits its log
        with pytest.raises(RegionEpochError):
            dead.raw_put(rkey(1, b"zombie"), b"x")
        # and its close-time replicate must not clobber the survivor's
        # MANIFEST (epoch check skips fenced regions)
        dead.close()
        surv_epoch = surv.epochs[1]
        man = surv._replicator.manifest(1)
        surv.replicate()
        man2 = surv._replicator.manifest(1)
        assert man2["epoch"] == surv_epoch >= man["epoch"]
        surv.close()
        coord.release_slot(0)
        coord.release_slot(1)
        inv = verify_region_invariants(coord, blob)
        assert inv["ok"], inv
        assert coord.verify_drained()["ok"]

    def test_lost_heartbeat_drops_the_store(self, tmp_path, coord, blob):
        """A host that misses its lease renewal must DROP the region the
        moment a heartbeat is rejected — keeping serving would split-
        brain against the failover owner."""
        coord.claim_slot(0)
        coord.claim_slot(1)
        a = RegionStore(str(tmp_path / "h0"), coord, 0, blob=blob)
        a.open_regions([2])
        b = RegionStore(str(tmp_path / "h1"), coord, 1, blob=blob,
                        lease_timeout_s=0.0)
        assert b.failover_expired() == [2]
        assert a.heartbeat() == [2]          # rejected -> dropped
        assert 2 not in a.stores
        a.close()
        b.close()

    def test_invariants_catch_a_lying_manifest(self, tmp_path, coord,
                                               blob):
        coord.claim_slot(0)
        rs = RegionStore(str(tmp_path / "h0"), coord, 0, blob=blob)
        rs.open_regions([0])
        rs.raw_put(rkey(0), b"v")
        man = rs.replicate()[0]
        rs.close()
        coord.release_slot(0)
        assert verify_region_invariants(coord, blob)["ok"]
        blob.delete(man["tail"])   # manifest now references a ghost
        inv = verify_region_invariants(coord, blob)
        assert not inv["ok"] and inv["manifest_errors"]

    def test_region_wal_dir_layout(self, tmp_path):
        root = str(tmp_path / "w")
        for rid in (0, 3, 7):
            os.makedirs(wal_mod.region_dir(root, rid))
        assert wal_mod.region_ids(root) == [0, 3, 7]


# -- the network coordinator --------------------------------------------------

class TestNetCoordinator:
    def test_parity_and_remote_errors(self, tmp_path, coord):
        srv = CoordServer(coord)
        addr = srv.start()
        try:
            net = NetCoordinator(addr)
            assert net.nregions == NREGIONS
            net.claim_slot(3)
            e = net.region_claim(1, 3)
            assert e > 0 and coord.region_check(1, e)
            assert net.region_info(1)["owner"] == 3
            assert net.tso_lease(8)[1] > 0
            # a semantic error crosses the wire typed, not as a hang
            with pytest.raises(CoordRemoteError) as ei:
                net.region_claim(NREGIONS + 9, 3)
            assert ei.value.err_type == "IndexError"
            # ops outside the allowlist don't exist on the client
            with pytest.raises(AttributeError):
                net.unlink
            net.region_release(1, 3)
            net.release_slot(3)
        finally:
            srv.stop()

    def test_region_store_over_the_wire(self, tmp_path, coord):
        srv = CoordServer(coord)
        addr = srv.start()
        try:
            net = NetCoordinator(addr)
            net.claim_slot(2)
            rs = RegionStore(str(tmp_path / "net"), net, 2)
            rs.open_regions([0, 1])
            rs.raw_put(rkey(0), b"over-tcp")
            assert rs.get(rkey(0), rs.tso.next_ts()) == b"over-tcp"
            rs.close()
            net.release_slot(2)
            assert coord.verify_drained()["ok"]
        finally:
            srv.stop()

    def test_down_window_degrades_admission_not_correctness(self):
        """With the coordinator unreachable, admission-shaped ops
        degrade to local-only (never a failed query) while
        correctness-critical ops (TSO) raise CoordUnavailableError
        FAST inside the down-window instead of re-paying the budget."""
        net = NetCoordinator("127.0.0.1:9", down_cooldown_s=60.0)
        t0 = time.monotonic()
        assert net.try_acquire_running(0, "g", 4) is True
        assert not net.healthy()
        assert net.vtimes(["g"]) == {"g": 0.0}
        assert net.live_slots() == []
        with pytest.raises(CoordUnavailableError):
            net.tso_lease(8)
        # one budgeted retry burst + instant short-circuits afterwards
        assert time.monotonic() - t0 < 5.0
