"""Serving benchmark: N concurrent client threads multiplexing ONE
Domain's device through the admission scheduler (ISSUE 6 / ROADMAP open
item 2).

Where bench.py measures one query at a time as fast as the hardware
allows, THIS bench measures the serving story: mixed TPC-H reads
(analytical tenant, forced device engine) + transfer-DML and point reads
(OLTP tenant, auto engine) from N client threads, with per-tenant
p50/p99 latency, queries/s, admission waits, batched fragments and
degradations on the report — optionally under the threaded chaos
catalog (seeded failpoints: backend hangs beneath a small
`tidb_device_call_timeout`, synthetic HBM OOM, admission refusals and
stalls), so SLO behavior under faults is pinned, not hoped for.

Invariants enforced (exit code 1 on violation):
  * every operation succeeds or fails with a CLEAN classified error —
    never an unclassified exception;
  * zero incorrect results: analytical reads match a fault-free host
    golden bit-for-bit; the transfer ledger sums to its seed total in
    every snapshot and at the end;
  * the admission queue drains to zero (no leaked tickets) and the
    residency ledger shows no drift.

Output: one JSON line per metric (same convention as bench.py):
  {"metric": "serve_latency_ms", "group": "olap", "p50": ..., "p99": ...}
  {"metric": "serve_qps", "value": ..., "threads": N, ...}
  {"metric": "serve_sched", "sched_queue_depth": 0, ...}

Usage:
  python bench_serve.py                  # 8 threads, default mix
  python bench_serve.py --smoke          # small fixed-seed tier-1 run
  python bench_serve.py --threads 16 --ops 40 --sf 0.01 --chaos
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import random
import sys
import threading
import time

import tidb_tpu  # noqa: F401  (x64 on)

from tidb_tpu.errors import TiDBError
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.failpoint import FailpointError

import bench  # repo-root sibling: TPC-H datagen + the north-star queries

#: transfer-ledger seed state (the write-atomicity invariant)
N_ACCTS = 8
SEED_BAL = 1000
LEDGER_TOTAL = N_ACCTS * SEED_BAL

#: analytical corpus: the north-star shapes that fit a serving mix
#: (Q1 scan-agg, Q3 join-agg — bench.py's exact SQL, so the serving and
#: single-query benches measure the same fragments)
OLAP_QUERIES = ("q1", "q3")

#: chaos catalog for --chaos runs: the threaded-chaos failure families
#: (hang + OOM + admission) at serving-friendly rates
CHAOS_FAULTS = {
    "device-agg-exec": ["1*panic", "sleep(0.05)"],
    "device-join-exec": ["1*panic", "sleep(0.05)"],
    "device-upload-oom": ["1*oom", "2*oom", "oom"],
    "device-admission": ["admission-queue-full", "1*admission-wait(0.05)",
                         "2*admission-wait(0.02)"],
    "txn-before-commit": ["1*panic"],
    "txn-before-prewrite": ["1*panic"],
}

_EMIT_LOCK = threading.Lock()


def _emit(obj) -> None:
    with _EMIT_LOCK:
        print(json.dumps(obj), flush=True)


def _is_clean(err: Exception) -> bool:
    return isinstance(err, (TiDBError, FailpointError))


def _pctl(sorted_vals, q: float):
    if not sorted_vals:
        return None
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return round(sorted_vals[i], 2)


def _setup(sf: float) -> tuple:
    """One Domain: TPC-H tables at `sf` (tpch db) + the transfer ledger
    (test db).  Returns (tk, goldens) — goldens are the fault-free HOST
    engine results for the analytical corpus."""
    tk = TestKit()
    failpoint.disable_all()
    bench.gen_all(tk, sf)
    tk.must_exec("use test")
    tk.must_exec("create table ledger (acct int primary key, bal int)")
    tk.must_exec("insert into ledger values " + ",".join(
        f"({i}, {SEED_BAL})" for i in range(1, N_ACCTS + 1)))
    tk.must_exec("use tpch")
    tk.must_exec("set tidb_executor_engine = 'host'")
    goldens = {q: tuple(map(tuple, tk.must_query(bench.QUERIES[q]).rows))
               for q in OLAP_QUERIES}
    tk.must_exec("set tidb_executor_engine = 'auto'")
    return tk, goldens


def run_serve(n_threads: int = 8, n_ops: int = 20, sf: float = 0.01,
              seed: int = 0, chaos: bool = False, emit=_emit) -> dict:
    """Drive the serving workload; returns the summary dict (also
    emitted as JSON lines).  Raises AssertionError on any invariant
    violation — tests call this in-process, the CLI exits 1."""
    from tidb_tpu.executor import scheduler, supervisor
    from tidb_tpu.ops import residency

    tk, goldens = _setup(sf)
    t_start = time.monotonic()

    mu = threading.Lock()
    lat = {}          # group -> [latency_ms]
    counts = {"ok": 0, "clean_errors": 0, "writes_ok": 0,
              "writes_failed": 0}
    violations: list = []
    start = threading.Barrier(n_threads)

    def record(group, ms):
        with mu:
            lat.setdefault(group, []).append(ms)

    def bump(key):
        with mu:
            counts[key] += 1

    def violate(tid, what, exc=None, conn_id=None):
        # a violation's post-mortem: the OFFENDING session's most recent
        # finished span trace (conn_id-filtered — with N concurrent
        # workers, a healthy thread's timeline must never be
        # misattributed to the failure), when the run samples
        from tidb_tpu.session import tracing
        trace = tracing.last_trace_text(conn_id, cap=2000)
        with mu:
            violations.append(
                f"thread {tid}: {what}"
                + (f" ({type(exc).__name__}: {exc})" if exc else "")
                + (("\n" + trace) if trace else ""))

    def _olap_op(wtk, rng, tid):
        qname = OLAP_QUERIES[rng.randrange(len(OLAP_QUERIES))]
        t0 = time.monotonic()
        try:
            rows = tuple(map(tuple,
                             wtk.must_query(bench.QUERIES[qname]).rows))
        except Exception as e:  # noqa: BLE001 — classification IS the check
            if _is_clean(e):
                bump("clean_errors")
            else:
                violate(tid, f"unclassified analytical failure on "
                        f"{qname}", e, conn_id=wtk.session.conn_id)
            return
        record("olap", (time.monotonic() - t0) * 1000.0)
        bump("ok")
        if rows != goldens[qname]:
            violate(tid, f"WRONG RESULT for {qname} (device path diverged"
                    " from host golden)", conn_id=wtk.session.conn_id)

    def _oltp_op(wtk, rng, tid):
        kind = rng.random()
        t0 = time.monotonic()
        try:
            if kind < 0.45:  # point read
                acct = rng.randrange(1, N_ACCTS + 1)
                wtk.must_query(
                    f"select bal from ledger where acct = {acct}")
            elif kind < 0.65:  # ledger-sum snapshot (atomicity check)
                total = wtk.must_query(
                    "select sum(bal) from ledger").rows[0][0]
                if str(total) != str(LEDGER_TOTAL):
                    violate(tid, f"ATOMICITY VIOLATION: ledger sum "
                            f"{total} != {LEDGER_TOTAL}")
            else:  # transfer write (acct order: no deadlock cycles)
                a, b = sorted(rng.sample(range(1, N_ACCTS + 1), 2))
                amt = rng.randrange(1, 40)
                wtk.must_exec("begin")
                wtk.must_exec(
                    f"update ledger set bal = bal - {amt} where acct={a}")
                wtk.must_exec(
                    f"update ledger set bal = bal + {amt} where acct={b}")
                wtk.must_exec("commit")
                bump("writes_ok")
        except Exception as e:  # noqa: BLE001
            if _is_clean(e):
                bump("clean_errors")
                if kind >= 0.65:
                    with mu:
                        counts["writes_failed"] += 1
                        counts["clean_errors"] -= 1
                try:
                    wtk.session.rollback()
                except Exception:
                    pass
            else:
                violate(tid, "unclassified OLTP failure", e,
                        conn_id=wtk.session.conn_id)
            return
        record("oltp", (time.monotonic() - t0) * 1000.0)
        bump("ok")

    def worker(tid):
        try:
            _worker_body(tid)
        except Exception as e:  # noqa: BLE001 — a dead worker IS a finding
            violate(tid, "worker thread died", e)

    def _worker_body(tid):
        rng = random.Random((seed << 8) ^ tid)
        olap = tid % 2 == 0  # even threads analytical, odd threads OLTP
        wtk = tk.new_session()
        group = "olap" if olap else "oltp"
        wtk.must_exec(f"set tidb_resource_group = '{group}'")
        if os.environ.get("BENCH_TRACE", "") == "1":
            # opt-in, same BENCH_TRACE=1 gate as bench.py: the serving
            # bench measures contended p99s, and N threads × sampling
            # every op would skew exactly the latencies under test
            wtk.must_exec("set tidb_trace_sampling_rate = 1")
        wtk.must_exec("set innodb_lock_wait_timeout = 2")
        if olap:
            wtk.must_exec("use tpch")
            # analytical tenants force the device engine: they are the
            # traffic the admission queue exists to schedule
            wtk.must_exec("set tidb_executor_engine = 'tpu'")
        else:
            wtk.must_exec("use test")
        start.wait(timeout=60)
        for _op in range(n_ops):
            with contextlib.ExitStack() as st:
                if chaos:
                    # half the ops run supervised with a deadline smaller
                    # than the injected sleeps: the hang path fires live
                    wtk.must_exec("set tidb_device_call_timeout = "
                                  + ("0.02" if rng.random() < 0.5 else "0"))
                    if rng.random() < 0.5:
                        for name in rng.sample(sorted(CHAOS_FAULTS),
                                               k=rng.choice([1, 1, 2])):
                            st.enter_context(failpoint.enabled(
                                name, rng.choice(CHAOS_FAULTS[name])))
                if olap:
                    _olap_op(wtk, rng, tid)
                else:
                    _oltp_op(wtk, rng, tid)

    threads = [threading.Thread(target=worker, args=(tid,), daemon=True,
                                name=f"serve-{tid}")
               for tid in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300.0)
    stuck = [t.name for t in threads if t.is_alive()]
    failpoint.disable_all()
    wall_s = time.monotonic() - t_start

    # -- invariants ----------------------------------------------------------
    assert not stuck, f"STUCK CLIENT THREADS: {stuck}"
    assert not violations, "\n".join(violations)
    tk.must_exec("use test")
    tk.must_exec("set tidb_executor_engine = 'host'")
    total = tk.must_query("select sum(bal) from ledger").rows[0][0]
    assert str(total) == str(LEDGER_TOTAL), (
        f"final ledger sum {total} != {LEDGER_TOTAL}")
    # abandoned supervised calls drain (chaos hangs are short sleeps),
    # then the admission queue must show zero leaked tickets
    deadline = time.monotonic() + 15.0
    while ((supervisor.abandoned_calls() > 0
            or not scheduler.verify_drained()["ok"])
           and time.monotonic() < deadline):
        time.sleep(0.01)
    drained = scheduler.verify_drained()
    assert drained["ok"], f"LEAKED ADMISSION TICKETS: {drained}"
    led = residency.verify_ledger()
    assert led["ok"], f"HBM LEDGER DRIFT: {led}"

    # -- report --------------------------------------------------------------
    n_queries = counts["ok"]
    sched = scheduler.snapshot()
    summary = {
        "threads": n_threads, "ops_per_thread": n_ops, "sf": sf,
        "seed": seed, "chaos": chaos, "wall_s": round(wall_s, 2),
        "qps": round(n_queries / wall_s, 2) if wall_s > 0 else 0.0,
        **counts,
        "violations": 0,
    }
    emit({"metric": "serve_clients", "value": n_threads,
          "unit": "threads", "chaos": chaos, "sf": sf, "seed": seed})
    for group, vals in sorted(lat.items()):
        vals.sort()
        emit({"metric": "serve_latency_ms", "group": group,
              "p50": _pctl(vals, 0.50), "p99": _pctl(vals, 0.99),
              "n": len(vals)})
        summary[f"p50_{group}"] = _pctl(vals, 0.50)
        summary[f"p99_{group}"] = _pctl(vals, 0.99)
    emit({"metric": "serve_qps", "value": summary["qps"],
          "unit": "queries/s", "threads": n_threads,
          "wall_s": summary["wall_s"], "ok": counts["ok"],
          "clean_errors": counts["clean_errors"],
          "writes_ok": counts["writes_ok"],
          "writes_failed": counts["writes_failed"]})
    emit({"metric": "serve_sched",
          "sched_queue_depth": sched["sched_queue_depth"],
          "sched_admission_waits_ms": sched["sched_admission_waits_ms"],
          "sched_batched_fragments": sched["sched_batched_fragments"],
          "sched_degradations": sched["degradations_by_group"],
          "admitted": sched["admitted"], "queued": sched["queued"],
          "rejected_full": sched["rejected_full"],
          "rejected_timeout": sched["rejected_timeout"],
          "rejected_injected": sched["rejected_injected"],
          "hbm_bytes_cached": residency.resident_bytes(),
          "supervisor_hangs": supervisor.snapshot()["hangs"]})
    # compile-service attribution (executor/compile_service.py): how much
    # compile the serving run paid on the query path vs in the background
    # pool, plus the pending/persist/prewarm counters — a chaos run with
    # injected compile faults also reports bg_failed here
    from tidb_tpu.executor import compile_service
    from tidb_tpu.executor.device_exec import pipe_cache_stats
    ps = pipe_cache_stats()
    emit({"metric": "serve_compile",
          "sync_compile_s": round(ps["compile_s"], 4),
          "bg_compile_s": round(ps["bg_compile_s"], 4),
          **compile_service.report_gauges()})
    summary.update({k: sched[k] for k in
                    ("admitted", "queued", "sched_batched_fragments",
                     "rejected_full", "rejected_timeout",
                     "rejected_injected")})
    summary["degradations_by_group"] = sched["degradations_by_group"]
    summary["sync_compile_s"] = round(ps["compile_s"], 4)
    summary["bg_compile_s"] = round(ps["bg_compile_s"], 4)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--ops", type=int, default=20,
                    help="operations per client thread")
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chaos", action="store_true",
                    help="run under the seeded chaos catalog "
                         "(hang + OOM + admission failpoints)")
    ap.add_argument("--smoke", action="store_true",
                    help="small fixed-seed run for CI (8 threads, "
                         "tiny SF, chaos on)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.threads, args.ops, args.sf, args.chaos = 8, 4, 0.002, True
    try:
        run_serve(n_threads=args.threads, n_ops=args.ops, sf=args.sf,
                  seed=args.seed, chaos=args.chaos)
    except AssertionError as e:
        _emit({"metric": "serve_violation", "error": str(e)[:2000]})
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
