"""Durable shared store (ISSUE 15): WAL framing + group commit +
checkpoint/truncation units, the crash-recovery matrix (SIGKILL at every
WAL/2PC stage failpoint → reopen → committed-visible / uncommitted-gone
/ torn-tail-CRC-truncated), fleet coherence over one log (shared lock
table, cross-replica visibility, schema cell, fleet GC floor), the
oracle-abstraction satellite, and the BR wal-tail round trip."""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from tidb_tpu.errors import LockedError
from tidb_tpu.kv import new_store, wal as wal_mod
from tidb_tpu.kv.mvcc import MVCCStore, TSOracle
from tidb_tpu.kv.shared_store import (DurableMVCCStore, SegmentTSOracle,
                                      key_hash)
from tidb_tpu.kv.store import Storage
from tidb_tpu.utils import failpoint


@pytest.fixture()
def wal_dir(tmp_path):
    return str(tmp_path / "wal")


def _mk_storage(engine) -> Storage:
    s = Storage.__new__(Storage)
    s.mvcc = engine
    s.backend = type(engine).__name__
    s._lock = threading.Lock()
    return s


# -- WAL unit layer -----------------------------------------------------------

class TestWalFraming:
    def test_append_read_roundtrip(self, wal_dir):
        w = wal_mod.WAL(wal_dir)
        l1 = w.append(("raw", -1, 7, [(b"a", b"1")], []))
        l2 = w.append(("rollback", -1, 9, [b"b"]))
        assert l2 > l1 > 0
        recs = list(w.read_records(w.base_lsn))
        assert [r[0][0] for r in recs] == ["raw", "rollback"]
        assert recs[-1][1] == l2
        w.close()

    def test_torn_tail_truncated_at_crc(self, wal_dir):
        w = wal_mod.WAL(wal_dir)
        good = w.append(("raw", -1, 1, [(b"k", b"v")], []))
        # torn frame: a header promising more bytes than exist
        w._f.seek(0, os.SEEK_END)
        w._f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00half")
        w._f.flush()
        assert w.scan_valid_end() == good
        torn = w.truncate_torn_tail()
        assert torn > 0
        assert list(w.read_records(w.base_lsn))[-1][1] == good
        w.close()

    def test_crc_corruption_stops_replay(self, wal_dir):
        w = wal_mod.WAL(wal_dir)
        l1 = w.append(("raw", -1, 1, [(b"k", b"v")], []))
        w.append(("raw", -1, 2, [(b"k2", b"v2")], []))
        # flip one payload byte of the SECOND record
        off = l1 - w.base_lsn + 16 + 8 + 3
        w._f.seek(off)
        b = w._f.read(1)
        w._f.seek(off)
        w._f.write(bytes([b[0] ^ 0xFF]))
        w._f.flush()
        assert w.scan_valid_end() == l1  # corrupt record excluded
        w.close()

    def test_checkpoint_truncates_and_replays(self, wal_dir):
        st = new_store(wal_dir=wal_dir)
        t = st.begin(); t.put(b"k1", b"v1"); t.commit()
        lsn = st.mvcc.wal.checkpoint(st.mvcc.dump_state())
        assert st.mvcc.wal.base_lsn == lsn  # tail truncated (solo)
        t = st.begin(); t.put(b"k2", b"v2"); t.commit()
        st.close()
        st2 = new_store(wal_dir=wal_dir)
        snap = st2.get_snapshot()
        assert snap.get(b"k1") == b"v1"
        assert snap.get(b"k2") == b"v2"
        st2.close()

    def test_group_commit_policies(self, wal_dir, tmp_path):
        wal_mod.reset_for_tests()
        st = new_store(wal_dir=wal_dir)
        st.mvcc.wal.policy_source = lambda: "never"
        t = st.begin(); t.put(b"a", b"1"); t.commit()
        assert wal_mod.snapshot()["wal_fsyncs"] == 0
        st.mvcc.wal.policy_source = lambda: "commit"
        t = st.begin(); t.put(b"b", b"2"); t.commit()
        assert wal_mod.snapshot()["wal_fsyncs"] >= 1
        st.mvcc.wal.policy_source = lambda: "interval"
        t = st.begin(); t.put(b"c", b"3"); t.commit()
        deadline = time.monotonic() + 2.0
        base = wal_mod.snapshot()["wal_fsyncs"]
        while (wal_mod.snapshot()["wal_fsyncs"] <= base
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert wal_mod.snapshot()["wal_fsyncs"] > base  # bg flusher ran
        st.close()

    def test_group_commit_shares_fsyncs_across_threads(self, wal_dir):
        wal_mod.reset_for_tests()
        st = new_store(wal_dir=wal_dir)
        st.mvcc.wal.policy_source = lambda: "commit"

        def committer(i):
            t = st.begin()
            t.put(b"gk%d" % i, b"v")
            t.commit()

        threads = [threading.Thread(target=committer, args=(i,))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(30)
        s = wal_mod.snapshot()
        # every commit either fsynced or rode a peer's group fsync; the
        # group protocol must have produced at least one shared ride OR
        # at most one fsync per commit (no double syncs)
        assert s["wal_fsyncs"] + s["wal_group_commits"] >= 8 \
            or s["wal_fsyncs"] <= 8
        snap = st.get_snapshot()
        for i in range(8):
            assert snap.get(b"gk%d" % i) == b"v"
        st.close()

    def test_fsync_failure_rolls_back_cleanly(self, wal_dir):
        st = new_store(wal_dir=wal_dir)
        t = st.begin(); t.put(b"base", b"1"); t.commit()
        with failpoint.enabled("wal-fsync-fail", "1*panic"):
            t = st.begin()
            t.put(b"doomed", b"x")
            with pytest.raises(Exception):
                t.commit()
        assert st.get_snapshot().get(b"doomed") is None
        # last-disposition-wins: recovery agrees with the live store
        st2 = new_store(wal_dir=wal_dir)
        assert st2.get_snapshot().get(b"doomed") is None
        assert st2.get_snapshot().get(b"base") == b"1"
        st2.close()
        st.close()

    def test_fsync_eio_retried_within_budget(self, wal_dir):
        """ISSUE 16 satellite: a TRANSIENT fsync error (one EIO) gets
        one budgeted Backoffer retry (kind walSyncRetry) before the
        owner aborts — the commit succeeds, and the stats show exactly
        one error absorbed by one retry."""
        wal_mod.reset_for_tests()
        st = new_store(wal_dir=wal_dir)
        st.mvcc.wal.policy_source = lambda: "commit"
        with failpoint.enabled("wal-fsync-fail", "1*return(eio)"):
            t = st.begin()
            t.put(b"survives", b"v")
            t.commit()          # the retry absorbs the EIO
        assert st.get_snapshot().get(b"survives") == b"v"
        s = wal_mod.snapshot()
        assert s["wal_fsync_errors"] >= 1, s
        assert s["wal_fsync_retries"] >= 1, s
        # durable for real: recovery sees the retried commit
        st2 = new_store(wal_dir=wal_dir)
        assert st2.get_snapshot().get(b"survives") == b"v"
        st2.close()
        st.close()

    def test_fsync_eio_persistent_aborts_cleanly(self, wal_dir):
        """A PERSISTENT fsync failure exhausts the walSyncRetry budget
        and aborts the txn with the original OSError — never an ack on
        storage that cannot sync, and recovery agrees the row is gone."""
        wal_mod.reset_for_tests()
        st = new_store(wal_dir=wal_dir)
        st.mvcc.wal.policy_source = lambda: "commit"
        t = st.begin(); t.put(b"base", b"1"); t.commit()
        with failpoint.enabled("wal-fsync-fail", "return(eio)"):
            t = st.begin()
            t.put(b"doomed", b"x")
            with pytest.raises(OSError):
                t.commit()
        assert st.get_snapshot().get(b"doomed") is None
        assert wal_mod.snapshot()["wal_fsync_errors"] >= 2
        st2 = new_store(wal_dir=wal_dir)
        assert st2.get_snapshot().get(b"doomed") is None
        assert st2.get_snapshot().get(b"base") == b"1"
        st2.close()
        st.close()

    def test_torn_append_heals_in_process(self, wal_dir):
        st = new_store(wal_dir=wal_dir)
        with failpoint.enabled("wal-append-torn", "1*return(torn)"):
            t = st.begin()
            t.put(b"doomed", b"x")
            with pytest.raises(Exception):
                t.commit()
        # the torn bytes were healed: later appends land on a clean tail
        t = st.begin(); t.put(b"after", b"1"); t.commit()
        st2 = new_store(wal_dir=wal_dir)
        assert st2.get_snapshot().get(b"after") == b"1"
        assert st2.get_snapshot().get(b"doomed") is None
        st2.close()
        st.close()


# -- the oracle abstraction satellite ----------------------------------------

class TestOracleAbstraction:
    def test_injected_oracle_feeds_raw_put_python(self):
        class Fixed:
            def __init__(self):
                self.n = 1000

            def next_ts(self):
                self.n += 1
                return self.n

        eng = MVCCStore(oracle=(o := Fixed()))
        eng.raw_put(b"k", b"v")
        assert o.n == 1001  # raw_put's self-allocated ts used the oracle
        assert eng.map.read(b"k", 1 << 62) == (0, b"v")

    def test_injected_oracle_feeds_raw_put_native(self):
        from tidb_tpu.kv.native import NativeMVCCStore, load_engine
        if load_engine() is None:
            pytest.skip("no native toolchain")

        class Fixed:
            def __init__(self):
                self.n = 5000

            def next_ts(self):
                self.n += 1
                return self.n

        eng = NativeMVCCStore(oracle=(o := Fixed()))
        eng.raw_put(b"k", b"v")
        assert o.n == 5001

    def test_advance_to_keeps_monotonic(self):
        o = TSOracle()
        ts = o.next_ts()
        o.advance_to(ts + (5 << 18))
        assert o.next_ts() > ts + (5 << 18)

    def test_segment_oracle_fleet_monotonic(self, tmp_path):
        from tidb_tpu.fabric.coord import Coordinator
        c = Coordinator.create(str(tmp_path / "c.json"), nslots=2)
        try:
            o1, o2 = SegmentTSOracle(c, batch=4), SegmentTSOracle(c, batch=4)
            seen = [o1.next_ts() for _ in range(10)]
            seen += [o2.next_ts() for _ in range(10)]
            assert len(set(seen)) == 20  # never a collision
            # advance_to pushes past a foreign commit even mid-lease
            hi = max(seen) + 100
            o1.advance_to(hi)
            assert o1.next_ts() > hi
        finally:
            c.unlink()


# -- crash-recovery matrix (SIGKILL at each stage, real processes) -----------

_CHILD = r"""
import json, sys
from tidb_tpu.utils import failpoint
from tidb_tpu.kv import new_store

wal_dir, stage = sys.argv[1], sys.argv[2]
st = new_store(wal_dir=wal_dir)
for i in range(4):
    t = st.begin()
    t.put(b"k%d" % i, b"v%d" % i)
    t.commit()
    print(json.dumps({"acked": i}), flush=True)
failpoint.enable(stage, "1*return(kill)")
t = st.begin()
t.put(b"doomed", b"x")
t.commit()  # SIGKILL fires at the armed stage
print(json.dumps({"acked": "doomed"}), flush=True)
"""

_RECOVER_CHILD = r"""
import sys
from tidb_tpu.utils import failpoint
from tidb_tpu.kv import new_store

failpoint.enable("store-recover-replay", "2*return(kill)")
new_store(wal_dir=sys.argv[1])  # SIGKILL mid-replay
print("survived")
"""

#: stages strictly BEFORE the commit record reaches the log: the dying
#: txn must be GONE after recovery.  wal-fsync-fail kills after the
#: record is written (ambiguity window: present-and-complete or absent
#: are both legal; the client never got an ack either way).
_PRE_COMMIT_STAGES = ("txn-before-prewrite", "txn-after-prewrite",
                      "txn-before-commit", "wal-append-torn")


@pytest.mark.chaos
class TestCrashRecoveryMatrix:
    @pytest.mark.parametrize("stage", [
        "txn-before-prewrite", "txn-after-prewrite", "txn-before-commit",
        "wal-append-torn", "wal-fsync-fail"])
    def test_kill_at_stage_then_recover(self, stage, tmp_path):
        wal_dir = str(tmp_path / "wal")
        r = subprocess.run(
            [sys.executable, "-c", _CHILD, wal_dir, stage],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=300)
        assert r.returncode == -9, (r.returncode, r.stderr[-1000:])
        acked = [json.loads(l)["acked"]
                 for l in r.stdout.strip().splitlines() if l.strip()]
        assert acked == [0, 1, 2, 3], acked  # doomed never acked
        wal_mod.reset_for_tests()
        st = new_store(wal_dir=wal_dir)
        snap = st.get_snapshot()
        # every ACKED commit survived the SIGKILL
        for i in acked:
            assert snap.get(b"k%d" % i) == b"v%d" % i, (stage, i)
        doomed = snap.get(b"doomed")
        if stage in _PRE_COMMIT_STAGES:
            assert doomed is None, (
                f"{stage}: un-acked txn visible after recovery")
        else:
            assert doomed in (None, b"x")
        if stage == "wal-append-torn":
            # the half-written commit record was CRC-truncated
            assert wal_mod.snapshot()["wal_truncated_records"] >= 1
        # no orphaned locks survive recovery (resolve-via-primary ran)
        assert not st.mvcc.locks, st.mvcc.locks
        st.close()

    def test_kill_mid_recovery_is_idempotent(self, tmp_path):
        wal_dir = str(tmp_path / "wal")
        st = new_store(wal_dir=wal_dir)
        for i in range(4):
            t = st.begin()
            t.put(b"r%d" % i, b"v%d" % i)
            t.commit()
        st.close()
        r = subprocess.run(
            [sys.executable, "-c", _RECOVER_CHILD, wal_dir],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=300)
        assert r.returncode == -9, (r.returncode, r.stderr[-800:])
        st2 = new_store(wal_dir=wal_dir)  # recovery restarts cleanly
        snap = st2.get_snapshot()
        for i in range(4):
            assert snap.get(b"r%d" % i) == b"v%d" % i
        st2.close()


# -- fleet coherence over one log (two replicas in one process) --------------

class _Replicas:
    def __init__(self, tmp_path, nslots=4):
        from tidb_tpu.fabric.coord import Coordinator
        self.c0 = Coordinator.create(str(tmp_path / "coord.json"),
                                     nslots=nslots)
        self.c1 = Coordinator.attach(str(tmp_path / "coord.json"))
        self.c0.claim_slot(0)
        self.c1.claim_slot(1)
        self.wal_dir = str(tmp_path / "wal")
        self.s0 = self._mk(self.c0, 0)
        self.s1 = self._mk(self.c1, 1)

    def _mk(self, coord, slot):
        w = wal_mod.WAL(self.wal_dir, coordinator=coord)
        eng = DurableMVCCStore(w, coordinator=coord, slot=slot,
                               oracle=SegmentTSOracle(coord))
        eng.recover()
        return _mk_storage(eng)

    def close(self):
        self.s0.close()
        self.s1.close()
        self.c1.close()
        self.c0.unlink()


@pytest.fixture()
def replicas(tmp_path):
    r = _Replicas(tmp_path)
    yield r
    r.close()


class TestFleetCoherence:
    def test_commit_visible_on_sibling_snapshot(self, replicas):
        t = replicas.s0.begin()
        t.put(b"x", b"w0")
        t.commit()
        # the sibling's NEXT snapshot catches up synchronously
        assert replicas.s1.get_snapshot().get(b"x") == b"w0"

    def test_concurrent_prewrite_conflicts_via_shared_locks(self, replicas):
        ta = replicas.s0.begin()
        tb = replicas.s1.begin()
        ta.put(b"y", b"a")
        tb.put(b"y", b"b")
        # drive ta through prewrite ONLY (hold the shared claim)
        muts = [(b"y", 0, b"a")]
        replicas.s0.mvcc.prewrite(muts, b"y", ta.start_ts)
        with pytest.raises(LockedError):
            replicas.s1.mvcc.prewrite([(b"y", 0, b"b")], b"y", tb.start_ts)
        # release via rollback; the sibling can then claim
        replicas.s0.mvcc.rollback([b"y"], ta.start_ts)
        replicas.s1.mvcc.prewrite([(b"y", 0, b"b")], b"y", tb.start_ts)
        replicas.s1.mvcc.commit([b"y"], tb.start_ts,
                                replicas.s1.next_ts())
        assert replicas.s0.get_snapshot().get(b"y") == b"b"
        assert not replicas.c0.verify_drained()["held_locks"]

    def test_dead_slot_lock_claims_reclaimed(self, replicas):
        replicas.s0.mvcc.prewrite([(b"z", 0, b"v")], b"z", 12345)
        assert replicas.c0.snapshot()["held_locks"] >= 1
        time.sleep(0.02)
        replicas.c1.reclaim_expired(0.01)  # slot 0's lease lapsed
        assert replicas.c1.snapshot()["held_locks"] == 0

    def test_schema_cell_published_on_meta_commit(self, replicas):
        t = replicas.s0.begin()
        t.put(b"m:schema_version", json.dumps(7).encode())
        t.commit()
        assert replicas.c1.schema_version() == 7
        assert replicas.s1.mvcc.fleet_schema_version() == 7

    def test_min_read_ts_floors_fleet_gc(self, replicas):
        replicas.c0.set_min_read_ts(0, 500)
        replicas.c1.set_min_read_ts(1, 300)
        assert replicas.c0.fleet_min_read_ts() == 300
        replicas.c1.set_min_read_ts(1, 0)
        assert replicas.c0.fleet_min_read_ts() == 500
        replicas.c0.set_min_read_ts(0, 0)
        assert replicas.c0.verify_drained()["min_read_pinned"] == []

    def test_tailer_applies_in_background(self, replicas):
        replicas.s1.mvcc.start_tailer()
        t = replicas.s0.begin()
        t.put(b"bg", b"tail")
        t.commit()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if replicas.s1.mvcc.map.read(b"bg", 1 << 62) is not None:
                break
            time.sleep(0.01)
        assert replicas.s1.mvcc.map.read(b"bg", 1 << 62) == (0, b"tail")

    def test_append_survives_peer_truncation_rewrite(self, replicas):
        """A peer's checkpoint truncation rewrites wal.log (os.replace):
        an appender still holding the OLD inode must revalidate under
        the flock, or its acked commit lands in an unlinked file no
        reader can ever see."""
        t = replicas.s0.begin()
        t.put(b"pre", b"1")
        t.commit()
        replicas.s1.mvcc.catch_up()
        # replica 1 checkpoints + truncates: wal.log is a NEW inode now
        replicas.s1.mvcc.wal.checkpoint(replicas.s1.mvcc.dump_state())
        # replica 0 appends WITHOUT any explicit reopen
        t = replicas.s0.begin()
        t.put(b"post", b"2")
        t.commit()
        # a fresh reader over the path sees the post-truncation commit
        w = wal_mod.WAL(replicas.wal_dir)
        kinds = [r[0][0] for r in w.read_records(w.base_lsn)]
        w.close()
        assert "commit" in kinds, kinds
        replicas.s1.mvcc.catch_up()
        assert replicas.s1.mvcc.map.read(b"post", 1 << 62) == (0, b"2")

    def test_truncation_floor_respects_stalled_claimed_slot(self, tmp_path):
        """min_wal_applied gates on CLAIMED slots regardless of lease
        age: a stalled-but-alive worker must not be truncated past."""
        from tidb_tpu.fabric.coord import Coordinator
        c = Coordinator.create(str(tmp_path / "c.json"), nslots=4)
        try:
            c.claim_slot(0)
            c.claim_slot(1)
            c.set_wal_applied(0, 1000)
            c.set_wal_applied(1, 400)
            time.sleep(0.02)
            c.heartbeat(0)  # slot 1's lease is now stale, slot 0 fresh
            assert c.min_wal_applied() == 400  # the stalled slot gates
            c.release_slot(1)  # genuinely dead: reclaimed, stops gating
            assert c.min_wal_applied() == 1000
        finally:
            c.unlink()

    def test_rawdel_after_backup_ts_not_in_tail(self, tmp_path):
        """A delete-range racing past the backup snapshot must be
        EXCLUDED from the shipped tail (its rows are in the backup)."""
        from tidb_tpu.kv.shared_store import _record_ts
        st = new_store(wal_dir=str(tmp_path / "wal"))
        st.mvcc.raw_put(b"t1", b"v")
        cut = st.next_ts()
        st.mvcc.raw_delete_range(b"t0", b"t9")  # after the "backup"
        recs = [r for r, _l in st.mvcc.wal.read_records(
            st.mvcc.wal.base_lsn)]
        dels = [r for r in recs if r[0] == "rawdel"]
        assert dels and _record_ts(dels[0]) > cut
        assert [r for r in recs
                if r[0] == "raw" and _record_ts(r) <= cut]
        st.close()

    def test_orphaned_prewrite_resolved_via_primary(self, tmp_path):
        # craft a log where txn A prewrote but never committed, and txn
        # B prewrote AND committed: recovery must roll A back and
        # commit B's leftovers (the Percolator primary rule)
        wal_dir = str(tmp_path / "wal")
        w = wal_mod.WAL(wal_dir)
        w.append(("prewrite", -1, 100, b"a", [(b"a", 0, b"va")]))
        w.append(("prewrite", -1, 200, b"b", [(b"b", 0, b"vb")]))
        w.append(("commit", -1, 200, 201, [b"b"], []))
        w.close()
        st = new_store(wal_dir=wal_dir)
        assert st.get_snapshot().get(b"b") == b"vb"
        assert st.get_snapshot().get(b"a") is None
        assert not st.mvcc.locks  # A rolled back, nothing orphaned
        st.close()


# -- BR integration -----------------------------------------------------------

class TestBrWalTail:
    def test_backup_ships_tail_and_restore_replays_to_ts(self, tmp_path):
        from tidb_tpu.session import bootstrap_domain, new_session
        from tidb_tpu import br
        wal_dir = str(tmp_path / "wal")
        dom = bootstrap_domain(new_store(wal_dir=wal_dir))
        s = new_session(dom)
        s.execute("use test")
        s.execute("create table bt (id int primary key, v int)")
        s.execute("insert into bt values (1, 10), (2, 20)")
        dest = f"local://{tmp_path}/bk"
        meta = br.backup_database(s, "test", dest)
        assert meta["wal"] is not None
        assert meta["wal"]["tail_records"] > 0
        # a LATER commit must not leak into the tail replay
        s.execute("insert into bt values (3, 30)")
        fresh = new_store(wal_dir=str(tmp_path / "wal2"))
        n = br.restore_wal_tail(fresh, dest)
        assert n == meta["wal"]["tail_records"]
        # the replayed store holds the backup-ts rows, not the late one
        live = {k: v for k, v in fresh.get_snapshot().scan(b"", b"")
                if k.startswith(b"t")}
        src = {k: v for k, v in dom.store.get_snapshot(
            meta["ts"]).scan(b"", b"") if k.startswith(b"t")}
        assert live == src
        fresh.close()
        dom.store.close()


class TestWalGauges:
    def test_status_and_metrics_surfaces(self, tmp_path):
        from tidb_tpu.server.http_status import StatusServer
        from tidb_tpu.session import bootstrap_domain
        dom = bootstrap_domain(new_store(wal_dir=str(tmp_path / "wal")))
        srv = StatusServer(dom, port=0)
        try:
            payload = srv._status()
            assert payload["storage_wal"]["wal_appends"] > 0
            assert "applied_lsn" in payload["storage_wal"]
            text = srv._metrics()
            assert "wal_appends " in text or "wal_appends{" in text
        finally:
            # never start()ed: shutdown() would block waiting for the
            # serve loop to acknowledge — just release the socket
            srv._server.server_close()
        dom.store.close()

    def test_report_gauges_empty_without_wal(self):
        wal_mod.reset_for_tests()
        assert wal_mod.report_gauges() == {}
