"""Gauge consistency: every gauge a subsystem publishes into the observe
registry must also be SURFACED on the two human-facing planes —

  * EXPLAIN ANALYZE annotations (executor/exec_select.py ``annotate``,
    directly or via a splatted ``report_gauges()``), and
  * the HTTP status port (server/http_status.py ``/status`` via the
    module ``snapshot()`` payloads; ``/metrics`` re-exports every observe
    gauge generically, so publishing alone covers it).

A gauge visible in /metrics but absent from EXPLAIN ANALYZE (or vice
versa) is how the PR 5-8 observability drifted name-by-name; this rule
pins the set statically.

Published names are collected from (a) literal first args of
``set_gauge`` calls, (b) literal dict keys / f-string prefixes /
subscript stores inside functions named ``_publish_gauges`` or
``report_gauges``.  Label-style suffixes (``sched_degradations:<group>``)
are normalized to their base name.
"""

from __future__ import annotations

import ast

from ..engine import Rule, register
from ._util import call_name, const_str

PUBLISH_FNS = ("_publish_gauges", "report_gauges")
STATUS_REL = "server/http_status.py"

#: fleet-era observability inventory (ISSUE 18): counters and
#: perf-store fields that flow through the fabric snapshot()/stats()
#: payloads rather than set_gauge / report_gauges, so the inference
#: below cannot see them.  Each name must appear as a literal BOTH in
#: its publishing module and in server/http_status.py — adding a field
#: to one side without the other is exactly the name-by-name drift this
#: rule exists to stop.
FLEET_INVENTORY = {
    "fabric/state.py": (
        "fabric_workers", "fabric_respawns", "fabric_dedup_hits",
        "fabric_compile_rtt_ms", "fleet_cache_hits",
        "fabric_perf_rows", "fabric_perf_samples",
        # fleet-frontier freshness (ISSUE 19): bumped by
        # kv/shared_store.fresh_read_ts, surfaced via report_gauges
        # (EXPLAIN ANALYZE) and /metrics
        "freshness_waits", "freshness_timeouts", "freshness_stale_ok"),
    "fabric/perf.py": ("perf_notes", "perf_merged"),
    # the span-ring eviction counter behind trace_ring_dropped_total
    "session/tracing.py": ("ring_dropped",),
}


def _base(name: str) -> str:
    return name.split(":", 1)[0]


def _fn_string_keys(fn: ast.AST) -> set:
    """Gauge-name candidates inside a publish/report function: dict keys,
    f-string key prefixes, literal subscript stores."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                s = const_str(k)
                if s:
                    out.add(s)
                elif isinstance(k, ast.JoinedStr) and k.values:
                    first = k.values[0]
                    if (isinstance(first, ast.Constant)
                            and isinstance(first.value, str)):
                        out.add(first.value.rstrip(":"))
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Store)):
            s = const_str(node.slice)
            if s:
                out.add(s)
    return out


def _module_fn_literals(sf, fn_names) -> set:
    """All string literals inside the named top-level functions of sf."""
    out = set()
    for node in sf.tree.body:
        if (isinstance(node, ast.FunctionDef) and node.name in fn_names):
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)):
                    out.add(sub.value)
                elif isinstance(sub, ast.JoinedStr):
                    for v in sub.values:
                        if (isinstance(v, ast.Constant)
                                and isinstance(v.value, str)):
                            out.add(v.value.rstrip(":"))
    return out


def _referenced_modules(sf) -> set:
    """Module local-names whose report_gauges()/snapshot() sf calls."""
    mods = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if "." in name and name.rsplit(".", 1)[-1] in (
                    "report_gauges", "snapshot"):
                mods.add(name.rsplit(".", 1)[0].rsplit(".", 1)[-1])
    return mods


@register
class GaugeConsistency(Rule):
    name = "gauge-consistency"
    title = "published gauges surfaced in EXPLAIN ANALYZE and /status"

    def run(self, ctx):
        status_sf = ctx.file(STATUS_REL)
        if status_sf is None:
            return []  # fixture tree without the serving surface

        by_module = {sf.rel.rsplit("/", 1)[-1][:-3]: sf
                     for sf in ctx.package_files}

        # -- published gauge names -----------------------------------------
        published = []  # (name, rel, line)
        for sf in ctx.package_files:
            if sf.rel.startswith("lint/"):
                continue
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.Call)
                        and call_name(node).rsplit(".", 1)[-1] ==
                        "set_gauge" and node.args):
                    s = const_str(node.args[0])
                    if s:
                        published.append((_base(s), sf.rel, node.lineno))
            for top in sf.tree.body:
                if (isinstance(top, ast.FunctionDef)
                        and top.name in PUBLISH_FNS):
                    # report_gauges() feeds _publish_gauges in several
                    # modules (mpp_exec builds its dict there), so both
                    # are publish sources
                    for s in _fn_string_keys(top):
                        published.append((_base(s), sf.rel, top.lineno))

        # -- surfaced sets --------------------------------------------------
        # /status side: literals in http_status.py + the snapshot()
        # payload keys of every module it reads
        status_names = {s for s in _all_literals(status_sf)}
        for mod in _referenced_modules(status_sf):
            sf = by_module.get(mod)
            if sf is not None:
                status_names |= _module_fn_literals(
                    sf, ("snapshot", "report_gauges"))
        # EXPLAIN ANALYZE side: any file calling .annotate(...) counts as
        # an annotation surface — its literals plus the report_gauges()
        # keys of modules it splats
        explain_names = set()
        for sf in ctx.package_files:
            if sf.rel.startswith("lint/"):
                continue
            annotate_calls = [
                n for n in ast.walk(sf.tree)
                if isinstance(n, ast.Call)
                and call_name(n).rsplit(".", 1)[-1] == "annotate"]
            if not annotate_calls:
                continue
            explain_names |= _all_literals(sf)
            # annotate(gauge_name=value): the KEYWORD is the surfaced key
            for call in annotate_calls:
                for kw in call.keywords:
                    if kw.arg:
                        explain_names.add(kw.arg)
            for mod in _referenced_modules(sf):
                m = by_module.get(mod)
                if m is not None:
                    explain_names |= _module_fn_literals(
                        m, ("report_gauges",))

        out = []
        seen = set()
        for name, rel, line in sorted(published):
            if name in seen:
                continue
            seen.add(name)
            if name not in status_names:
                out.append(self.finding(
                    rel, line, f"unsurfaced-status:{name}",
                    f"gauge '{name}' is published but absent from the "
                    "/status payload (module snapshot())"))
            if name not in explain_names:
                out.append(self.finding(
                    rel, line, f"unsurfaced-explain:{name}",
                    f"gauge '{name}' is published but never annotated "
                    "into EXPLAIN ANALYZE"))
        out += self._check_histograms(ctx)
        out += self._check_fleet_inventory(ctx, status_sf)
        return out

    def _check_fleet_inventory(self, ctx, status_sf):
        """Pin the FLEET_INVENTORY names on both ends: the publishing
        module must still emit each field, and /metrics must still
        surface it."""
        out = []
        status_lits = _all_literals(status_sf)
        for rel, names in sorted(FLEET_INVENTORY.items()):
            sf = ctx.file(rel)
            if sf is None:
                continue  # fixture tree without the fabric modules
            lits = _all_literals(sf)
            for name in names:
                if name not in lits:
                    out.append(self.finding(
                        rel, 1, f"fleet-inventory-source:{name}",
                        f"fleet observability field '{name}' is in the "
                        "lint inventory but its publishing module no "
                        "longer mentions it"))
                if name not in status_lits:
                    out.append(self.finding(
                        status_sf.rel, 1,
                        f"fleet-inventory-status:{name}",
                        f"fleet observability field '{name}' (published "
                        f"by {rel}) is absent from /metrics "
                        "(server/http_status.py)"))
        return out

    def _check_histograms(self, ctx):
        """The histogram analog of the gauge check: every `observe_hist`
        call must name a key of the HIST_BUCKETS registry
        (session/observe.py — the literal dict /metrics renders as
        `_bucket`/`_sum`/`_count` series), and every registry key must
        have a caller — a documented-but-dead histogram name is the same
        drift the gauge rule pins."""
        obs_sf = ctx.file("session/observe.py")
        if obs_sf is None:
            return []
        registry = {}
        for node in obs_sf.tree.body:
            if (isinstance(node, ast.Assign) and node.targets
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "HIST_BUCKETS"
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    s = const_str(k)
                    if s:
                        registry[s] = node.lineno
        observed = []  # (name, rel, line)
        for sf in ctx.package_files:
            if sf.rel.startswith("lint/"):
                continue
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.Call)
                        and call_name(node).rsplit(".", 1)[-1] in
                        ("observe_hist", "_observe_hist") and node.args):
                    s = const_str(node.args[0])
                    if s:
                        observed.append((s, sf.rel, node.lineno))
        out = []
        seen = set()
        for name, rel, line in sorted(observed):
            if rel == "session/observe.py" or name in seen:
                continue  # the registry's own recorder method
            seen.add(name)
            if name not in registry:
                out.append(self.finding(
                    rel, line, f"unregistered-hist:{name}",
                    f"histogram '{name}' is observed but not a key of "
                    "session/observe.py HIST_BUCKETS (the /metrics "
                    "bucket registry)"))
        for name, line in sorted(registry.items()):
            if name not in seen:
                out.append(self.finding(
                    obs_sf.rel, line, f"unobserved-hist:{name}",
                    f"histogram '{name}' is registered in HIST_BUCKETS "
                    "but nothing ever observes it"))
        return out


def _all_literals(sf) -> set:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, str):
                    out.add(v.value.rstrip(":"))
    return out
