"""Wide decimals (precision > 18): exact arbitrary-precision semantics for
decimal(38,x) columns — storage, arithmetic, SUM/AVG, ordering — with
parity against Python's decimal module (reference: types/mydecimal.go,
81-digit fixed point; SURVEY §7 wide-decimal plan)."""

from decimal import Decimal, getcontext

import pytest

getcontext().prec = 80  # exact reference arithmetic (default 28 rounds)

from tidb_tpu.testkit import TestKit

VALS = [
    "12345678901234567890123456.1234567890",
    "-9999999999999999999999999.9999999999",
    "0.0000000001",
    "31415926535897932384626433.8327950288",
    "-1.5",
    "99999999999999999999999999.0000000001",
]


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database wd")
    tk.must_exec("use wd")
    tk.must_exec("create table d (id bigint, v decimal(38,10))")
    for i, v in enumerate(VALS):
        tk.must_exec(f"insert into d values ({i}, {v})")
    tk.must_exec("insert into d values (99, null)")
    return tk


def test_roundtrip_exact(tk):
    rows = tk.must_query("select v from d where id = 0").rows
    assert rows == [("12345678901234567890123456.1234567890",)]


def test_sum_matches_python_decimal(tk):
    want = sum(Decimal(v) for v in VALS)
    rows = tk.must_query("select sum(v) from d").rows
    assert Decimal(rows[0][0]) == want


def test_avg_matches_python_decimal(tk):
    rows = tk.must_query("select avg(v) from d").rows
    got = Decimal(rows[0][0])
    want = sum(Decimal(v) for v in VALS) / 6
    # avg output scale is bounded; compare at the returned scale
    assert abs(got - want) <= Decimal("0.0001")


def test_arithmetic_exact(tk):
    rows = tk.must_query(
        "select v + v, v - 1 from d where id = 3").rows
    v = Decimal(VALS[3])
    assert Decimal(rows[0][0]) == v + v
    assert Decimal(rows[0][1]) == v - 1


def test_order_and_minmax(tk):
    rows = tk.must_query(
        "select min(v), max(v) from d").rows
    ds = sorted(Decimal(v) for v in VALS)
    assert Decimal(rows[0][0]) == ds[0]
    assert Decimal(rows[0][1]) == ds[-1]
    ordered = tk.must_query(
        "select id from d where v is not null order by v").rows
    want = [str(i) for i, _ in sorted(enumerate(VALS),
                                      key=lambda p: Decimal(p[1]))]
    assert [r[0] for r in ordered] == [w for w in want]


def test_filter_and_group(tk):
    rows = tk.must_query(
        "select count(*) from d where v > 0").rows
    assert rows == [(str(sum(1 for v in VALS if Decimal(v) > 0)),)]
    rows = tk.must_query(
        "select v, count(*) from d group by v having count(*) = 1 "
        "order by v desc limit 1").rows
    assert Decimal(rows[0][0]) == max(Decimal(v) for v in VALS)


def test_narrow_sum_never_wraps(tk):
    # int64-scaled decimal(18,0) summed past 2^63 must still be exact
    tk.must_exec("create table nw (v decimal(18,0))")
    tk.must_exec("insert into nw values " + ",".join(
        ["(900000000000000000)"] * 12))
    rows = tk.must_query("select sum(v) from nw").rows
    assert rows == [("10800000000000000000",)]


def test_update_and_join_on_wide(tk):
    tk.must_exec("create table d2 (v decimal(38,10))")
    tk.must_exec(f"insert into d2 values ({VALS[0]}), ({VALS[4]})")
    rows = tk.must_query(
        "select count(*) from d, d2 where d.v = d2.v").rows
    assert rows == [("2",)]
    tk.must_exec(f"update d2 set v = v + 1 where v = {VALS[4]}")
    rows = tk.must_query("select v from d2 order by v limit 1").rows
    assert Decimal(rows[0][0]) == Decimal(VALS[4]) + 1


def test_tpu_engine_parity_via_fallback(tk):
    # the device path declines wide-decimal columns; engine='tpu' must
    # still return identical rows through the host fallback
    q = "select sum(v), count(*) from d where v > 0"
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(q).rows
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    dev = tk.must_query(q).rows
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert host == dev


def test_partitioned_join_wide_narrow_keys(tk):
    # review regression: wide (object) and narrow (int64) decimal join
    # keys must hash to the same spill partition
    import numpy as np
    from tidb_tpu.ops.host import partition_ids
    vals = [-1, 5, 2 ** 61, 7, -(2 ** 60)]
    wide = np.array(vals, dtype=object)
    narrow = np.array(vals, dtype=np.int64)
    z = np.zeros(len(vals), dtype=bool)
    assert list(partition_ids([(wide, z)], 16)) == \
        list(partition_ids([(narrow, z)], 16))


def test_narrow_to_wide_rescale_exact(tk):
    # review regression: narrow decimal coerced to a wide common scale
    # must promote to bigints, not wrap
    tk.must_exec("create table mix (a decimal(12,0), b decimal(38,10))")
    tk.must_exec("insert into mix values (1000000000, 1000000000.0000000000)")
    rows = tk.must_query("select count(*) from mix where a = b").rows
    assert rows == [("1",)]


def test_sum_with_int64_min_no_wrap(tk):
    tk.must_exec("create table mn (v bigint)")
    tk.must_exec(f"insert into mn values ({-2**63}), ({-5 * 10**18})")
    rows = tk.must_query("select sum(v) from mn").rows
    assert rows == [(str(-2**63 - 5 * 10**18),)]
