"""Delta-maintained columnar cache: committed writes apply incrementally
(append + tombstone + compact) instead of rebuilding the table snapshot
(reference analog: TiFlash delta tree; v1 rebuilt on every version bump)."""

import numpy as np
import pytest

import tidb_tpu.storage.columnar as columnar
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("create table d (a int primary key, b int, c varchar(16))")
    for i in range(20):
        tk.must_exec(f"insert into d values ({i}, {i * 10}, 'x{i}')")
    return tk


def _entry(tk):
    info = tk.session.infoschema().table_by_name("test", "d")
    return tk.session.domain.columnar_cache._entries.get(info.id), info


def _forbid_rebuild(tk, monkeypatch):
    """After the first materialization, any full rebuild is a bug."""
    cache = tk.session.domain.columnar_cache

    def boom(*a, **k):
        raise AssertionError("columnar cache rebuilt — delta path not taken")
    monkeypatch.setattr(cache, "_build", boom)


def test_insert_applies_as_delta(tk, monkeypatch):
    tk.must_query("select count(*) from d")      # materialize
    _forbid_rebuild(tk, monkeypatch)
    tk.must_exec("insert into d values (100, 1000, 'new')")
    tk.must_query("select count(*) from d").check([("21",)])
    tk.must_query("select b from d where a = 100").check([("1000",)])
    e, _ = _entry(tk)
    assert e is not None and e.segs, "insert did not land in the delta layer"


def test_update_tombstones_old_version(tk, monkeypatch):
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    tk.must_exec("update d set b = 999 where a = 5")
    tk.must_query("select b from d where a = 5").check([("999",)])
    # the row appears exactly once
    tk.must_query("select count(*) from d where a = 5").check([("1",)])
    tk.must_query("select count(*) from d").check([("20",)])


def test_delete_tombstones(tk, monkeypatch):
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    tk.must_exec("delete from d where a < 3")
    tk.must_query("select count(*) from d").check([("17",)])
    tk.must_query("select min(a) from d").check([("3",)])


def test_repeated_update_single_row(tk, monkeypatch):
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    for v in (1, 2, 3, 4):
        tk.must_exec(f"update d set b = {v} where a = 7")
        tk.must_query("select b from d where a = 7").check([(str(v),)])
    tk.must_query("select count(*) from d").check([("20",)])


def test_compaction_restores_base(tk, monkeypatch):
    monkeypatch.setattr(columnar, "_COMPACT_MIN", 8)
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    for i in range(200, 230):
        tk.must_exec(f"insert into d values ({i}, {i}, 'z{i}')")
    e, _ = _entry(tk)
    assert e is not None
    assert e.delta_rows() <= 8, "delta never compacted"
    # handle order restored ascending after compaction
    assert (np.diff(e.handles) > 0).all()
    tk.must_query("select count(*) from d").check([("50",)])
    tk.must_query("select max(a) from d").check([("229",)])


def test_multi_session_deltas_chain(tk, monkeypatch):
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    tk2 = tk.new_session()
    tk.must_exec("insert into d values (300, 1, 'a')")
    tk2.must_exec("insert into d values (301, 2, 'b')")
    tk.must_exec("update d set b = 5 where a = 300")
    tk2.must_query("select count(*) from d").check([("22",)])
    tk2.must_query("select b from d where a = 300").check([("5",)])


def test_explicit_txn_multi_statement_delta(tk, monkeypatch):
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    tk.must_exec("begin")
    tk.must_exec("insert into d values (400, 7, 'in-txn')")
    tk.must_exec("update d set b = 8 where a = 400")
    tk.must_exec("delete from d where a = 0")
    tk.must_exec("commit")
    tk.must_query("select b from d where a = 400").check([("8",)])
    tk.must_query("select count(*) from d").check([("20",)])


def test_rollback_leaves_cache_untouched(tk, monkeypatch):
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    tk.must_exec("begin")
    tk.must_exec("insert into d values (500, 1, 'r')")
    tk.must_exec("rollback")
    tk.must_query("select count(*) from d").check([("20",)])


def test_device_path_sees_delta(tk, monkeypatch):
    """The fused device fragment scans the merged view."""
    tk.must_query("select count(*) from d")
    _forbid_rebuild(tk, monkeypatch)
    tk.must_exec("insert into d values (600, 600, 'dev')")
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    r = tk.must_query("select sum(b) from d where a >= 600")
    assert r.rows[0][0] == "600"
    tk.must_exec("set tidb_executor_engine = 'auto'")


def test_repeatable_read_in_explicit_txn(tk):
    """A txn's reads must not see rows committed after its start
    (cache must not serve post-snapshot data to an old read view)."""
    tk.must_query("select count(*) from d")
    tk2 = tk.new_session()
    tk.must_exec("begin")
    tk.must_query("select count(*) from d").check([("20",)])
    tk2.must_exec("insert into d values (900, 9, 'post')")
    # tk still inside its txn: the new row is invisible (repeatable read)
    tk.must_query("select count(*) from d").check([("20",)])
    tk.must_query("select count(*) from d where a = 900").check([("0",)])
    tk.must_exec("commit")
    tk.must_query("select count(*) from d").check([("21",)])


def test_cold_cache_build_inside_old_txn_not_poisoned(tk):
    """Finding: a rebuild from an old-ts snapshot must not be installed as
    the current version (it would permanently hide newer commits)."""
    tk.must_query("select count(*) from d")
    info = tk.session.infoschema().table_by_name("test", "d")
    tk2 = tk.new_session()
    tk.must_exec("begin")                      # old read view
    tk.must_query("select count(*) from d")    # pin the view
    tk2.must_exec("insert into d values (901, 1, 'x')")
    # evict so tk's next read would be a cold build from its old snapshot
    tk.session.domain.columnar_cache.invalidate(info.id)
    tk.must_query("select count(*) from d").check([("20",)])  # own view
    tk.must_exec("commit")
    # other (fresh) sessions must see the committed row — the old-ts build
    # must not have been installed as current
    tk2.must_query("select count(*) from d").check([("21",)])
    tk.must_query("select count(*) from d").check([("21",)])


def test_view_immutable_after_commit(tk):
    """COW: a view captured before a commit keeps its row set — closes the
    get→project window where in-place deltas would leak newer rows."""
    tk.must_query("select count(*) from d")
    info = tk.session.infoschema().table_by_name("test", "d")
    cache = tk.session.domain.columnar_cache
    view = cache.get(info, tk.session.store.begin())
    before = view.nrows
    tk.must_exec("insert into d values (950, 1, 'post-view')")
    tk.must_exec("delete from d where a = 1")
    assert view.nrows == before
    chunk = cache.project(view, info.public_columns(), info)
    assert chunk.num_rows == before
    # while the entry's CURRENT view advanced
    fresh = cache.get(info, tk.session.store.begin())
    assert fresh.nrows == before  # +1 insert -1 delete
