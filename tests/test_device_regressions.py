"""Device-engine regressions: host and tpu engines must agree.

Each case was a reproduced divergence (code review round 1): empty global
aggregate, NULL-vs--1 group key collision, first_row NULL preservation."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database devreg")
    tk.must_exec("use devreg")
    tk.must_exec("create table t (a bigint, b bigint)")
    tk.must_exec("insert into t values (-1, 1), (null, 2), (5, 3)")
    tk.must_exec("create table t2 (g bigint, b bigint)")
    tk.must_exec("insert into t2 values (1, null), (1, 7)")
    return tk


def both_engines(tk, sql):
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    tpu = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert host == tpu, f"\nhost: {host}\ntpu:  {tpu}"
    return host


def test_empty_global_agg(tk):
    rows = both_engines(
        tk, "select count(*), sum(b), min(b) from t where a > 100")
    assert rows == [("0", None, None)]


def test_null_key_not_merged_with_minus_one(tk):
    rows = both_engines(
        tk, "select a, count(*) from t group by a order by a is null, a")
    assert rows == [("-1", "1"), ("5", "1"), (None, "1")]


def test_first_row_keeps_null(tk):
    rows = both_engines(tk, "select g, b from t2 group by g")
    assert rows == [("1", None)]


def test_min_max_with_nulls_and_negatives(tk):
    rows = both_engines(
        tk, "select a, min(b), max(b), avg(b) from t group by a "
            "order by a is null, a")
    assert rows == [("-1", "1", "1", "1.0000"),
                    ("5", "3", "3", "3.0000"),
                    (None, "2", "2", "2.0000")]
