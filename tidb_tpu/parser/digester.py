"""SQL normalization + digest (reference: parser/digester.go NormalizeDigest):
literals become '?', whitespace collapses, keywords lowercase. The digest keys
plan cache, statement summary, and plan binding."""

from __future__ import annotations

import hashlib

from .lexer import (
    EOF, HINT, IDENT, NUM_DEC, NUM_FLOAT, NUM_INT, OP, PARAM, QIDENT,
    STRING, SYSVAR, USERVAR, tokenize,
)


def normalize(sql: str) -> str:
    try:
        toks = tokenize(sql)
    except Exception:
        return sql.strip().lower()
    out = []
    prev_lit = False
    for t in toks:
        if t.kind == EOF:
            break
        if t.kind == HINT:
            # hints never key the digest: a hinted and an unhinted form
            # are the SAME statement for binding/plan-cache/summary
            # purposes (reference: digester strips hint comments)
            continue
        if t.kind in (NUM_INT, NUM_DEC, NUM_FLOAT, STRING, PARAM):
            # collapse IN (?, ?, ?) lists into (...)
            if prev_lit:
                continue
            out.append("?")
            prev_lit = True
            continue
        if t.kind == OP and t.val == "," and prev_lit:
            continue
        prev_lit = False
        if t.kind == IDENT:
            out.append(t.val.lower())
        elif t.kind == QIDENT:
            out.append(t.val.lower())
        elif t.kind == SYSVAR:
            out.append("@@" + t.val.lower())
        elif t.kind == USERVAR:
            out.append("@" + t.val.lower())
        else:
            out.append(str(t.val))
    return " ".join(out)


def digest(sql: str) -> str:
    return hashlib.sha256(normalize(sql).encode()).hexdigest()
