"""Subquery decorrelation (reference: planner/core/optimizer.go:73-91
decorrelate rule + expression_rewriter.go): correlated EXISTS / [NOT] IN
whose correlation is equality-only plan as semi/anti joins — reaching the
hash-join executors (and the device fragment path) instead of per-outer-row
SubqueryApply re-execution."""

import time

import numpy as np
import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table orders_d (o_orderkey bigint, o_custkey bigint,"
                 " o_orderdate date, o_comment varchar(40))")
    tk.must_exec("create table lineitem_d (l_orderkey bigint, "
                 "l_commitdate date, l_receiptdate date, l_suppkey bigint)")
    tk.must_exec("create table customer_d (c_custkey bigint, "
                 "c_acctbal decimal(12,2), c_phone varchar(15))")
    rows_o, rows_l, rows_c = [], [], []
    rng = np.random.default_rng(9)
    for i in range(1, 401):
        rows_o.append(f"({i}, {i % 37 + 1}, '199{i % 7}-0{i % 9 + 1}-15', "
                      f"'c{i}')")
    for i in range(1, 1201):
        ok = i % 400 + 1
        c = int(rng.integers(0, 2000))
        r = c + int(rng.integers(-500, 1500))
        rows_l.append(f"({ok}, '1995-01-{c % 28 + 1:02d}', "
                      f"'1995-02-{r % 28 + 1:02d}', {i % 50 + 1})")
    for i in range(1, 38):
        bal = round(float(rng.uniform(-500, 5000)), 2)
        rows_c.append(f"({i}, {bal}, '{i % 30 + 10}-000')")
    tk.must_exec("insert into orders_d values " + ",".join(rows_o))
    tk.must_exec("insert into lineitem_d values " + ",".join(rows_l))
    tk.must_exec("insert into customer_d values " + ",".join(rows_c))
    return tk


def _plan(tk, sql):
    return "\n".join(r[0] + "|" + r[1] for r in
                     tk.must_query("explain " + sql).rows)


class TestDecorrelatePlans:
    def test_q4_shape_exists_plans_semi_join(self, tk):
        """TPC-H Q4: EXISTS over lineitem correlated on orderkey."""
        sql = ("select o_orderkey from orders_d where exists ("
               "select 1 from lineitem_d where l_orderkey = o_orderkey "
               "and l_commitdate < l_receiptdate) order by o_orderkey")
        p = _plan(tk, sql)
        assert "semi" in p and "apply" not in p

    def test_q21_shape_exists_plus_not_exists(self, tk):
        """TPC-H Q21: both EXISTS and NOT EXISTS correlated conjuncts."""
        sql = ("select o_orderkey from orders_d where exists ("
               "select 1 from lineitem_d where l_orderkey = o_orderkey and "
               "l_suppkey = 7) and not exists (select 1 from lineitem_d "
               "where l_orderkey = o_orderkey and l_suppkey = 9) "
               "order by o_orderkey")
        p = _plan(tk, sql)
        assert "semi" in p and "anti" in p and "apply" not in p

    def test_q22_shape_not_exists(self, tk):
        """TPC-H Q22 inner: NOT EXISTS orders per customer."""
        sql = ("select c_custkey from customer_d where c_acctbal > 0 and "
               "not exists (select 1 from orders_d "
               "where o_custkey = c_custkey) order by c_custkey")
        p = _plan(tk, sql)
        assert "anti" in p and "apply" not in p

    def test_correlated_in_plans_semi(self, tk):
        sql = ("select o_orderkey from orders_d where o_custkey in ("
               "select c_custkey from customer_d where c_custkey = o_custkey "
               "and c_acctbal > 100)")
        p = _plan(tk, sql)
        assert "semi" in p and "apply" not in p

    def test_non_equality_correlation_falls_back(self, tk):
        sql = ("select c_custkey from customer_d where exists ("
               "select 1 from orders_d where o_custkey > c_custkey)")
        assert "apply" in _plan(tk, sql)

    def test_correlated_under_aggregate_falls_back(self, tk):
        sql = ("select c_custkey from customer_d where exists ("
               "select o_custkey from orders_d where o_custkey = c_custkey "
               "group by o_custkey having count(*) > 1)")
        assert "apply" in _plan(tk, sql)


class TestDecorrelateResults:
    def _parity(self, tk, decorrelated_sql, apply_sql):
        a = tk.must_query(decorrelated_sql).rows
        b = tk.must_query(apply_sql).rows
        assert a == b
        return a

    def test_exists_parity_with_apply_fallback(self, tk):
        """Same query through the join path and (forced via non-eq shape
        that keeps semantics) the apply path."""
        dec = ("select o_orderkey from orders_d where exists ("
               "select 1 from lineitem_d where l_orderkey = o_orderkey "
               "and l_commitdate < l_receiptdate) order by o_orderkey")
        # + 0 on the correlated side defeats the bare-OuterRef pattern →
        # apply fallback with identical semantics
        app = ("select o_orderkey from orders_d where exists ("
               "select 1 from lineitem_d where l_orderkey = o_orderkey + 0 "
               "and l_commitdate < l_receiptdate) order by o_orderkey")
        rows = self._parity(tk, dec, app)
        assert len(rows) > 0

    def test_not_exists_parity(self, tk):
        dec = ("select c_custkey from customer_d where not exists ("
               "select 1 from orders_d where o_custkey = c_custkey and "
               "o_orderdate < '1993-01-01') order by c_custkey")
        app = ("select c_custkey from customer_d where not exists ("
               "select 1 from orders_d where o_custkey = c_custkey + 0 and "
               "o_orderdate < '1993-01-01') order by c_custkey")
        self._parity(tk, dec, app)

    def test_not_in_null_semantics(self, tk):
        tk.must_exec("create table tn (a bigint)")
        tk.must_exec("create table sn (g bigint, b bigint)")
        tk.must_exec("insert into tn values (1),(2),(null)")
        tk.must_exec("insert into sn values (1,1),(1,null),(2,5),(3,7)")
        # a NOT IN {b : g = a}: a=1 -> set {1,NULL}: match -> drop;
        # a=2 -> {5}: no match, no null -> keep; NULL a with non-empty set
        # (never: g=NULL matches nothing -> empty set -> keep)
        rows = tk.must_query(
            "select a from tn where a not in (select b from sn where "
            "sn.g = tn.a) order by a").rows
        assert rows == [(None,), ("2",)]
        # and the plan is the null-aware anti join, not apply
        p = _plan(tk, "select a from tn where a not in (select b from sn "
                      "where sn.g = tn.a)")
        assert "anti" in p and "apply" not in p

    def test_q17_shape_scalar_avg_cmp(self, tk):
        """x < (SELECT 0.2*avg(...) WHERE k = outer.k) → semi join against
        the re-grouped aggregate."""
        tk.must_exec("create table li17 (l_partkey bigint, "
                     "l_quantity bigint, l_price bigint)")
        rng = np.random.default_rng(4)
        tk.must_exec("insert into li17 values " + ",".join(
            f"({int(rng.integers(1, 20))}, {int(rng.integers(1, 50))}, "
            f"{int(rng.integers(100, 900))})" for _ in range(300)))
        dec = ("select sum(l_price) from li17 where l_quantity < ("
               "select 0.2 * avg(l_quantity) from li17 l2 "
               "where l2.l_partkey = li17.l_partkey)")
        app = dec.replace("l2.l_partkey = li17.l_partkey",
                          "l2.l_partkey = li17.l_partkey + 0")
        assert tk.must_query(dec).rows == tk.must_query(app).rows
        p = _plan(tk, dec)
        assert "semi" in p and "apply" not in p

    def test_q20_shape_two_key_sum_cmp(self, tk):
        tk.must_exec("create table ps20 (pk bigint, sk bigint, av bigint)")
        tk.must_exec("create table li20 (pk bigint, sk bigint, q bigint)")
        rng = np.random.default_rng(6)
        tk.must_exec("insert into ps20 values " + ",".join(
            f"({int(rng.integers(1, 15))}, {i % 5 + 1}, "
            f"{int(rng.integers(10, 900))})" for i in range(80)))
        tk.must_exec("insert into li20 values " + ",".join(
            f"({int(rng.integers(1, 15))}, {int(rng.integers(1, 6))}, "
            f"{int(rng.integers(1, 40))})" for _ in range(200)))
        dec = ("select count(*) from ps20 where av > (select 0.5 * sum(q) "
               "from li20 where li20.pk = ps20.pk and li20.sk = ps20.sk)")
        app = dec.replace("li20.pk = ps20.pk", "li20.pk = ps20.pk + 0")
        assert tk.must_query(dec).rows == tk.must_query(app).rows
        assert "semi" in _plan(tk, dec)

    def test_scalar_count_cmp_falls_back(self, tk):
        """COUNT's empty-group scalar is 0 (not NULL): must NOT rewrite to
        a semi join (which drops no-match rows)."""
        tk.must_exec("create table tc (a bigint)")
        tk.must_exec("create table sc (g bigint)")
        tk.must_exec("insert into tc values (1),(2)")
        tk.must_exec("insert into sc values (1)")
        q = ("select a from tc where 0 = (select count(*) from sc "
             "where sc.g = tc.a) order by a")
        assert tk.must_query(q).rows == [("2",)]
        assert "apply" in _plan(tk, q)

    def test_scaling_not_quadratic(self, tk):
        """10k-outer-row correlated EXISTS must run as one join, not 10k
        subquery re-plans (the O(N) replan pathology the VERDICT cites)."""
        tk.must_exec("create table big_o (k bigint)")
        tk.must_exec("create table big_i (k bigint)")
        vals = ",".join(f"({i})" for i in range(10_000))
        tk.must_exec("insert into big_o values " + vals)
        tk.must_exec("insert into big_i values " +
                     ",".join(f"({i})" for i in range(0, 10_000, 2)))
        t0 = time.perf_counter()
        rows = tk.must_query(
            "select count(*) from big_o where exists ("
            "select 1 from big_i where big_i.k = big_o.k)").rows
        dt = time.perf_counter() - t0
        assert rows == [("5000",)]
        assert dt < 5.0  # apply-per-row took minutes at this size
