"""Disk-backed paged columns (storage/paged.py): the larger-than-memory
scan path (reference: cop paging kv/kv.go:349-350 + chunk spill
util/chunk/disk.go — here a memmap-backed columnar layer whose scans
stream fixed-size pages through the device pipeline)."""

import numpy as np
import pytest

from tidb_tpu.storage.paged import (
    PagedTableWriter, chunk_is_paged, open_paged_columns)
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils.chunk import LazyDictColumn

N = 9_000
PAGE = 2_000


@pytest.fixture(scope="module")
def tk(tmp_path_factory):
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table pg (k bigint, grp bigint, amount bigint, "
                 "price decimal(10,2), tag varchar(8))")
    tk.must_exec("create table ref (k bigint, grp bigint, amount bigint, "
                 "price decimal(10,2), tag varchar(8))")

    rng = np.random.default_rng(3)
    k = np.arange(1, N + 1, dtype=np.int64)
    grp = rng.integers(0, 7, N)
    amount = rng.integers(-50, 500, N)
    price = rng.integers(0, 100000, N)  # cents
    tags = [b"alpha", b"beta", b"gamma"]
    tag_codes = rng.integers(0, 3, N).astype(np.int32)

    root = tmp_path_factory.mktemp("paged") / "pg"
    info = tk.domain.infoschema().table_by_name("test", "pg")
    w = PagedTableWriter(str(root), info)
    w.set_dictionary("tag", tags)
    for lo in range(0, N, PAGE):  # multiple append calls = multiple pages
        hi = min(lo + PAGE, N)
        w.append({"k": k[lo:hi], "grp": grp[lo:hi],
                  "amount": amount[lo:hi], "price": price[lo:hi],
                  "tag": tag_codes[lo:hi]})
    columns, handles = w.finalize()
    tk.domain.columnar_cache.install_bulk(info, columns, handles)

    # reference table through the ordinary SQL write path
    rows = []
    for i in range(N):
        rows.append(f"({k[i]}, {grp[i]}, {amount[i]}, "
                    f"{price[i] / 100:.2f}, '{tags[tag_codes[i]].decode()}')")
    for lo in range(0, N, 3000):
        tk.must_exec("insert into ref values " + ",".join(rows[lo:lo + 3000]))
    tk._paged_root = str(root)
    tk._paged_info = info
    return tk


AGG = ("select grp, tag, count(*), sum(amount), min(amount), max(price), "
       "avg(price) from {t} where amount > 0 group by grp, tag "
       "order by grp, tag")


class TestPagedStorage:
    def test_columns_are_memmap_backed(self, tk):
        cols = open_paged_columns(tk._paged_root, tk._paged_info)
        kinds = {type(c).__name__ for c in cols.values()}
        assert "LazyDictColumn" in kinds
        for c in cols.values():
            if isinstance(c, LazyDictColumn):
                codes, uniques = c.dict_encode()
                assert isinstance(codes, np.memmap)
                assert c._mat is None  # nothing materialized yet
            else:
                assert isinstance(c.data, np.memmap)

    def test_device_stream_parity_with_sql_loaded_table(self, tk):
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {PAGE}")
        dev = tk.must_query(AGG.format(t="pg")).rows
        tk.must_exec("set tidb_device_stream_rows = 0")
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(AGG.format(t="ref")).rows
        assert dev == host

    def test_host_path_reads_paged_table(self, tk):
        tk.must_exec("set tidb_executor_engine = 'host'")
        a = tk.must_query(AGG.format(t="pg")).rows
        b = tk.must_query(AGG.format(t="ref")).rows
        assert a == b

    def test_point_lookups_and_strings(self, tk):
        tk.must_exec("set tidb_executor_engine = 'host'")
        r = tk.must_query(
            "select tag, amount from pg where k = 17").rows
        s = tk.must_query(
            "select tag, amount from ref where k = 17").rows
        assert r == s

    def test_streaming_does_not_materialize_string_column(self, tk):
        """The device scan must read dictionary CODES from the memmap, never
        the object view (materializing 600M python bytes at SF100 is the
        exact failure this layer exists to prevent)."""
        cols = open_paged_columns(tk._paged_root, tk._paged_info)
        info = tk._paged_info
        tk.domain.columnar_cache.install_bulk(
            info, cols, np.arange(1, N + 1, dtype=np.int64))
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {PAGE}")
        tk.must_query(AGG.format(t="pg"))
        tk.must_exec("set tidb_device_stream_rows = 0")
        lazy = [c for c in cols.values() if isinstance(c, LazyDictColumn)]
        assert lazy and all(c._mat is None for c in lazy)

    def test_ci_collation_streams_without_materializing(self, tk):
        """_ci group keys on a paged table go through the per-page remap
        view, not a table-sized ci_codes array."""
        tk.must_exec("create table pgci (g bigint, s varchar(8) collate "
                     "utf8mb4_general_ci)")
        info = tk.domain.infoschema().table_by_name("test", "pgci")
        import tempfile
        root = tempfile.mkdtemp() + "/pgci"
        w = PagedTableWriter(root, info)
        w.set_dictionary("s", [b"AA", b"aa", b"bb"])
        rng = np.random.default_rng(5)
        w.append({"g": rng.integers(0, 3, 6000),
                  "s": rng.integers(0, 3, 6000).astype(np.int32)})
        cols, handles = w.finalize()
        tk.domain.columnar_cache.install_bulk(info, cols, handles)
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec("set tidb_device_stream_rows = 1500")
        rows = tk.must_query(
            "select s, count(*) from pgci group by s order by s").rows
        tk.must_exec("set tidb_device_stream_rows = 0")
        # AA and aa collate equal → 2 classes
        assert len(rows) == 2
        sc = [c for c in cols.values() if isinstance(c, LazyDictColumn)][0]
        assert sc._mat is None
        from tidb_tpu.utils.chunk import _PageRemapCodes
        ci_codes, _kd, _reps = sc.dict_encode_ci("utf8mb4_general_ci")
        assert isinstance(ci_codes, _PageRemapCodes)

    def test_chunk_is_paged_detection(self, tk):
        from tidb_tpu.utils.chunk import Chunk
        cols = open_paged_columns(tk._paged_root, tk._paged_info)
        assert chunk_is_paged(Chunk(list(cols.values())))


@pytest.fixture(scope="module")
def tkj(tmp_path_factory):
    """Paged FACT table + resident dimension tables: the streamed-probe
    join path (device_join._paged_join_agg)."""
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table fact (fk bigint, dk bigint, v bigint)")
    tk.must_exec("create table reffact (fk bigint, dk bigint, v bigint)")
    tk.must_exec("create table dim (dk bigint, dname varchar(8), "
                 "region bigint)")
    tk.must_exec("create table dim2 (region bigint, rname varchar(8))")

    rng = np.random.default_rng(11)
    nf, nd = 12_000, 40
    fk = np.arange(1, nf + 1, dtype=np.int64)
    dk = rng.integers(1, nd + 1, nf)
    v = rng.integers(0, 1000, nf)

    root = tmp_path_factory.mktemp("pagedj") / "fact"
    info = tk.domain.infoschema().table_by_name("test", "fact")
    w = PagedTableWriter(str(root), info)
    for lo in range(0, nf, 2_500):
        hi = min(lo + 2_500, nf)
        w.append({"fk": fk[lo:hi], "dk": dk[lo:hi], "v": v[lo:hi]})
    columns, handles = w.finalize()
    tk.domain.columnar_cache.install_bulk(info, columns, handles)

    rows = [f"({fk[i]}, {dk[i]}, {v[i]})" for i in range(nf)]
    for lo in range(0, nf, 3000):
        tk.must_exec("insert into reffact values "
                     + ",".join(rows[lo:lo + 3000]))
    for d in range(1, nd + 1):
        tk.must_exec(f"insert into dim values ({d}, 'd{d % 7}', {d % 5})")
    for r in range(5):
        tk.must_exec(f"insert into dim2 values ({r}, 'r{r}')")
    for t in ("reffact", "dim", "dim2"):
        tk.must_exec(f"analyze table {t}")
    return tk


JOINQ = ("select dname, count(*), sum(v) from {f}, dim "
         "where {f}.dk = dim.dk and v > 100 group by dname order by dname")

JOIN2Q = ("select rname, count(*), sum(v), min(v) from {f}, dim, dim2 "
          "where {f}.dk = dim.dk and dim.region = dim2.region "
          "group by rname order by rname")


class TestPagedProbeJoin:
    def test_single_join_parity(self, tkj):
        tkj.must_exec("set tidb_executor_engine = 'tpu'")
        tkj.must_exec("set tidb_device_stream_rows = 2500")
        dev = tkj.must_query(JOINQ.format(f="fact")).rows
        tkj.must_exec("set tidb_device_stream_rows = 0")
        tkj.must_exec("set tidb_executor_engine = 'host'")
        host = tkj.must_query(JOINQ.format(f="reffact")).rows
        assert dev == host and len(dev) > 0

    def test_chain_join_parity(self, tkj):
        tkj.must_exec("set tidb_executor_engine = 'tpu'")
        tkj.must_exec("set tidb_device_stream_rows = 2500")
        dev = tkj.must_query(JOIN2Q.format(f="fact")).rows
        tkj.must_exec("set tidb_device_stream_rows = 0")
        tkj.must_exec("set tidb_executor_engine = 'host'")
        host = tkj.must_query(JOIN2Q.format(f="reffact")).rows
        assert dev == host and len(dev) > 0

    def test_odd_tail_page(self, tkj):
        """Page size that does not divide the row count: the padded tail
        page must not leak padding rows into the aggregate."""
        tkj.must_exec("set tidb_executor_engine = 'tpu'")
        tkj.must_exec("set tidb_device_stream_rows = 1700")
        dev = tkj.must_query(JOINQ.format(f="fact")).rows
        tkj.must_exec("set tidb_device_stream_rows = 0")
        tkj.must_exec("set tidb_executor_engine = 'host'")
        host = tkj.must_query(JOINQ.format(f="reffact")).rows
        assert dev == host
