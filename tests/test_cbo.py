"""Cost-based access paths: PointGet / IndexLookUp / full scan chosen by
selectivity, with plan-independent results (reference:
planner/core/point_get_plan.go:467 TryFastPlan,
planner/core/find_best_task.go:359, executor/point_get.go,
executor/distsql.go, statistics/histogram.go:50)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database cbo")
    tk.must_exec("use cbo")
    tk.must_exec("""create table t (
        id bigint primary key, grp bigint, val decimal(10,2),
        name varchar(20), key idx_grp (grp), unique key uk_name (name))""")
    rows = ",".join(
        f"({i}, {i % 100}, {i}.25, 'name{i:04d}')" for i in range(2000))
    tk.must_exec(f"insert into t values {rows}")
    return tk


def plan_of(tk, sql):
    return "\n".join(" | ".join(c or "" for c in r)
                     for r in tk.must_query("explain " + sql).rows)


def test_point_get_pk(tk):
    sql = "select id, name from t where id = 1437"
    assert "PointGet" in plan_of(tk, sql)
    assert tk.must_query(sql).rows == [("1437", "name1437")]
    # miss → empty, not an error
    assert tk.must_query("select id from t where id = 999999").rows == []


def test_point_get_unique_index(tk):
    sql = "select id from t where name = 'name0042'"
    assert "PointGet" in plan_of(tk, sql)
    assert tk.must_query(sql).rows == [("42",)]


def test_point_get_sees_txn_writes(tk):
    s = tk.new_session()
    s.must_exec("use cbo")
    s.must_exec("begin")
    s.must_exec("insert into t values (100000, 5, 1.00, 'fresh')")
    assert s.must_query(
        "select name from t where id = 100000").rows == [("fresh",)]
    s.must_exec("update t set name = 'renamed' where id = 100000")
    assert s.must_query(
        "select id from t where name = 'renamed'").rows == [("100000",)]
    s.must_exec("rollback")
    assert tk.must_query(
        "select name from t where id = 100000").rows == []


def test_index_path_switches_on_selectivity(tk):
    tk.must_exec("analyze table t")
    # grp = const matches ~20 of 2000 rows → the seek path wins
    sel = "select id from t where grp = 7 order by id limit 3"
    assert "IndexLookUp" in plan_of(tk, sel)
    assert tk.must_query(sel).rows == [("7",), ("107",), ("207",)]
    # grp >= 1 matches ~99% of rows → the vectorized full scan wins
    unsel = "select count(1) from t where grp >= 1"
    assert "TableScan" in plan_of(tk, unsel)
    assert tk.must_query(unsel).rows == [("1980",)]


def test_index_range_scan(tk):
    tk.must_exec("analyze table t")
    sql = "select id from t where grp = 3 and id < 500 order by id"
    rows = tk.must_query(sql).rows
    assert rows == [(str(i),) for i in range(3, 500, 100)]


def test_index_path_parity_with_full_scan(tk):
    """Same query with and without the index available must agree."""
    tk.must_exec("analyze table t")
    sql = "select id, val from t where grp = 55 order by id"
    via_index = tk.must_query(sql).rows
    assert "IndexLookUp" in plan_of(tk, sql)
    # an equivalent predicate the index cannot serve (expression on grp)
    sql_noidx = "select id, val from t where grp + 0 = 55 order by id"
    assert "IndexLookUp" not in plan_of(tk, sql_noidx)
    assert via_index == tk.must_query(sql_noidx).rows
    assert len(via_index) == 20


def test_update_maintains_index_reads(tk):
    tk.must_exec("insert into t values (200000, 777, 9.99, 'mover')")
    tk.must_exec("update t set grp = 778 where id = 200000")
    tk.must_exec("analyze table t")
    assert tk.must_query(
        "select id from t where grp = 778").rows == [("200000",)]
    assert tk.must_query(
        "select count(1) from t where grp = 777").rows == [("0",)]
    tk.must_exec("delete from t where id = 200000")
    assert tk.must_query(
        "select id from t where grp = 778").rows == []


def test_explain_shows_estimates(tk):
    tk.must_exec("analyze table t")
    p = plan_of(tk, "select id from t where grp = 7")
    assert "idx_grp" in p and "est_rows" in p


class TestBatchPointGet:
    """reference: point_get_plan.go newBatchPointGetPlan +
    executor/batch_point_get.go."""

    @pytest.fixture()
    def btk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table bp (id int primary key, a int, "
                     "v varchar(8), unique key ua (a))")
        tk.must_exec("insert into bp values "
                     + ",".join(f"({i},{i + 1000},'v{i}')"
                                for i in range(200)))
        tk.must_exec("analyze table bp")
        return tk

    def _explain(self, tk, q):
        return "\n".join(" ".join(map(str, r))
                         for r in tk.must_query("EXPLAIN " + q).rows)

    def test_pk_in_list(self, btk):
        txt = self._explain(btk, "select * from bp where id in (3, 7, 9)")
        assert "BatchPointGet" in txt and "handles:3" in txt
        btk.must_query("select v from bp where id in (3, 7, 9) "
                       "order by id").check([("v3",), ("v7",), ("v9",)])

    def test_unique_index_in_list(self, btk):
        txt = self._explain(btk, "select * from bp where a in (1003, 1009)")
        assert "BatchPointGet" in txt
        btk.must_query("select id from bp where a in (1003, 1009) "
                       "order by id").check([("3",), ("9",)])

    def test_missing_keys_skip(self, btk):
        btk.must_query("select count(*) from bp where id in (1, 99999)"
                       ).check([("1",)])

    def test_in_txn_sees_uncommitted(self, btk):
        btk.must_exec("begin")
        btk.must_exec("update bp set v = 'dirty' where id = 3")
        btk.must_query("select v from bp where id in (3, 4) order by id"
                       ).check([("dirty",), ("v4",)])
        btk.must_exec("rollback")

    def test_duplicate_in_values_return_one_row(self, btk):
        """Regression: IN (3, 3) must not fetch the row twice."""
        btk.must_query("select count(*) from bp where id in (3, 3)").check(
            [("1",)])
        btk.must_query("select count(*) from bp where a in (1003, 1003)"
                       ).check([("1",)])
