"""MVCC garbage collection worker (reference: store/gcworker/gc_worker.go —
runGCJob :619, resolveLocks :1015, the safepoint lease in mysql.tidb).

Each GC round:
 1. compute the safepoint: now - gc_life_time, floored at the oldest live
    reader so an open snapshot never loses its versions;
 2. resolve locks abandoned before the safepoint (check the primary's
    commit status via the version chain, then commit or roll back the
    secondaries — Percolator crash recovery);
 3. drop version-chain entries older than the newest visible-at-safepoint
    version in both MVCC engines.
"""

from __future__ import annotations

import logging
import threading
import time

_log = logging.getLogger("tidb_tpu.coordinator")


def parse_duration(s: str) -> float:
    """'10m0s' / '30m' / '1h10m' / '50s' → seconds (the Go duration syntax
    used by tidb_gc_life_time)."""
    s = s.strip().lower()
    if not s:
        raise ValueError("empty duration")
    units = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}
    total = 0.0
    num = ""
    i = 0
    while i < len(s):
        ch = s[i]
        if ch.isdigit() or ch == ".":
            num += ch
            i += 1
            continue
        unit = ch
        if ch == "m" and i + 1 < len(s) and s[i + 1] == "s":
            unit = "ms"
            i += 1
        i += 1
        if not num or unit not in units:
            raise ValueError(f"bad duration {s!r}")
        total += float(num) * units[unit]
        num = ""
    if num:  # bare number = seconds
        total += float(num)
    return total


class GCWorker:
    """Background safepoint GC (the store/gcworker role; leader election
    collapses to the single in-process domain)."""

    def __init__(self, domain):
        self.domain = domain
        self._thread = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.safe_point = 0
        self.last_run = 0.0
        self.runs = 0
        self.locks_resolved = 0

    # -- config (reference: gc_worker.go loadDurationWithDefault) ------------

    def life_time_s(self) -> float:
        v = self.domain.global_vars.get("tidb_gc_life_time", "10m0s")
        try:
            return max(parse_duration(str(v)), 10.0)  # floor: 10s
        except ValueError:
            return 600.0

    def run_interval_s(self) -> float:
        v = self.domain.global_vars.get("tidb_gc_run_interval", "10m0s")
        try:
            return max(parse_duration(str(v)), 1.0)
        except ValueError:
            return 600.0

    # -- one round -----------------------------------------------------------

    def compute_safepoint(self) -> int:
        """now - life_time as a TSO timestamp, floored at the oldest live
        transaction so open snapshots keep their read views (reference:
        gc_worker.go calcNewSafePoint + minStartTS guard).  Under the
        serving fabric the floor is FLEET-wide: every worker publishes
        its oldest live read-ts into its segment slot column, and GC on
        any worker floors below the minimum — a version a SIBLING
        worker still reads is never dropped."""
        now_ms = int(time.time() * 1000)
        life_ms = int(self.life_time_s() * 1000)
        sp = max(now_ms - life_ms, 0) << 18
        min_start = self._min_active_start_ts()
        if min_start is not None:
            sp = min(sp, min_start - 1)
        fleet_min = self._fleet_min_read_ts()
        if fleet_min:
            sp = min(sp, fleet_min - 1)
        return max(sp, 0)

    def _fleet_min_read_ts(self) -> int:
        """min over live fleet slots' published min-read-ts (0 = no
        fabric, or no sibling pins the floor)."""
        try:
            from ..fabric import state as fabric_state
            if not fabric_state.active():
                return 0
            return fabric_state.coordinator().fleet_min_read_ts()
        except Exception as e:
            # a torn-down segment must not fail a GC round — but a GC
            # running blind to sibling readers is worth a classified log
            from ..utils.backoff import classify
            _log.warning("fleet min-read-ts unreadable (%s): %s",
                         classify(e), e)
            return 0

    def _min_active_start_ts(self):
        starts = [
            s.txn.start_ts
            for s in list(self.domain.sessions.values())
            if getattr(s, "txn", None) is not None and s.txn.valid
        ]
        return min(starts) if starts else None

    def run_once(self, safe_point: int | None = None) -> dict:
        """One GC round; returns its summary (reference: runGCJob)."""
        if str(self.domain.global_vars.get("tidb_gc_enable", "ON")
               ).upper() in ("OFF", "0"):
            return {"safe_point": self.safe_point, "skipped": True}
        store = self.domain.store
        coord = getattr(self.domain, "coordinator", None)
        if coord is not None and not coord.campaign("gc", "tidb-0"):
            # another GC leader holds the lease (reference: gc_worker.go
            # leader election via the owner manager) — skipping is the
            # graceful-degrade path, but losing leadership is still an
            # event the operator should see (satellite: no silent swallow)
            _log.info("gc leader campaign lost; round skipped")
            return {"safe_point": self.safe_point, "skipped": True}
        sp = self.compute_safepoint() if safe_point is None else safe_point
        if coord is not None:
            # service safepoints pin GC: BR/CDC hold a watermark while a
            # task runs; collecting past it would tear their snapshots
            # (reference: PD service safepoints, br/pkg/task)
            pin = coord.min_pin_excluding("gc")
            if pin is not None:
                sp = min(sp, pin)
        if sp <= self.safe_point:
            return {"safe_point": self.safe_point, "skipped": True}
        resolved = self._resolve_stale_locks(sp)
        store.mvcc.gc(sp)
        ranges_done = self._process_delete_ranges(sp)
        with self._lock:
            self.safe_point = sp
            if coord is not None:
                coord.set_safepoint("gc", sp)
            self.last_run = time.time()
            self.runs += 1
            self.locks_resolved += resolved
        obs = getattr(self.domain, "observe", None)
        if obs is not None:
            obs.inc("gc_runs_total")
            obs.inc("gc_locks_resolved_total", resolved)
        return {"safe_point": sp, "resolved_locks": resolved,
                "delete_ranges": ranges_done, "skipped": False}

    def _process_delete_ranges(self, safe_point: int) -> int:
        """Physically delete ranges dropped before the safepoint; while an
        entry is pending, RECOVER/FLASHBACK TABLE can still resurrect the
        data (reference: gc_worker.go:691 deleteRanges +
        ddl/delete_range.go)."""
        from ..meta import Meta
        store = self.domain.store
        # serialized against DDL (RECOVER rewrites the same meta keys), and
        # the meta claim COMMITS BEFORE any physical delete: once committed,
        # RECOVER can no longer find the entries, so it can never resurrect
        # a schema whose data this round is about to purge. A crash after
        # commit leaks orphan KV ranges (space, not correctness).
        with self.domain.ddl_lock:
            txn = store.begin()
            to_delete = []
            try:
                m = Meta(txn)
                gone_owners = set()
                live_owners = set()
                for key, rec in m.delete_ranges():
                    if rec["ts"] < safe_point:
                        to_delete.append((bytes.fromhex(rec["start"]),
                                          bytes.fromhex(rec["end"])))
                        m.remove_delete_range(key)
                        gone_owners.add(rec["owner"])
                    else:
                        live_owners.add(rec["owner"])
                for owner in gone_owners - live_owners:
                    m.remove_dropped_table(owner)
                txn.commit()
            except Exception as e:
                txn.rollback()
                # a failed claim round retries next tick; classify so a
                # persistently-failing purge shows up in the logs
                from ..utils.backoff import classify
                _log.warning("gc delete-range claim failed (%s): %s",
                             classify(e), e)
                return 0
        for start, end in to_delete:
            store.mvcc.raw_delete_range(start, end)
        return len(to_delete)

    def _resolve_stale_locks(self, safe_point: int) -> int:
        """Percolator crash recovery for locks abandoned before the
        safepoint: a committed primary means commit the secondary, a live
        or absent primary record means roll back (reference:
        gc_worker.go:1015 resolveLocks + legacyResolveLocks)."""
        mvcc = self.domain.store.mvcc
        n = 0
        for key, start_ts, primary in mvcc.scan_locks(safe_point):
            committed, commit_ts = self._primary_status(primary, start_ts)
            mvcc.resolve_lock(key, committed, commit_ts)
            n += 1
        return n

    def _primary_status(self, primary: bytes, start_ts: int):
        """-> (committed, commit_ts) by inspecting the primary's version
        chain (reference: client-go CheckTxnStatus)."""
        for commit_ts, s_ts, _op, _v in self.domain.store.mvcc.debug_chain(
                primary):
            if s_ts == start_ts:
                return True, commit_ts
        return False, 0

    # -- the loop ------------------------------------------------------------

    def start(self, interval: float | None = None):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval or self.run_interval_s()):
                try:
                    self.run_once()
                except Exception as e:
                    # background GC must never crash the server, but a GC
                    # round that dies every tick means unbounded MVCC
                    # garbage — classify and log
                    from ..utils.backoff import classify
                    _log.warning("gc round failed (%s): %s", classify(e), e)
        self._thread = threading.Thread(target=loop, name="gc-worker",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def status(self) -> dict:
        with self._lock:
            return {"safe_point": self.safe_point, "last_run": self.last_run,
                    "runs": self.runs, "locks_resolved": self.locks_resolved,
                    "life_time_s": self.life_time_s(),
                    "run_interval_s": self.run_interval_s()}
