"""Hierarchical memory tracking with an action chain (reference:
util/memory/tracker.go:54 — session→statement→operator trackers — and
util/memory/action.go — on quota breach run spill actions, then cancel).

Executors consume approximate chunk bytes into the statement tracker.
Crossing the quota first runs registered spill actions (operators that can
move state to disk); if the overshoot persists, the query is cancelled with
the reference's "Out Of Memory Quota!" error."""

from __future__ import annotations

import threading

from ..errors import TiDBError


class MemQuotaExceeded(TiDBError):
    pass


class MemTracker:
    """One node of the tracker tree. consume() bubbles to the root; any
    ancestor with a limit enforces it."""

    def __init__(self, label: str, limit: int = 0, parent: "MemTracker | None" = None):
        self.label = label
        self.limit = limit            # 0 = unlimited
        self.parent = parent
        self.consumed = 0
        self.max_consumed = 0
        self._actions = []            # [(priority, fn)] fn() -> freed bytes
        self._lock = threading.Lock()

    def child(self, label: str, limit: int = 0) -> "MemTracker":
        return MemTracker(label, limit, parent=self)

    def register_spill(self, fn, priority: int = 0):
        """fn() -> bytes freed. Higher priority runs first (reference:
        actionForSpill before actionForHardLimit)."""
        with self._lock:
            self._actions.append((priority, fn))
            self._actions.sort(key=lambda p: -p[0])

    def unregister_spill(self, fn):
        with self._lock:
            self._actions = [(p, f) for p, f in self._actions if f is not fn]

    def consume(self, n: int):
        node = self
        while node is not None:
            with node._lock:
                node.consumed += n
                node.max_consumed = max(node.max_consumed, node.consumed)
            if node.limit and node.consumed > node.limit:
                node._on_exceed()
            node = node.parent

    def release(self, n: int):
        self.consume(-n)

    def _on_exceed(self):
        # 1. spill actions anywhere in the subtree may free memory
        for _prio, fn in list(self._actions):
            if self.consumed <= self.limit:
                return
            try:
                freed = fn() or 0
            except MemQuotaExceeded:
                raise
            except Exception:
                freed = 0
            if freed:
                self.release(freed)
        if self.consumed > self.limit:
            # 2. cancel (reference: PanicOnExceed / action.go)
            raise MemQuotaExceeded(
                f"Out Of Memory Quota! [{self.label}] consumed "
                f"{self.consumed} bytes, quota {self.limit} bytes")

    def remaining(self) -> int:
        if not self.limit:
            return 1 << 62
        return max(self.limit - self.consumed, 0)

    def remaining_chain(self) -> int:
        """Tightest remaining quota over this tracker and its ancestors —
        what an operator may still allocate before SOME limit fires."""
        r = self.remaining()
        node = self.parent
        while node is not None:
            r = min(r, node.remaining())
            node = node.parent
        return r


def approx_chunk_bytes(chunk) -> int:
    """Cheap per-chunk estimate (exact byte-walks over object columns are
    O(rows) Python work — too hot for per-operator tracking)."""
    total = 0
    for c in chunk.columns:
        if c.data.dtype == object:
            total += 48 * len(c.data)  # pointer + typical small bytes
        else:
            total += c.data.nbytes
        total += c.nulls.nbytes
    return total
