"""Driver benchmark: TPC-H Q1 (SF from BENCH_SF env, default 1) through the
FULL SQL path — parse → plan → fused device kernel — on the real device,
vs the host (numpy) executor as the reference-CPU stand-in.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Hardened after round 1 (BENCH_r01.json rc=1, TPU backend init failure with
no output at all): the device backend is probed in a SUBPROCESS under a
timeout before any in-process jax computation; on probe failure the bench
falls back to the XLA CPU backend (device path = jitted XLA-on-CPU vs host
numpy — still a real number, flagged "fallback"). A SIGALRM watchdog
guarantees a JSON line even on a hang, and staged progress goes to stderr.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

import tidb_tpu  # noqa: F401  (x64 on)

from tidb_tpu.testkit import TestKit
from tidb_tpu.utils.chunk import Column

_STAGE = ["start"]


def _stage(msg: str) -> None:
    _STAGE[0] = msg
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _emit(obj) -> None:
    print(json.dumps(obj), flush=True)


def _probe_backend(timeout_s: int) -> str:
    """Initialize the default jax backend in a subprocess under a timeout.

    Returns the platform name ('tpu', 'axon', 'cpu', ...) or '' when the
    backend errors or hangs — in which case the parent process must force
    the CPU platform before touching jax, or it would hit the same failure.
    """
    code = ("import jax; jax.device_put(1).block_until_ready(); "
            "print('PLATFORM=' + jax.default_backend())")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return ""
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()[-1:] or [""]
        print(f"[bench] backend probe failed: {tail[0]}",
              file=sys.stderr, flush=True)
        return ""
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return ""

Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(1) as count_order
from lineitem
where l_shipdate <= '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""


def gen_lineitem(tk, sf: float):
    """Synthetic lineitem with TPC-H-like distributions, bulk-installed via
    the Lightning-role columnar loader (no per-row encode)."""
    n = int(6_001_215 * sf)
    rng = np.random.default_rng(42)
    tk.must_exec("create database if not exists tpch")
    tk.must_exec("use tpch")
    tk.must_exec("""
        create table lineitem (
            l_orderkey bigint, l_quantity decimal(15,2),
            l_extendedprice decimal(15,2), l_discount decimal(15,2),
            l_tax decimal(15,2), l_returnflag varchar(1),
            l_linestatus varchar(1), l_shipdate date)""")
    info = tk.domain.infoschema().table_by_name("tpch", "lineitem")

    orderkey = rng.integers(1, max(int(1_500_000 * sf), 2), n)
    qty = rng.integers(1, 51, n) * 100               # 1.00-50.00
    price = rng.integers(900_00, 105_000_00, n)      # ~dbgen price range
    disc = rng.integers(0, 11, n)                    # 0.00-0.10
    tax = rng.integers(0, 9, n)                      # 0.00-0.08
    # shipdate: 1992-01-01 .. 1998-12-01 in days-since-epoch
    d0 = (np.datetime64("1992-01-01") - np.datetime64("1970-01-01")).astype(int)
    d1 = (np.datetime64("1998-12-01") - np.datetime64("1970-01-01")).astype(int)
    shipdate = rng.integers(d0, d1, n).astype(np.int32)
    flag_codes = rng.integers(0, 3, n).astype(np.int32)
    status_codes = rng.integers(0, 2, n).astype(np.int32)
    flag_dict = np.array([b"A", b"N", b"R"], dtype=object)
    status_dict = np.array([b"F", b"O"], dtype=object)

    def strcol(codes, dictionary, ft):
        c = Column(ft, dictionary[codes], np.zeros(n, dtype=bool))
        c.set_dict(codes, dictionary)
        return c

    z = np.zeros(n, dtype=bool)
    cols = {c.name: c for c in info.public_columns()}
    data = {
        "l_orderkey": orderkey, "l_quantity": qty, "l_extendedprice": price,
        "l_discount": disc, "l_tax": tax, "l_shipdate": shipdate,
    }
    columns = {}
    for name, arr in data.items():
        c = cols[name]
        columns[c.id] = Column(c.ftype, arr, z)
    columns[cols["l_returnflag"].id] = strcol(
        flag_codes, flag_dict, cols["l_returnflag"].ftype)
    columns[cols["l_linestatus"].id] = strcol(
        status_codes, status_dict, cols["l_linestatus"].ftype)
    tk.domain.columnar_cache.install_bulk(
        info, columns, np.arange(1, n + 1, dtype=np.int64))
    return n


Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < '1995-03-15'
  and l_shipdate > '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""


def gen_orders_customer(tk, sf: float):
    """customer + orders with TPC-H-like sizes; lineitem l_orderkey values
    must already be in [1, n_orders] (gen_lineitem draws them that way)."""
    n_cust = int(150_000 * sf)
    n_orders = int(1_500_000 * sf)
    rng = np.random.default_rng(7)
    tk.must_exec("""
        create table customer (
            c_custkey bigint, c_mktsegment varchar(10))""")
    tk.must_exec("""
        create table orders (
            o_orderkey bigint, o_custkey bigint, o_orderdate date,
            o_shippriority bigint)""")
    segs = np.array([b"AUTOMOBILE", b"BUILDING", b"FURNITURE",
                     b"MACHINERY", b"HOUSEHOLD"], dtype=object)
    d0 = (np.datetime64("1992-01-01") - np.datetime64("1970-01-01")).astype(int)
    d1 = (np.datetime64("1998-08-02") - np.datetime64("1970-01-01")).astype(int)

    info = tk.domain.infoschema().table_by_name("tpch", "customer")
    cols = {c.name: c for c in info.public_columns()}
    z = np.zeros(n_cust, dtype=bool)
    seg_codes = rng.integers(0, 5, n_cust).astype(np.int32)
    seg_col = Column(cols["c_mktsegment"].ftype, segs[seg_codes], z)
    # set_dict requires sorted uniques; map codes through argsort
    order = np.argsort(segs)
    remap = np.empty_like(order)
    remap[order] = np.arange(5)
    seg_col.set_dict(remap[seg_codes], segs[order])
    tk.domain.columnar_cache.install_bulk(info, {
        cols["c_custkey"].id: Column(cols["c_custkey"].ftype,
                                     np.arange(1, n_cust + 1), z),
        cols["c_mktsegment"].id: seg_col,
    }, np.arange(1, n_cust + 1, dtype=np.int64))

    info = tk.domain.infoschema().table_by_name("tpch", "orders")
    cols = {c.name: c for c in info.public_columns()}
    z = np.zeros(n_orders, dtype=bool)
    tk.domain.columnar_cache.install_bulk(info, {
        cols["o_orderkey"].id: Column(cols["o_orderkey"].ftype,
                                      np.arange(1, n_orders + 1), z),
        cols["o_custkey"].id: Column(cols["o_custkey"].ftype,
                                     rng.integers(1, n_cust + 1, n_orders), z),
        cols["o_orderdate"].id: Column(
            cols["o_orderdate"].ftype,
            rng.integers(d0, d1, n_orders).astype(np.int32), z),
        cols["o_shippriority"].id: Column(
            cols["o_shippriority"].ftype,
            np.zeros(n_orders, dtype=np.int64), z),
    }, np.arange(1, n_orders + 1, dtype=np.int64))
    return n_orders


def time_query(tk, sql, repeats=3):
    best = float("inf")
    rows = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        rows = tk.must_query(sql).rows
        best = min(best, time.perf_counter() - t0)
    return best, rows


def main():
    watchdog_s = int(os.environ.get("BENCH_TIMEOUT_S", "2700"))

    def _on_alarm(signum, frame):
        _emit({"metric": "tpch_q1_bench", "value": 0, "unit": "rows/s",
               "vs_baseline": 0, "error": f"watchdog after {watchdog_s}s",
               "stage": _STAGE[0]})
        os._exit(1)

    signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(watchdog_s)

    _stage("probing device backend (subprocess)")
    probe_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "300"))
    platform = _probe_backend(probe_s)
    fallback = False
    if not platform:
        # Backend init failed/hung; force the XLA CPU platform for THIS
        # process (config.update is authoritative over plugin discovery).
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform, fallback = "cpu", True
    _stage(f"backend: {platform}{' (fallback)' if fallback else ''}")

    default_sf = "1" if not fallback else "0.1"
    sf = float(os.environ.get("BENCH_SF", default_sf))

    _stage(f"generating lineitem SF{sf:g}")
    tk = TestKit()
    # the bench measures engine throughput, not quota governance: lift the
    # per-statement memory quota so the host-reference run at SF>=1 isn't
    # cancelled by the OOM action
    tk.must_exec("set tidb_mem_quota_query = 0")
    n = gen_lineitem(tk, sf)

    _stage("device warmup (compile + columnar materialize)")
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    time_query(tk, Q1, repeats=1)
    _stage("device timed runs")
    dev_t, dev_rows = time_query(tk, Q1, repeats=3)

    _stage("host reference run")
    tk.must_exec("set tidb_executor_engine = 'host'")
    host_t, host_rows = time_query(tk, Q1, repeats=1)

    if dev_rows != host_rows:
        _emit({"metric": "tpch_q1_parity", "value": 0,
               "unit": "bool", "vs_baseline": 0, "platform": platform})
        sys.exit(1)

    signal.alarm(0)
    _emit({
        "metric": f"tpch_q1_sf{sf:g}_device_rows_per_sec",
        "value": round(n / dev_t),
        "unit": "rows/s",
        "vs_baseline": round(host_t / dev_t, 3),
        "platform": platform,
        "fallback": fallback,
        "device_s": round(dev_t, 4),
        "host_s": round(host_t, 4),
    })


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as exc:  # guarantee one JSON line, whatever happens
        _emit({"metric": "tpch_q1_bench", "value": 0, "unit": "rows/s",
               "vs_baseline": 0, "error": f"{type(exc).__name__}: {exc}",
               "stage": _STAGE[0]})
        sys.exit(1)
