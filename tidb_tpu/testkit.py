"""TestKit — the reference's single most important testing idea
(testkit/testkit.go:41): full-stack parse→plan→execute→MVCC tests against an
embedded store, with MustExec / MustQuery().Check(...) assertions."""

from __future__ import annotations

from .session import Domain, bootstrap_domain, new_session


class QueryResult:
    def __init__(self, result):
        self.result = result

    @property
    def rows(self):
        return self.result.rows

    def check(self, expected):
        """expected: list of tuples of display strings (None for NULL)."""
        got = [tuple(r) for r in self.result.rows]
        exp = [tuple(r) for r in expected]
        assert got == exp, f"\nexpected: {exp}\ngot:      {got}"

    def check_unordered(self, expected):
        got = sorted(map(tuple, self.result.rows), key=repr)
        exp = sorted(map(tuple, expected), key=repr)
        assert got == exp, f"\nexpected: {exp}\ngot:      {got}"

    def sort(self):
        self.result_rows = sorted(self.result.rows)
        return self


class TestKit:
    def __init__(self, domain: Domain | None = None):
        self.domain = domain or bootstrap_domain()
        self.session = new_session(self.domain)

    def must_exec(self, sql: str):
        results = self.session.execute(sql)
        return results[-1] if results else None

    def must_query(self, sql: str) -> QueryResult:
        results = self.session.execute(sql)
        return QueryResult(results[-1])

    def exec_error(self, sql: str) -> Exception:
        try:
            self.session.execute(sql)
        except Exception as e:
            return e
        raise AssertionError(f"expected error for: {sql}")

    def new_session(self) -> "TestKit":
        """Second session over the same domain (multi-connection tests)."""
        return TestKit(self.domain)
