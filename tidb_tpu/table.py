"""Row-level table abstraction over KV (reference: table/tables/tables.go —
AddRecord :643, UpdateRecord :331, RemoveRecord :1057; index maintenance in
table/tables/index.go).

Also home of the *columnar read path*: ``scan_columnar`` materializes a whole
table (or a key range) into a Chunk, which is what feeds device kernels. The
per-row KV codec is the transactional source of truth; the columnar cache on
top (storage layer) is the TiFlash-replica analog.
"""

from __future__ import annotations

import numpy as np

from . import tablecodec
from .errors import DupEntryError, TiDBError
from .model import SchemaState, TableInfo
from .sqltypes import (
    FLAG_UNSIGNED, INT_RANGES, INT_TYPES, STRING_TYPES,
    TYPE_DATE, TYPE_DATETIME, TYPE_DOUBLE, TYPE_DURATION, TYPE_FLOAT,
    TYPE_JSON, TYPE_LONGLONG, TYPE_NEWDATE, TYPE_NEWDECIMAL, TYPE_TIMESTAMP,
    FieldType, parse_date_str, parse_datetime_str, str_to_decimal, dec_rescale,
)
from .errors import OutOfRangeError, TypeError_
from .utils.chunk import Chunk, Column, np_dtype_for


def cast_value(v, ft: FieldType, truncate_as_error: bool = True):
    """Convert a parser/protocol value into the internal representation for
    column type `ft` (reference: table/column.go CastValue + types/convert.go).
    """
    if v is None:
        return None
    import decimal as _decimal
    tp = ft.tp
    if tp in INT_TYPES:
        # MySQL rounds half AWAY FROM ZERO on fractional→int, whatever
        # the carrier (string literal, double, Decimal) — python's
        # round()/int(float()) banker's/truncation semantics differ
        def _half_away(d):
            return int(d.to_integral_value(_decimal.ROUND_HALF_UP))
        if isinstance(v, bool):
            v = int(v)
        elif isinstance(v, (bytes, str)):
            s = v.decode() if isinstance(v, bytes) else v
            try:
                v = (_half_away(_decimal.Decimal(s))
                     if ("." in s or "e" in s.lower()) else int(s))
            except (ValueError, _decimal.InvalidOperation):
                if truncate_as_error:
                    raise TypeError_(f"Truncated incorrect INTEGER value: '{s}'")
                v = 0
        elif isinstance(v, float):
            v = _half_away(_decimal.Decimal(repr(v)))
        elif isinstance(v, _decimal.Decimal):
            v = _half_away(v)
        else:
            v = int(v)
        lo, hi, uhi = INT_RANGES.get(tp, INT_RANGES[TYPE_LONGLONG])
        if ft.flag & FLAG_UNSIGNED:
            if v < 0 or v > uhi:
                raise OutOfRangeError(f"Out of range value for column")
        elif v < lo or v > hi:
            raise OutOfRangeError(f"Out of range value for column")
        return v
    if tp == TYPE_NEWDECIMAL:
        scale = ft.scale
        if isinstance(v, (bytes, str)):
            s = v.decode() if isinstance(v, bytes) else v
            try:
                return str_to_decimal(s, scale)
            except ValueError:
                raise TypeError_(f"Truncated incorrect DECIMAL value: '{s}'")
        if isinstance(v, float):
            return str_to_decimal(repr(v), scale)
        if isinstance(v, _decimal.Decimal):
            return str_to_decimal(format(v, "f"), scale)
        if isinstance(v, tuple) and len(v) == 2:  # (scaled, scale) internal
            return dec_rescale(v[0], v[1], scale)
        return int(v) * 10 ** scale
    if tp in (TYPE_FLOAT, TYPE_DOUBLE):
        if isinstance(v, (bytes, str)):
            s = v.decode() if isinstance(v, bytes) else v
            try:
                return float(s)
            except ValueError:
                raise TypeError_(f"Truncated incorrect DOUBLE value: '{s}'")
        return float(v)
    if tp in (TYPE_DATE, TYPE_NEWDATE):
        if isinstance(v, (bytes, str)):
            s = v.decode() if isinstance(v, bytes) else v
            try:
                return parse_date_str(s)
            except ValueError:
                raise TypeError_(f"Incorrect DATE value: '{s}'")
        return int(v)
    if tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        if isinstance(v, (bytes, str)):
            s = v.decode() if isinstance(v, bytes) else v
            try:
                return parse_datetime_str(s)
            except ValueError:
                raise TypeError_(f"Incorrect DATETIME value: '{s}'")
        return int(v)
    if tp == TYPE_DURATION:
        if isinstance(v, (bytes, str)):
            s = v.decode() if isinstance(v, bytes) else v
            neg = s.startswith("-")
            if neg:
                s = s[1:]
            parts = s.split(":")
            frac = 0
            if "." in parts[-1]:
                parts[-1], fs = parts[-1].split(".")
                frac = int((fs + "000000")[:6])
            parts = [int(p) for p in parts]
            while len(parts) < 3:
                parts.insert(0, 0)
            us = (parts[0] * 3600 + parts[1] * 60 + parts[2]) * 1_000_000 + frac
            return -us if neg else us
        return int(v)
    if tp in STRING_TYPES or tp == TYPE_JSON:
        if isinstance(v, str):
            b = v.encode("utf-8")
        elif isinstance(v, (bytes, bytearray)):
            b = bytes(v)
        else:
            b = str(v).encode()
        if ft.flen not in (None, -1) and tp != TYPE_JSON and len(b) > max(ft.flen * 4, ft.flen):
            # flen is chars; utf8 up to 4B/char — cheap conservative check
            if len(b.decode("utf-8", "ignore")) > ft.flen:
                raise TypeError_(f"Data too long for column")
        return b
    return v


def convert_internal(v, src_ft: FieldType, dst_ft: FieldType):
    """Convert an *internal* value (scaled decimal, day/micros ints) from one
    field type to another — used when expression results flow into columns
    (INSERT ... SELECT, UPDATE SET, reference: types/convert.go)."""
    if v is None:
        return None
    import decimal as _decimal
    if isinstance(v, _decimal.Decimal):
        # user-facing decimal (eval_scalar product): already unscaled —
        # the exact string cast is correct at any target scale
        return cast_value(format(v, "f"), dst_ft)
    from .expression.core import phys_kind, K_DEC, K_DATE
    from .sqltypes import decimal_to_str
    sk = phys_kind(src_ft)
    if sk == K_DEC:
        if dst_ft.tp == TYPE_NEWDECIMAL:
            return dec_rescale(int(v), src_ft.scale, dst_ft.scale)
        return cast_value(decimal_to_str(int(v), src_ft.scale), dst_ft)
    src_dt = src_ft.tp in (TYPE_DATETIME, TYPE_TIMESTAMP)
    dst_dt = dst_ft.tp in (TYPE_DATETIME, TYPE_TIMESTAMP)
    src_d = src_ft.tp in (TYPE_DATE, TYPE_NEWDATE)
    dst_d = dst_ft.tp in (TYPE_DATE, TYPE_NEWDATE)
    if src_d and dst_dt:
        return int(v) * 86_400_000_000
    if src_dt and dst_d:
        return int(v) // 86_400_000_000
    if (src_d or src_dt) and not (dst_d or dst_dt):
        from .sqltypes import format_value
        return cast_value(format_value(int(v), src_ft), dst_ft)
    return cast_value(v, dst_ft)


def schema_fp(info: TableInfo) -> tuple:
    """Fingerprint of everything the write path's encoding depends on:
    column set/offsets/states and index set/states. A transaction records
    it per written table and the commit re-validates it against the then-
    current schema — if the online-DDL worker advanced an index or column
    state mid-statement, the buffered mutations may lack maintenance the
    new state requires (e.g. a delete-only index's entry removal), so the
    commit must fail retriably instead (reference: the commit-time schema
    check behind ErrInfoSchemaChanged + session/schema_amender.go)."""
    return (tuple((c.id, c.offset, c.state) for c in info.columns),
            tuple((i.id, i.state, i.unique) for i in info.indexes))


class Table:
    """Bound (TableInfo, txn) row operations.

    Partitioned tables (info.partition set) dispatch here too: writes route
    to one partition's physical id by the partition function; point reads
    search partitions in definition order (reference:
    table/tables/partition.go PartitionedTable)."""

    def __init__(self, info: TableInfo, txn, parts=None):
        self.info = info
        self.txn = txn
        self._part_fn = None
        self.parts = parts  # pruned PartitionDefs for reads (None = all)

    # -- partition dispatch --------------------------------------------------

    def _route(self, row: dict) -> "Table":
        """Partition-routed physical Table for this row dict."""
        from .partition import locate_partition, make_part_fn, partition_view
        if self._part_fn is None:
            self._part_fn = make_part_fn(self.info)
        pdef = locate_partition(self.info.partition, self._part_fn(row))
        return Table(partition_view(self.info, pdef), self.txn)

    def partition_tables(self, defs=None):
        """Physical Tables for each partition (or the given/pruned defs)."""
        from .partition import partition_view
        if defs is None:
            defs = self.parts if self.parts is not None \
                else self.info.partition.defs
        return [Table(partition_view(self.info, d), self.txn) for d in defs]

    # -- write path ---------------------------------------------------------

    def add_record(self, row: dict, handle: int, check_dup: bool = True):
        """row: {col_id: internal value}. Writes record + all index entries
        into the txn membuffer (reference: tables.go:643 AddRecord)."""
        if self.info.partition is not None:
            return self._route(row).add_record(row, handle, check_dup)
        info = self.info
        key = tablecodec.record_key(info.id, handle)
        if check_dup and info.pk_is_handle:
            if self.txn.get(key) is not None:
                raise DupEntryError(
                    f"Duplicate entry '{handle}' for key 'PRIMARY'")
        col_ids = [c.id for c in info.columns if c.state >= SchemaState.WRITE_ONLY and c.id in row]
        values = [row.get(cid) for cid in col_ids]
        self.txn.put(key, tablecodec.encode_row(col_ids, values))
        for idx in info.indexes:
            # delete-only / none-state indexes take deletes but not inserts
            # (F1 state machine, reference: ddl/index.go:519-541)
            if idx.state <= SchemaState.DELETE_ONLY:
                continue
            self._index_put(idx, row, handle, check_dup)
        self._mark_written(info)

    def _index_values(self, idx, row):
        vals = []
        for ic in idx.columns:
            col = self.info.columns[ic.offset]
            v = row.get(col.id)
            if isinstance(v, (bytes, bytearray)) and ic.length > 0:
                v = bytes(v)[:ic.length]
            vals.append(v)
        return vals

    def _index_put(self, idx, row, handle, check_dup=True):
        vals = self._index_values(idx, row)
        if idx.unique and not any(v is None for v in vals):
            key = tablecodec.index_key(self.info.id, idx.id, vals)
            existing = self.txn.get(key)
            if existing is not None and check_dup:
                raise DupEntryError(
                    "Duplicate entry '%s' for key '%s'" % (
                        "-".join(_dup_str(v) for v in vals), idx.name))
            self.txn.put(key, tablecodec.encode_index_handle(handle))
        else:
            key = tablecodec.index_key(self.info.id, idx.id, vals, handle=handle)
            self.txn.put(key, tablecodec.INDEX_VALUE_MARKER)

    def _index_delete(self, idx, row, handle):
        vals = self._index_values(idx, row)
        if idx.unique and not any(v is None for v in vals):
            key = tablecodec.index_key(self.info.id, idx.id, vals)
        else:
            key = tablecodec.index_key(self.info.id, idx.id, vals, handle=handle)
        self.txn.delete(key)

    def remove_record(self, row: dict, handle: int):
        if self.info.partition is not None:
            return self._route(row).remove_record(row, handle)
        self.txn.delete(tablecodec.record_key(self.info.id, handle))
        for idx in self.info.indexes:
            if idx.state >= SchemaState.DELETE_ONLY:
                self._index_delete(idx, row, handle)
        self._mark_written(self.info)

    def update_record(self, old_row: dict, new_row: dict, handle: int):
        if self.info.partition is not None:
            old_t = self._route(old_row)
            new_t = self._route(new_row)
            if old_t.info.id != new_t.info.id:
                # row moves between partitions: delete + insert
                old_t.remove_record(old_row, handle)
                new_t.add_record(new_row, handle)
                return
            return old_t.update_record(old_row, new_row, handle)
        info = self.info
        col_ids = [c.id for c in info.columns if c.state >= SchemaState.WRITE_ONLY and c.id in new_row]
        values = [new_row.get(cid) for cid in col_ids]
        self.txn.put(tablecodec.record_key(info.id, handle),
                     tablecodec.encode_row(col_ids, values))
        for idx in info.indexes:
            if idx.state < SchemaState.DELETE_ONLY:
                continue
            old_vals = self._index_values(idx, old_row)
            new_vals = self._index_values(idx, new_row)
            if old_vals != new_vals:
                self._index_delete(idx, old_row, handle)
                if idx.state > SchemaState.DELETE_ONLY:
                    self._index_put(idx, new_row, handle)
        self._mark_written(info)

    def _mark_written(self, info):
        self.txn.touched_tables.add(info.id)
        if not info.temporary:  # session-local: no shared schema to race
            self.txn.schema_fps.setdefault(info.id, schema_fp(info))

    # -- read path ----------------------------------------------------------

    def get_row(self, handle: int):
        if self.info.partition is not None:
            for pt in self.partition_tables():
                row = pt.get_row(handle)
                if row is not None:
                    return row
            return None
        data = self.txn.get(tablecodec.record_key(self.info.id, handle))
        if data is None:
            return None
        return tablecodec.decode_row(data)

    def iter_rows(self):
        """-> iterator of (handle, {col_id: value})."""
        if self.info.partition is not None:
            for pt in self.partition_tables():
                yield from pt.iter_rows()
            return
        start, end = tablecodec.table_range(self.info.id)
        for key, value in self.txn.scan(start, end):
            _tid, handle = tablecodec.decode_record_key(key)
            yield handle, tablecodec.decode_row(value)

    def index_lookup(self, idx, values):
        """Unique-index point lookup -> handle or None."""
        if self.info.partition is not None:
            for pt in self.partition_tables():
                h = pt.index_lookup(idx, values)
                if h is not None:
                    return h
            return None
        key = tablecodec.index_key(self.info.id, idx.id, values)
        v = self.txn.get(key)
        return tablecodec.decode_index_handle(v) if v is not None else None

    def index_scan_handles(self, idx, lo_vals=None, hi_vals=None):
        """Range scan on an index -> [handle] in index order."""
        if self.info.partition is not None:
            out = []
            for pt in self.partition_tables():
                out.extend(pt.index_scan_handles(idx, lo_vals, hi_vals))
            return out
        tid = self.info.id
        start = (tablecodec.index_key(tid, idx.id, lo_vals)
                 if lo_vals is not None else tablecodec.index_prefix(tid, idx.id))
        if hi_vals is not None:
            end = tablecodec.index_key(tid, idx.id, hi_vals) + b"\xff"
        else:
            end = tablecodec.index_prefix(tid, idx.id) + b"\xff" * 16
        out = []
        for key, value in self.txn.scan(start, end):
            h = tablecodec.decode_index_handle(value)
            out.append(h if h is not None
                       else tablecodec.decode_index_values(key)[-1])
        return out

    def scan_columnar(self, col_infos=None, with_handle=False, parts=None):
        """Materialize visible rows into a Chunk (columnar assembly from the
        row codec). col_infos: subset of ColumnInfo to project.
        parts: for a partitioned table, the PartitionDefs to scan."""
        info = self.info
        if info.partition is not None:
            from .utils.chunk import concat_chunks
            chunks = [pt.scan_columnar(col_infos, with_handle)
                      for pt in self.partition_tables(parts)]
            return concat_chunks(chunks)
        cols = col_infos if col_infos is not None else info.public_columns()
        handles = []
        rowdicts = []
        for handle, row in self.iter_rows():
            handles.append(handle)
            rowdicts.append(row)
        return rows_to_chunk(info, cols, handles, rowdicts, with_handle)


def rows_to_chunk(info: TableInfo, cols, handles, rowdicts, with_handle=False) -> Chunk:
    n = len(rowdicts)
    out = []
    for c in cols:
        dt = np_dtype_for(c.ftype)
        nulls = np.zeros(n, dtype=bool)
        # a column *absent* from a stored row (added by later DDL) takes the
        # column's origin default; an explicit NULL is stored as None
        default = c.default_value if c.has_default else None
        if dt is object:
            from .utils.chunk import null_fill_value
            null_fill = null_fill_value(c.ftype)
            data = np.empty(n, dtype=object)
            for i, rd in enumerate(rowdicts):
                v = rd.get(c.id, _ABSENT)
                if v is _ABSENT:
                    v = default
                if v is None:
                    data[i] = null_fill
                    nulls[i] = True
                else:
                    data[i] = v
        else:
            data = np.zeros(n, dtype=dt)
            for i, rd in enumerate(rowdicts):
                v = rd.get(c.id, _ABSENT)
                if v is _ABSENT:
                    v = default
                if v is None:
                    if info.pk_is_handle and c.id == info.pk_col_id:
                        data[i] = handles[i]
                    else:
                        nulls[i] = True
                else:
                    data[i] = v
        out.append(Column(c.ftype, data, nulls))
    if with_handle:
        ft = FieldType(tp=TYPE_LONGLONG)
        out.append(Column(ft, np.array(handles, dtype=np.int64),
                          np.zeros(n, dtype=bool)))
    return Chunk(out)


_ABSENT = object()


def _dup_str(v):
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)
