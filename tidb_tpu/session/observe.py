"""Observability state shared by all sessions of a Domain: slow-query log,
statement summary and a metrics registry.

Reference roles: slow log (`executor/slow_query.go` + SlowLogFormat in
sessionctx/variable/session.go), statement summary
(`util/stmtsummary/statement_summary.go`), Prometheus metrics
(`metrics/metrics.go:169`). All three are fed from one hook in the
session statement loop and read back through information_schema memtables,
keeping the reference's "observability is SQL-queryable" property."""

from __future__ import annotations

import collections
import logging
import threading
import time

log = logging.getLogger("tidb_tpu.observe")

#: serializes slow-query-file appends ACROSS Observability instances: a
#: multi-line SlowLogFormat entry bigger than the I/O buffer would
#: otherwise interleave with a concurrent session's entry and corrupt
#: both records for the parser (process-level because the file is)
_SLOW_FILE_LOCK = threading.Lock()

#: rendered-trace cap inside a slow-file entry (the memtable keeps the
#: full tree; the text file favors parseability over completeness)
_SLOW_FILE_TRACE_CAP = 8000

#: The per-layer latency histogram inventory (name -> bucket upper bounds
#: in SECONDS).  This literal dict is the registry the `gauge-consistency`
#: lint audits: every `observe_hist` call in the package must name a key
#: here, and every key must have a caller — the histogram analog of the
#: gauge inventory (README "Tracing").  /metrics renders each as proper
#: Prometheus `_bucket`/`_sum`/`_count` series so p99s are scrapeable
#: without bench.py.
HIST_BUCKETS = {
    # whole-statement wall clock (session/session.py statement loop)
    "statement_duration_seconds": (
        0.001, 0.005, 0.02, 0.1, 0.5, 2.5, 10.0, 60.0),
    # device admission queue wait (executor/scheduler.py queued path)
    "admission_wait_seconds": (
        0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0),
    # sync XLA compiles paid on the query path (executor/device_exec.py
    # observed_jit meter; background compiles deliberately excluded)
    "sync_compile_seconds": (
        0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 180.0),
    # one admitted device fragment end-to-end (executor/device_exec.py
    # run_device: supervisor + breaker + upload + dispatch)
    "device_dispatch_seconds": (
        0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0, 30.0),
    # hybrid hash join probe halves (executor/hybrid_join.py): the
    # device partitions' pipelined pass vs the supervisor worker's
    # concurrent numpy pass over the spilled partitions — the measured
    # inputs of the cost-based device/host split point
    "hj_probe_device_seconds": (
        0.001, 0.005, 0.02, 0.1, 0.5, 2.5, 10.0, 60.0),
    "hj_probe_host_seconds": (
        0.001, 0.005, 0.02, 0.1, 0.5, 2.5, 10.0, 60.0),
    # fleet-frontier freshness wait at ts acquisition (session/session.py
    # Domain hookup of kv/shared_store.fresh_read_ts): 0 on the fast
    # path, up to the FRESHNESS_BUDGET_MS refusal ceiling when blocked
    # behind a lagging origin's durable commit frontier
    "freshness_wait_seconds": (
        0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0),
}


class Histogram:
    """Fixed-bucket latency histogram (reference: the prometheus client's
    cumulative-bucket model, rendered by server/http_status.py)."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        v = float(value)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class SlowQueryItem:
    __slots__ = ("ts", "user", "db", "duration_s", "digest", "sql",
                 "rows", "succ", "plan", "trace")

    def __init__(self, ts, user, db, duration_s, digest, sql, rows, succ,
                 plan="", trace=""):
        self.ts = ts
        self.user = user
        self.db = db
        self.duration_s = duration_s
        self.digest = digest
        self.sql = sql
        self.rows = rows
        self.succ = succ
        self.plan = plan
        # the statement's rendered span tree when it was traced
        # (session/tracing.py) — the causal timeline right next to the
        # slow entry, readable back through information_schema.slow_query
        self.trace = trace


class StmtSummary:
    """Per-digest aggregate (reference: stmtSummaryByDigest)."""

    __slots__ = ("digest", "sample_sql", "db", "exec_count", "sum_latency",
                 "max_latency", "min_latency", "sum_rows", "first_seen",
                 "last_seen", "err_count")

    def __init__(self, digest, sample_sql, db, now=None):
        self.digest = digest
        self.sample_sql = sample_sql
        self.db = db
        self.exec_count = 0
        self.sum_latency = 0.0
        self.max_latency = 0.0
        self.min_latency = float("inf")
        self.sum_rows = 0
        self.first_seen = now if now is not None else time.time()
        self.last_seen = self.first_seen
        self.err_count = 0

    def add(self, latency_s, rows, succ, now=None):
        self.exec_count += 1
        self.sum_latency += latency_s
        self.max_latency = max(self.max_latency, latency_s)
        self.min_latency = min(self.min_latency, latency_s)
        self.sum_rows += rows
        self.last_seen = now if now is not None else time.time()
        if not succ:
            self.err_count += 1


class Observability:
    def __init__(self, slow_log_cap=1024, summary_cap=512):
        self._lock = threading.Lock()
        self.slow_queries = collections.deque(maxlen=slow_log_cap)
        self.stmt_summary: "collections.OrderedDict[str, StmtSummary]" = \
            collections.OrderedDict()
        self._summary_cap = summary_cap
        # metrics: flat counter/gauge registry (reference: metrics/metrics.go)
        self.counters = collections.Counter()
        # gauges are SET, not incremented: point-in-time values like the
        # supervisor's "abandoned device calls outstanding"
        # (executor/supervisor.py publishes into every registered sink)
        self.gauges: dict = {}
        # per-layer latency histograms (HIST_BUCKETS registry above)
        self.histograms: dict[str, Histogram] = {}

    def inc(self, name, n=1):
        with self._lock:
            self.counters[name] += n

    def set_gauge(self, name, value):
        with self._lock:
            self.gauges[name] = value

    def gauge_snapshot(self) -> dict:
        with self._lock:
            return dict(self.gauges)

    def observe_hist(self, name, value):
        """Record one latency sample into a registered histogram.  Names
        must come from HIST_BUCKETS (lint-pinned); an unregistered name
        still records (with a default ladder) rather than failing the
        caller's statement."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(
                    HIST_BUCKETS.get(
                        name, (0.001, 0.01, 0.1, 1.0, 10.0)))
            h.observe(value)

    def hist_snapshot(self) -> dict:
        """name -> (bounds, per-bucket counts, sum, count) — consumed by
        the /metrics renderer (server/http_status.py)."""
        with self._lock:
            return {name: (h.bounds, list(h.counts), h.sum, h.count)
                    for name, h in self.histograms.items()}

    def observe_stmt(self, *, user, db, sql, digest, latency_s, rows, succ,
                     slow_threshold_s, plan="", trace="",
                     slow_query_file=""):
        # item construction (and the wall-clock reads) happen OUTSIDE the
        # lock: N concurrent sessions funnel through this hook per
        # statement, and the critical section must stay counter/append
        # sized — not time.time()-twice-plus-allocation sized
        now = time.time()
        slow_item = None
        if latency_s >= slow_threshold_s:
            slow_item = SlowQueryItem(now, user, db, latency_s, digest,
                                      sql, rows, succ, plan, trace)
        with self._lock:
            st = self.stmt_summary.get(digest)
            if st is None:
                while len(self.stmt_summary) >= self._summary_cap:
                    self.stmt_summary.popitem(last=False)
                st = self.stmt_summary[digest] = StmtSummary(digest, sql,
                                                             db, now=now)
            st.add(latency_s, rows, succ, now=now)
            self.counters["executor_statement_total"] += 1
            if not succ:
                self.counters["executor_statement_error_total"] += 1
            if slow_item is not None:
                self.slow_queries.append(slow_item)
        if slow_item is not None and slow_query_file:
            self._append_slow_file(slow_query_file, slow_item)

    def _append_slow_file(self, path: str, it: SlowQueryItem):
        """SlowLogFormat-style text append (reference: the slow-log file
        executor/slow_query.go parses back;
        sessionctx/variable/session.go SlowLogFormat).  A write failure
        is LOGGED CLASSIFIED, never swallowed and never allowed to fail
        the statement."""
        try:
            ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(it.ts))
            lines = [
                f"# Time: {ts}.{int((it.ts % 1) * 1e6):06d}",
                f"# User@Host: {it.user}",
                f"# Db: {it.db}",
                f"# Query_time: {it.duration_s:.6f}",
                f"# Digest: {it.digest}",
                f"# Result_rows: {it.rows}",
                f"# Succ: {'true' if it.succ else 'false'}",
            ]
            if it.trace:
                lines += ["# Trace: " + ln for ln in
                          it.trace[:_SLOW_FILE_TRACE_CAP].splitlines()]
            sql = it.sql.rstrip(";")
            lines.append(sql + ";")
            payload = "\n".join(lines) + "\n"
            with _SLOW_FILE_LOCK:
                with open(path, "a") as f:
                    f.write(payload)
        except Exception as e:
            from ..utils.backoff import classify
            log.warning("slow-query-file append failed (%s, path=%s): %s",
                        classify(e), path, e)
