"""Telemetry collector (reference: telemetry/telemetry.go:46,128 + data.go).

The reference reports cluster/hardware/feature-usage payloads weekly to an
external endpoint when enabled. Here the collector builds the SAME payload
shape but NEVER leaves the process: reporting is disabled by default
(tidb_enable_telemetry = OFF) and "reporting" appends to an in-memory
history the operator can inspect via ADMIN SHOW TELEMETRY — the privacy
default the task environment requires (zero egress)."""

from __future__ import annotations

import json
import platform
import threading
import time


def enabled(domain) -> bool:
    v = str(domain.global_vars.get("tidb_enable_telemetry", "OFF"))
    return v.upper() in ("ON", "1", "TRUE")


def collect(domain) -> dict:
    """Build the usage payload (reference: telemetry/data.go
    generateTelemetryData: cluster info, hardware, feature usage)."""
    infos = domain.infoschema()
    n_tables = n_views = n_sequences = n_partitioned = 0
    for db in infos.schema_names():
        for t in infos.tables_in_schema(db):
            if t.is_view:
                n_views += 1
            elif t.is_sequence:
                n_sequences += 1
            else:
                n_tables += 1
                if t.partition is not None:
                    n_partitioned += 1
    counters = dict(getattr(domain.observe, "counters", {}))
    return {
        "trackingID": f"tpu-htap-{id(domain) & 0xFFFF:04x}",
        "reportTimestamp": int(time.time()),
        "cluster": {
            "storeBackend": domain.store.backend,
            "schemaVersion": infos.version,
        },
        "hardware": {
            "os": platform.system().lower(),
            "arch": platform.machine(),
        },
        "featureUsage": {
            "tables": n_tables, "views": n_views,
            "sequences": n_sequences, "partitionedTables": n_partitioned,
            "bindings": len(domain.bind_handle.list()),
            "counters": counters,
        },
    }


class Telemetry:
    """Domain-held collector with the weekly-loop shape (reference:
    domain/domain.go telemetry loop); report() is a local append."""

    def __init__(self, domain):
        self.domain = domain
        self._lock = threading.Lock()
        self.history: list[dict] = []

    def report_once(self) -> dict | None:
        if not enabled(self.domain):
            return None
        payload = collect(self.domain)
        with self._lock:
            self.history.append(payload)
            del self.history[:-16]  # bounded
        return payload

    def preview(self) -> str:
        """What WOULD be reported (ADMIN SHOW TELEMETRY), regardless of
        the enable switch — the reference shows the payload on demand."""
        return json.dumps(collect(self.domain), indent=1, sort_keys=True)
