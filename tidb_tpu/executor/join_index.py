"""Host-built join indexes for the device join path.

The reference probes a hash table built per query execution
(executor/join.go:192 build workers, hash_table.go). On XLA that design
loses twice: hash tables need data-dependent shapes, and the sort-based
replacement re-sorts the build side on EVERY execution. But a join whose
build side is a BASE TABLE scan has a data-dependent part that only
changes when the table version changes — so the expensive part (ordering
the build rows by key) moves to the host, runs ONCE per table version in
numpy, and is cached on the Column exactly like the HBM upload
(utils/chunk.py Column._device). The device-side lookup degenerates to
gathers and searchsorteds — no sort in the compiled program at all.

Two layouts:
- ``dense`` — CSR over the key span (``starts`` of size span+1, ``rows``
  listing valid row ids in key order). Applies when the packed key span
  is within a small factor of the row count: TPC-H keys are dense
  1..N, so every PK/FK join takes this path. Lookup = 2 gathers.
- ``sorted`` — row ids argsorted by packed key + the sorted key array.
  Applies to sparse/composite keys (e.g. partsupp's (partkey, suppkey)
  whose packed span is ~nb²). Lookup = binary search into the
  host-sorted array.

Either layout knows whether the (non-null) build keys are UNIQUE. A
unique build side makes the join output shape the PROBE side's shape —
the expansion pass, its output capacity, and the overflow/recompile
machinery all disappear (TPC-H joins are fact⋈dim = FK⋈unique-PK, so
this is the common case on every north-star query).

Multi-column keys fold into one int64 by range packing with host-known
(min, span) per column — unlike the device-side data-dependent packing
(device_join._combined_join_keys), these are static at trace time.

Version tolerance (ROADMAP "version-tolerant pack"): the per-column
(min, span) is QUANTIZED to a geometric grid (`_quantize_range`) instead
of being exact.  The packs are baked into compiled-fragment signatures
and dense-CSR array shapes (`device_join._strategy_sig`,
`JoinIndex.starts`), so with exact bounds ANY dimension-table delta that
nudged a key's min/max — one UPDATE widening a range by 1 — changed the
signature and forced a full XLA recompile.  With ~1/16-of-magnitude
slack on each end, a delta that stays inside the widened range rebuilds
only the (cheap, numpy) host index and re-uses the compiled fragment:
the lookup arrays are passed as runtime arguments, so same shapes ⇒ same
program.  Correctness is unaffected — probe keys in the slack region
simply find zero matches, exactly like any other unmatched key.

Bucketed shapes + traced n_valid (ROADMAP item 1, the LAST recompile
trigger): the row-id array (and the sorted-key array) pads to a
geometric bucket (ops/device.py bucket_rows) and the live entry count
``n_valid`` rides to the device as a TRACED scalar in the ``jidx``
runtime arguments — never baked into the compiled program.  A build-side
INSERT that stays inside the bucket (and inside the quantized pack
range) rebuilds only this cheap numpy index: same array shapes, same
fragment signature, same compiled executable, zero new XLA compiles.
Padding is inert by construction: ``rows`` pads with 0 (only reachable
behind a ``cnt`` guard that is 0 there) and ``sorted_keys`` pads with
int64 max (sorts after every real key, so probe searchsorted results
for real keys are unchanged and the ``lo < n_valid`` guard kills the
sentinel region).
"""

from __future__ import annotations

import numpy as np

from ..ops.device import bucket_rows

#: dense CSR is worth it while the span stays within this factor of the
#: row count (beyond that the starts array dwarfs the table)
_DENSE_SLACK = 4
_DENSE_FLOOR = 65536

#: pack quantization: grid = 2^(bit_length(span)-1-SLACK_BITS) ≈ span/16
#: (min floors to the grid, max ceils) — ≤ ~12.5% span overshoot buys
#: signature stability across small dimension-table range drifts
_PACK_SLACK_BITS = 4


def _quantize_range(mn: int, mx: int) -> tuple[int, int]:
    """Widen [mn, mx] to a geometric grid so slightly-shifted bounds from
    a future table version land on the SAME packed range."""
    span = mx - mn + 1
    g = 1 << max((span - 1).bit_length() - _PACK_SLACK_BITS, 0)
    mn_q = (mn // g) * g                # floor toward -inf
    mx_q = ((mx // g) + 1) * g - 1      # ceil to the next grid edge - 1
    return mn_q, mx_q


class JoinIndex:
    """Host index over one ordered key-column tuple of a base chunk."""

    __slots__ = ("kind", "packs", "unique", "n_rows", "n_valid", "span",
                 "starts", "rows", "sorted_keys", "avg_cnt", "max_cnt",
                 "rows_len", "_dev")

    def __init__(self):
        self._dev = None

    def device_arrays(self):
        """Upload (lazily, once) and return the (a0, a1, n_valid) lookup
        tuple the compiled fragment takes as runtime arguments: the CSR
        starts / sorted keys, the bucket-padded row ids, and the live
        entry count as a TRACED scalar (np.int64, the n_lives
        convention) — a same-shape index refresh re-dispatches the
        compiled program without retracing."""
        if self._dev is None:
            import jax.numpy as jnp
            a0 = self.starts if self.kind == "dense" else self.sorted_keys
            self._dev = (jnp.asarray(a0), jnp.asarray(self.rows),
                         np.int64(self.n_valid))
        return self._dev


def _pack_host(datas, valid, packs):
    """Fold key columns into one int64 per row (valid rows only are
    meaningful; invalid rows fold to arbitrary in-range values)."""
    packed = np.zeros(len(datas[0]), dtype=np.int64)
    for d, (mn, span) in zip(datas, packs):
        v = d.astype(np.int64) - mn
        np.clip(v, 0, span - 1, out=v)
        packed = packed * span + v
    return packed


def build_join_index(columns, mask_fn=None, cache_tag="", packs=None,
                     force_sorted=False,
                     pad_rows=None) -> "JoinIndex | None":
    """Index over `columns` (utils.chunk.Column tuple, int-kinded numpy
    data), cached on columns[0]. None when the keys can't range-pack into
    int64 (caller falls back to the device-side sort join).

    mask_fn/cache_tag: optional build-side FILTER — the leaf's pushed-down
    predicates evaluated host-side (lazily, only on cache miss). A
    filtered index drops non-qualifying rows from the CSR counts, so an
    expansion join's capacity tracks the SELECTED rows, not the raw table
    (TPC-H Q5's orders⋈customer leg shrinks ~7x: the date filter keeps
    15% of orders but an unfiltered count expands all of them). The tag
    keys the cache per predicate set; one Column can hold one index at a
    time (queries alternating predicate sets rebuild — numpy, cheap).

    packs / force_sorted / pad_rows override the shape-determining
    choices for PARTITIONED builds (executor/hybrid_join.py): every radix
    partition of one hybrid join must carry the SAME per-column (min,
    span) packs, the same layout kind and the same padded array length —
    otherwise each partition would bake its own shapes into the fragment
    signature and the zero-recompile invariant would die P ways.  `packs`
    are the whole-table quantized ranges (probe keys outside a
    partition's narrower true range simply find no match); force_sorted
    skips the dense-CSR layout (a per-partition `starts` array spans the
    WHOLE key range — P copies of it would dwarf the data); `pad_rows`
    floors the bucket so all partitions pad to the largest one's."""
    host = columns[0]
    # the cached tuple PINS the column objects: a live reference can never
    # share its id with a newly allocated Column, which is what makes the
    # id()-keyed composite lookup sound (same convention as the pipeline
    # cache's dict_refs, executor/device_exec.py)
    cache_key = (tuple(id(c) for c in columns), cache_tag, packs,
                 force_sorted, pad_rows)
    cached = getattr(host, "_join_index", None)
    if cached is not None and cached[0] == cache_key:
        return cached[1]

    datas = [c.data for c in columns]
    nulls = columns[0].nulls
    for c in columns[1:]:
        nulls = nulls | c.nulls
    valid = ~nulls
    if mask_fn is not None:
        m = mask_fn()
        if m is not None:
            valid = valid & m
    nb = len(datas[0])
    n_valid = int(valid.sum())

    if packs is not None:
        total_span = 1.0
        for _mn, span in packs:
            total_span *= span
        packs = list(packs)
    else:
        packs = []
        total_span = 1.0
        for d in datas:
            dv = d[valid]
            if dv.size == 0:
                mn, mx = 0, 0
            else:
                mn, mx = int(dv.min()), int(dv.max())
            # slack-quantized range: within-slack deltas keep the pack —
            # and therefore the fragment signature and compiled program —
            # stable
            mn, mx = _quantize_range(mn, mx)
            span = mx - mn + 1
            total_span *= span
            packs.append((mn, span))
    if total_span > 2.0**62:
        # the negative entry must pin the columns too — id() keys are
        # only sound while the referenced objects stay alive
        host._join_index = (cache_key, None, tuple(columns))
        return None

    idx = JoinIndex()
    idx.packs = tuple(packs)
    idx.n_rows = nb
    idx.n_valid = n_valid
    span_total = int(total_span)
    idx.span = span_total
    packed = _pack_host(datas, valid, packs)

    row_dt = np.int32 if nb < (1 << 31) else np.int64
    # geometric BUCKET for the row-id (and sorted-key) array shapes: a
    # within-bucket build delta keeps every traced shape — the default
    # granularity (2 buckets per doubling) is fixed here because the
    # index is cached per table version, not per session
    pad_len = bucket_rows(max(n_valid, pad_rows or 1, 1))
    idx.rows_len = pad_len

    def _pad_rows(arr):
        out = np.zeros(pad_len, dtype=row_dt)
        out[:len(arr)] = arr
        return out

    if not force_sorted and span_total <= max(_DENSE_SLACK * nb,
                                              _DENSE_FLOOR):
        idx.kind = "dense"
        counts = np.bincount(packed[valid], minlength=span_total)
        starts = np.empty(span_total + 1, dtype=row_dt)
        starts[0] = 0
        np.cumsum(counts, out=starts[1:])
        # row ids grouped by key: stable argsort with invalid rows parked
        # past every real key
        sort_key = np.where(valid, packed, np.int64(span_total))
        order = np.argsort(sort_key, kind="stable")
        idx.starts = starts
        idx.rows = _pad_rows(order[:n_valid])
        idx.max_cnt = int(counts.max(initial=0))
        idx.unique = idx.max_cnt <= 1
        idx.sorted_keys = None
        idx.avg_cnt = n_valid / max(int(np.count_nonzero(counts)), 1)
    else:
        idx.kind = "sorted"
        sort_key = np.where(valid, packed, np.iinfo(np.int64).max)
        order = np.argsort(sort_key, kind="stable")
        sk = sort_key[order[:n_valid]] if n_valid else np.zeros(
            0, dtype=np.int64)
        # int64-max sentinels on the pad tail sort after every real key:
        # probe searchsorted positions for real keys are unchanged, and
        # the traced lo < n_valid guard excludes the sentinel region
        idx.sorted_keys = np.concatenate(
            [sk, np.full(pad_len - n_valid, np.iinfo(np.int64).max,
                         dtype=np.int64)])
        idx.rows = _pad_rows(order[:n_valid])
        idx.starts = None
        idx.unique = bool(n_valid <= 1 or not np.any(sk[1:] == sk[:-1]))
        n_distinct = (1 + int(np.count_nonzero(sk[1:] != sk[:-1]))
                      if n_valid else 1)
        idx.avg_cnt = n_valid / max(n_distinct, 1)
        if n_valid:
            # longest equal-key run = the hottest key's row count
            bounds = np.flatnonzero(np.concatenate(
                ([True], sk[1:] != sk[:-1], [True])))
            idx.max_cnt = int(np.diff(bounds).max())
        else:
            idx.max_cnt = 0
    host._join_index = (cache_key, idx, tuple(columns))
    return idx
