"""AOT compile-cache host fingerprinting (satellite, MULTICHIP_r05
finding): XLA:CPU's persistent-cache key ignores host CPU features, so an
artifact compiled on another machine loads with a ~3KB "could lead to
SIGILL" warning per program and mis-tuned code. The cache directory —
default AND explicit TIDB_TPU_JAX_CACHE=<dir> — is scoped by a
(cpu-flags, machine-arch, jax-version) fingerprint subdirectory, making
mismatched artifacts unreachable: they are skipped silently, never loaded
with a warning flood."""

import os
import subprocess
import sys

import jax

import tidb_tpu


class TestHostFingerprint:
    def test_stable_and_hexish(self):
        fp = tidb_tpu._host_fingerprint()
        assert fp == tidb_tpu._host_fingerprint()
        assert len(fp) == 12
        assert all(c in "0123456789abcdef" for c in fp)

    def test_this_process_cache_dir_is_fingerprint_scoped(self):
        cache_dir = jax.config.jax_compilation_cache_dir
        if not cache_dir:
            # operator opted out (TIDB_TPU_JAX_CACHE=off) or config
            # failed: nothing to scope
            assert os.environ.get("TIDB_TPU_JAX_CACHE") == "off"
            return
        assert os.path.basename(cache_dir) == tidb_tpu._host_fingerprint()

    def test_explicit_dir_is_scoped_too(self, tmp_path):
        """A SHARED explicit cache dir (network mount) must still key by
        host fingerprint: artifacts a different machine wrote land in a
        sibling subdirectory and can never be picked up here."""
        out = subprocess.run(
            [sys.executable, "-c",
             "import tidb_tpu, jax; "
             "print(jax.config.jax_compilation_cache_dir); "
             "print(tidb_tpu._host_fingerprint())"],
            env={**os.environ, "TIDB_TPU_JAX_CACHE": str(tmp_path),
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120, check=True)
        cache_dir, fp = out.stdout.strip().splitlines()[-2:]
        assert cache_dir == os.path.join(str(tmp_path), fp)
        # a foreign machine's artifacts would sit in a DIFFERENT subdir:
        # same parent, disjoint leaf — unreachable by construction
        foreign = os.path.join(str(tmp_path), "0" * 12)
        assert foreign != cache_dir
        assert os.path.dirname(foreign) == os.path.dirname(cache_dir)


_PERSIST_WORKLOAD = r"""
import json
from tidb_tpu.testkit import TestKit
from tidb_tpu.executor import compile_service

tk = TestKit()
tk.must_exec("use test")
tk.must_exec("create table p (id int primary key, g int, v int)")
rows = ",".join(f"({i},{i%5},{(i*31)%97})" for i in range(200))
tk.must_exec(f"insert into p values {rows}")
# pin the group-count estimate: the compiled-pipeline capacity rides the
# stats, and the persistent-index key must be IDENTICAL across processes
tk.must_exec("analyze table p")
q = "select g, sum(v), count(*) from p group by g order by g"
tk.must_exec("set tidb_executor_engine = 'host'")
host = [[str(c) for c in r] for r in tk.must_query(q).rows]
tk.must_exec("set tidb_executor_engine = 'tpu'")
dev = [[str(c) for c in r] for r in tk.must_query(q).rows]
snap = compile_service.snapshot()
print(json.dumps({"rows": dev, "host": host,
                  "persist_hits": snap["compile_persist_hits"],
                  "sync_compiles": snap["sync_compiles"]}))
"""


class TestPersistentExecutableCache:
    """ISSUE 8 acceptance: a fresh subprocess against a populated
    persistent cache reports compile_persist_hits > 0 and bit-exact
    query results vs host goldens — a process restart (or a second
    serving process on the same cache mount) starts WARM: the signature
    index (executor/compile_service.py pipe-index/) marks what compiled
    here, and the jax AOT cache underneath holds the executables."""

    def _run(self, cache_dir):
        import json
        out = subprocess.run(
            [sys.executable, "-c", _PERSIST_WORKLOAD],
            env={**os.environ, "TIDB_TPU_JAX_CACHE": str(cache_dir),
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=240, check=True)
        return json.loads(out.stdout.strip().splitlines()[-1])

    def test_second_process_starts_warm_and_bit_exact(self, tmp_path):
        first = self._run(tmp_path)
        assert first["rows"] == first["host"]
        assert first["sync_compiles"] >= 1  # cold: built + recorded
        second = self._run(tmp_path)
        # the restart is WARM: the cold obtain found its signature in the
        # index (the "compile" under it is an AOT-cache deserialize)...
        assert second["persist_hits"] > 0
        # ...and the deserialized executable computes the same bits
        assert second["rows"] == second["host"] == first["host"]
