import os, sys, time
sys.path.insert(0, "/root/repo")
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
import tidb_tpu
import numpy as np, jax.numpy as jnp
from tidb_tpu.ops import device as dev

n, ndv, cap = 600_000, 150_000, 262_144
rng = np.random.default_rng(0)
key = jnp.asarray(rng.integers(1, ndv+1, n))
knull = jnp.zeros(n, dtype=bool)
val = jnp.asarray(rng.integers(100, 5000, n))
mask = jnp.ones(n, dtype=bool)

def timeit(label, f):
    f()  # compile
    t0 = time.perf_counter(); r = [f() for _ in range(5)]
    jax.block_until_ready(r)
    print(f"{label}: {(time.perf_counter()-t0)/5*1000:.1f} ms")

# full kernel, packed (18 bits) vs unpacked
timeit("agg packed", lambda: dev._agg_kernel((key,), (knull,), (val,), (knull,), mask,
        n_keys=1, agg_ops=("sum_i",), capacity=cap, pack=((18, 0),)))
timeit("agg unpacked", lambda: dev._agg_kernel((key,), (knull,), (val,), (knull,), mask,
        n_keys=1, agg_ops=("sum_i",), capacity=cap, pack=None))
# pieces
timeit("argsort i32", lambda: jnp.argsort(key.astype(jnp.int32), stable=True))
timeit("argsort i64", lambda: jnp.argsort(key, stable=True))
f_topk = jax.jit(lambda x: jax.lax.top_k(-x, cap)[0])
timeit("top_k cap", lambda: f_topk(key))
gid = jnp.sort(key)
f_ss = jax.jit(lambda g: (jnp.searchsorted(g, jnp.arange(cap), side="left"),
                          jnp.searchsorted(g, jnp.arange(cap), side="right")))
timeit("2x searchsorted cap", lambda: f_ss(gid))
timeit("cumsum", lambda: jnp.cumsum(val))
timeit("gather n", lambda: val[key % n])
