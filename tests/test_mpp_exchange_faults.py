"""MPP exchange retry path under injected send/recv faults (satellite:
executor/mpp_exec dispatch loop — previously only exercised incidentally).

The conftest's 8 virtual CPU devices stand in for the mesh; faults fire at
the exchange boundary of the shard_map-jitted fragment dispatch."""

import pytest

from tidb_tpu.errors import BackoffExhaustedError, ErrCode
from tidb_tpu.executor.mpp_exec import MPP_STATS
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (a int, b int)")
    tk.must_exec("insert into t values " + ",".join(
        f"({i % 5},{i})" for i in range(400)))
    return tk


Q = "select a, sum(b) from t group by a order by a"


def _golden(tk):
    tk.must_exec("set tidb_executor_engine = 'host'")
    rows = tk.must_query(Q).rows
    tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
    return rows


class TestExchangeFaults:
    def test_transient_send_fault_retried_exact(self, tk):
        golden = _golden(tk)
        before = MPP_STATS["exchange_retries"]
        with failpoint.enabled("mpp-exchange-send", "2*panic"):
            assert tk.must_query(Q).rows == golden
        assert MPP_STATS["exchange_retries"] - before == 2

    def test_transient_recv_fault_retried_exact(self, tk):
        golden = _golden(tk)
        with failpoint.enabled("mpp-exchange-recv", "1*panic"):
            assert tk.must_query(Q).rows == golden

    def test_persistent_fault_exhausts_classified(self, tk):
        _golden(tk)
        with failpoint.enabled("mpp-exchange-send", "panic"):
            e = tk.exec_error(Q)
        assert isinstance(e, BackoffExhaustedError)
        assert e.code == ErrCode.BackoffExhausted
        assert e.retry_kind == "exchangeRetry"
        assert e.error_class == "exchange"

    def test_exhaustion_feeds_device_breaker(self, tk):
        from tidb_tpu.executor.circuit import get_breaker
        _golden(tk)
        before = get_breaker(tk.session).snapshot()["failures"]
        with failpoint.enabled("mpp-exchange-recv", "panic"):
            tk.exec_error(Q)
        assert get_breaker(tk.session).snapshot()["failures"] == before + 1

    def test_recovery_after_fault_clears(self, tk):
        golden = _golden(tk)
        with failpoint.enabled("mpp-exchange-send", "panic"):
            tk.exec_error(Q)
        assert tk.must_query(Q).rows == golden

    def test_join_fragment_send_fault_retried(self, tk):
        tk.must_exec("create table o (id int, ref int, amt int)")
        tk.must_exec("insert into o values " + ",".join(
            f"({i},{i % 400},{i % 50})" for i in range(300)))
        qj = ("select t.a, sum(o.amt) from t join o on t.b = o.ref "
              "group by t.a order by t.a")
        tk.must_exec("set tidb_executor_engine = 'host'")
        golden = tk.must_query(qj).rows
        tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
        with failpoint.enabled("mpp-exchange-send", "1*panic"):
            assert tk.must_query(qj).rows == golden

    def test_join_exhaustion_charges_join_breaker_not_agg(self, tk):
        """A join-tree MPP fragment's exchange exhaustion must charge the
        JOIN-shape breaker — charging "agg" (the pre-fix default) would
        open the healthy agg breaker from join faults and orphan a join
        probe's verdict."""
        from tidb_tpu.executor.circuit import get_breaker
        tk.must_exec("create table o2 (id int, ref int, amt int)")
        tk.must_exec("insert into o2 values " + ",".join(
            f"({i},{i % 400},{i % 50})" for i in range(300)))
        qj = ("select t.a, sum(o2.amt) from t join o2 on t.b = o2.ref "
              "group by t.a order by t.a")
        tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
        tk.must_query(qj)  # warm: the fragment must reach the exchange
        agg0 = get_breaker(tk.session, shape="agg").snapshot()["failures"]
        join0 = get_breaker(tk.session, shape="join").snapshot()["failures"]
        with failpoint.enabled("mpp-exchange-send", "panic"):
            e = tk.exec_error(qj)
        assert isinstance(e, BackoffExhaustedError)
        assert get_breaker(tk.session, shape="join").snapshot()[
            "failures"] == join0 + 1
        assert get_breaker(tk.session, shape="agg").snapshot()[
            "failures"] == agg0
