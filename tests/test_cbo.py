"""Cost-based access paths: PointGet / IndexLookUp / full scan chosen by
selectivity, with plan-independent results (reference:
planner/core/point_get_plan.go:467 TryFastPlan,
planner/core/find_best_task.go:359, executor/point_get.go,
executor/distsql.go, statistics/histogram.go:50)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database cbo")
    tk.must_exec("use cbo")
    tk.must_exec("""create table t (
        id bigint primary key, grp bigint, val decimal(10,2),
        name varchar(20), key idx_grp (grp), unique key uk_name (name))""")
    rows = ",".join(
        f"({i}, {i % 100}, {i}.25, 'name{i:04d}')" for i in range(2000))
    tk.must_exec(f"insert into t values {rows}")
    return tk


def plan_of(tk, sql):
    return "\n".join(" | ".join(c or "" for c in r)
                     for r in tk.must_query("explain " + sql).rows)


def test_point_get_pk(tk):
    sql = "select id, name from t where id = 1437"
    assert "PointGet" in plan_of(tk, sql)
    assert tk.must_query(sql).rows == [("1437", "name1437")]
    # miss → empty, not an error
    assert tk.must_query("select id from t where id = 999999").rows == []


def test_point_get_unique_index(tk):
    sql = "select id from t where name = 'name0042'"
    assert "PointGet" in plan_of(tk, sql)
    assert tk.must_query(sql).rows == [("42",)]


def test_point_get_sees_txn_writes(tk):
    s = tk.new_session()
    s.must_exec("use cbo")
    s.must_exec("begin")
    s.must_exec("insert into t values (100000, 5, 1.00, 'fresh')")
    assert s.must_query(
        "select name from t where id = 100000").rows == [("fresh",)]
    s.must_exec("update t set name = 'renamed' where id = 100000")
    assert s.must_query(
        "select id from t where name = 'renamed'").rows == [("100000",)]
    s.must_exec("rollback")
    assert tk.must_query(
        "select name from t where id = 100000").rows == []


def test_index_path_switches_on_selectivity(tk):
    tk.must_exec("analyze table t")
    # grp = const matches ~20 of 2000 rows → the seek path wins
    sel = "select id from t where grp = 7 order by id limit 3"
    assert "IndexLookUp" in plan_of(tk, sel)
    assert tk.must_query(sel).rows == [("7",), ("107",), ("207",)]
    # grp >= 1 matches ~99% of rows → the vectorized full scan wins
    unsel = "select count(1) from t where grp >= 1"
    assert "TableScan" in plan_of(tk, unsel)
    assert tk.must_query(unsel).rows == [("1980",)]


def test_index_range_scan(tk):
    tk.must_exec("analyze table t")
    sql = "select id from t where grp = 3 and id < 500 order by id"
    rows = tk.must_query(sql).rows
    assert rows == [(str(i),) for i in range(3, 500, 100)]


def test_index_path_parity_with_full_scan(tk):
    """Same query with and without the index available must agree."""
    tk.must_exec("analyze table t")
    sql = "select id, val from t where grp = 55 order by id"
    via_index = tk.must_query(sql).rows
    assert "IndexLookUp" in plan_of(tk, sql)
    # an equivalent predicate the index cannot serve (expression on grp)
    sql_noidx = "select id, val from t where grp + 0 = 55 order by id"
    assert "IndexLookUp" not in plan_of(tk, sql_noidx)
    assert via_index == tk.must_query(sql_noidx).rows
    assert len(via_index) == 20


def test_update_maintains_index_reads(tk):
    tk.must_exec("insert into t values (200000, 777, 9.99, 'mover')")
    tk.must_exec("update t set grp = 778 where id = 200000")
    tk.must_exec("analyze table t")
    assert tk.must_query(
        "select id from t where grp = 778").rows == [("200000",)]
    assert tk.must_query(
        "select count(1) from t where grp = 777").rows == [("0",)]
    tk.must_exec("delete from t where id = 200000")
    assert tk.must_query(
        "select id from t where grp = 778").rows == []


def test_explain_shows_estimates(tk):
    tk.must_exec("analyze table t")
    p = plan_of(tk, "select id from t where grp = 7")
    assert "idx_grp" in p and "est_rows" in p


class TestBatchPointGet:
    """reference: point_get_plan.go newBatchPointGetPlan +
    executor/batch_point_get.go."""

    @pytest.fixture()
    def btk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table bp (id int primary key, a int, "
                     "v varchar(8), unique key ua (a))")
        tk.must_exec("insert into bp values "
                     + ",".join(f"({i},{i + 1000},'v{i}')"
                                for i in range(200)))
        tk.must_exec("analyze table bp")
        return tk

    def _explain(self, tk, q):
        return "\n".join(" ".join(map(str, r))
                         for r in tk.must_query("EXPLAIN " + q).rows)

    def test_pk_in_list(self, btk):
        txt = self._explain(btk, "select * from bp where id in (3, 7, 9)")
        assert "BatchPointGet" in txt and "handles:3" in txt
        btk.must_query("select v from bp where id in (3, 7, 9) "
                       "order by id").check([("v3",), ("v7",), ("v9",)])

    def test_unique_index_in_list(self, btk):
        txt = self._explain(btk, "select * from bp where a in (1003, 1009)")
        assert "BatchPointGet" in txt
        btk.must_query("select id from bp where a in (1003, 1009) "
                       "order by id").check([("3",), ("9",)])

    def test_missing_keys_skip(self, btk):
        btk.must_query("select count(*) from bp where id in (1, 99999)"
                       ).check([("1",)])

    def test_in_txn_sees_uncommitted(self, btk):
        btk.must_exec("begin")
        btk.must_exec("update bp set v = 'dirty' where id = 3")
        btk.must_query("select v from bp where id in (3, 4) order by id"
                       ).check([("dirty",), ("v4",)])
        btk.must_exec("rollback")

    def test_duplicate_in_values_return_one_row(self, btk):
        """Regression: IN (3, 3) must not fetch the row twice."""
        btk.must_query("select count(*) from bp where id in (3, 3)").check(
            [("1",)])
        btk.must_query("select count(*) from bp where a in (1003, 1003)"
                       ).check([("1",)])


class TestStatsDepth:
    """Round-3 statistics depth: index prefix NDVs from ANALYZE and
    NDV-containment join cardinality driving greedy join order
    (reference: statistics/builder.go index stats,
    planner/core/stats.go join row-count estimation,
    rule_join_reorder.go greedy by estimated rows)."""

    @pytest.fixture(scope="class")
    def stk(self):
        tk = TestKit()
        tk.must_exec("create database statsd")
        tk.must_exec("use statsd")
        tk.must_exec("""create table ct (
            a bigint, b bigint, c bigint, key idx_ab (a, b))""")
        # a: 10 distinct, b: 4 distinct per a (40 pairs), 400 rows
        rows = ",".join(f"({i % 10}, {i % 40}, {i})" for i in range(400))
        tk.must_exec(f"insert into ct values {rows}")
        tk.must_exec("analyze table ct")
        return tk

    def test_index_prefix_ndv(self, stk):
        info = stk.domain.infoschema().table_by_name("statsd", "ct")
        stats = stk.domain.stats[info.id]
        idx = next(i for i in info.indexes if i.name == "idx_ab")
        assert stats["indexes"][str(idx.id)]["prefix_ndv"] == [10, 40]

    def test_prefix_ndv_drives_two_col_eq_estimate(self, stk):
        # independence would estimate 400 * (1/10) * (1/40) = 1 row;
        # the pair NDV knows (a,b) has 40 distinct values -> 10 rows
        p = plan_of(stk, "select c from ct where a = 3 and b = 13")
        assert "idx_ab" in p
        assert "est_rows:10" in p

    def test_join_cardinality_orders_by_output_not_size(self, stk):
        # f: 600 rows; f.a unique, f.b has NDV 3.
        # dima: 400 rows unique key -> f |><| dima ~= 400 rows
        # dimb: 300 rows, key NDV 3 -> f |><| dimb explodes to ~60k rows
        # smallest-first greedy would seed with dimb (300 < 400 < 600) and
        # join dimb |><| f first; cardinality-aware greedy must start from
        # the (f, dima) edge instead.
        stk.must_exec("create table f (a bigint, b bigint)")
        stk.must_exec("create table dima (a bigint)")
        stk.must_exec("create table dimb (b bigint)")
        stk.must_exec("insert into f values " + ",".join(
            f"({i}, {i % 3})" for i in range(600)))
        stk.must_exec("insert into dima values " + ",".join(
            f"({i})" for i in range(400)))
        stk.must_exec("insert into dimb values " + ",".join(
            f"({i % 3})" for i in range(300)))
        for t in ("f", "dima", "dimb"):
            stk.must_exec(f"analyze table {t}")
        p = plan_of(stk, """select count(1) from f, dima, dimb
                            where f.a = dima.a and f.b = dimb.b""")
        assert p.index("table:dima") < p.index("table:dimb"), p

    def test_selectivity_matches_distribution(self, stk):
        # grp-style skew: value 0 occurs 361 times, others once each
        stk.must_exec("create table sk (v bigint)")
        stk.must_exec("insert into sk values " + ",".join(
            f"({0 if i < 361 else i})" for i in range(400)))
        stk.must_exec("analyze table sk")
        info = stk.domain.infoschema().table_by_name("statsd", "sk")
        stats = stk.domain.stats[info.id]
        from tidb_tpu.statistics.selectivity import cond_selectivity
        from tidb_tpu.expression.core import (
            Column as EC, Constant, ScalarFunc)
        from tidb_tpu.sqltypes import FieldType, TYPE_LONGLONG
        ft = FieldType(tp=TYPE_LONGLONG)
        cols = info.public_columns()
        eq0 = ScalarFunc("eq", [EC(0, ft), Constant(0, ft)], ft)
        sel = cond_selectivity(stats, cols, eq0)
        assert abs(sel - 361 / 400) < 0.01       # TopN exact count
        eq_rare = ScalarFunc("eq", [EC(0, ft), Constant(365, ft)], ft)
        sel = cond_selectivity(stats, cols, eq_rare)
        assert sel <= 5 / 400                    # rare value stays rare

    def test_force_index_without_analyze(self, stk):
        # review regression: FORCE INDEX on a never-analyzed table must not
        # crash on the missing stats blob
        stk.must_exec("create table fi (v bigint, key idx_v (v))")
        stk.must_exec("insert into fi values (1), (2), (3)")
        p = plan_of(stk, "select v from fi force index (idx_v) where v = 2")
        assert "idx_v" in p
        rows = stk.must_query(
            "select v from fi force index (idx_v) where v = 2").rows
        assert rows == [("2",)]

    def test_skewed_hot_value_prefers_scan(self, stk):
        # review regression: single-column eq must keep the TopN-exact
        # estimate — the hot value covers 361/400 rows, so the index path
        # (361 seeks) must lose to the full scan
        stk.must_exec("create table skx (v bigint, key idx_v (v))")
        stk.must_exec("insert into skx values " + ",".join(
            f"({0 if i < 361 else i})" for i in range(400)))
        stk.must_exec("analyze table skx")
        assert "TableScan" in plan_of(stk, "select v from skx where v = 0")
        # the rare value still picks the index
        assert "idx_v" in plan_of(stk, "select v from skx where v = 399")


class TestIndexMerge:
    """IndexMerge union reader (reference: executor/index_merge_reader.go,
    planner/core/indexmerge_path.go): an OR of per-column indexable
    predicates resolves as a union of index handle sets."""

    @pytest.fixture()
    def mtk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table im (id bigint primary key, a bigint, "
                     "b bigint, c varchar(10), key ia (a), key ib (b))")
        tk.must_exec("insert into im values " + ",".join(
            f"({i}, {i % 100}, {i % 97}, 'v{i % 5}')" for i in range(1000)))
        tk.must_exec("analyze table im")
        return tk

    def test_or_of_indexed_columns_uses_merge(self, mtk):
        sql = "select id from im where a = 3 or b = 7"
        plan = "\n".join(" ".join(map(str, r)) for r in
                         mtk.must_query("explain " + sql).rows)
        assert "IndexMerge" in plan, plan
        assert "union:[ia,ib]" in plan, plan
        got = sorted(int(r[0]) for r in mtk.must_query(sql).rows)
        want = sorted(i for i in range(1000) if i % 100 == 3 or i % 97 == 7)
        assert got == want

    def test_or_with_pk_and_range(self, mtk):
        sql = "select id from im where id = 5 or a > 97"
        plan = "\n".join(" ".join(map(str, r)) for r in
                         mtk.must_query("explain " + sql).rows)
        assert "IndexMerge" in plan, plan
        got = sorted(int(r[0]) for r in mtk.must_query(sql).rows)
        want = sorted(i for i in range(1000) if i == 5 or i % 100 > 97)
        assert got == want

    def test_unindexed_disjunct_stays_scan(self, mtk):
        # c has no index: the OR cannot pre-select, full scan remains
        sql = "select id from im where a = 3 or c = 'v1'"
        plan = "\n".join(" ".join(map(str, r)) for r in
                         mtk.must_query("explain " + sql).rows)
        assert "IndexMerge" not in plan, plan
        assert "TableScan" in plan, plan

    def test_merge_sees_txn_writes(self, mtk):
        # visibility: the handle union must go through the txn snapshot
        mtk.must_exec("begin")
        mtk.must_exec("insert into im values (5000, 3, 1, 'x')")
        mtk.must_exec("update im set a = 3 where id = 10")
        got = sorted(int(r[0]) for r in mtk.must_query(
            "select id from im where a = 3 or b = 7").rows)
        mtk.must_exec("rollback")
        assert 5000 in got and 10 in got


class TestAggElimination:
    """rule_aggregation_elimination.go analog: unique-keyed GROUP BY
    collapses to a projection — and the two traps the rewrite must dodge."""

    def test_unique_group_key_eliminates(self, tk):
        tk.must_exec("create table ae1 (id bigint primary key, v bigint)")
        tk.must_exec("insert into ae1 values (1, 10), (2, 20)")
        plan = "\n".join(r[0] for r in tk.must_query(
            "explain select id, sum(v) from ae1 group by id").rows)
        assert "Agg" not in plan, plan
        tk.must_query("select id, sum(v), count(v) from ae1 group by id "
                      "order by id").check(
            [("1", "10", "1"), ("2", "20", "1")])

    def test_nullable_unique_key_not_eliminated(self, tk):
        # unique indexes admit many NULL rows: the NULL group aggregates
        tk.must_exec("create table ae2 (id bigint primary key, a bigint, "
                     "b bigint, unique key ua (a))")
        tk.must_exec("insert into ae2 values (1, null, 1), (2, null, 2), "
                     "(3, 5, 10)")
        plan = "\n".join(r[0] for r in tk.must_query(
            "explain select a, sum(b) from ae2 group by a").rows)
        assert "Agg" in plan, plan
        tk.must_query("select a, sum(b) from ae2 group by a "
                      "order by a").check([(None, "3"), ("5", "10")])

    def test_count_null_constant(self, tk):
        tk.must_exec("create table ae3 (id bigint primary key)")
        tk.must_exec("insert into ae3 values (1), (2)")
        tk.must_query("select id, count(null) from ae3 group by id "
                      "order by id").check([("1", "0"), ("2", "0")])
