"""Device (TPU) execution paths for the hot operators.

The fused scan→filter→aggregate pipeline: when a HashAgg sits directly on a
TableScan, the pushed-down filters, the aggregate input expressions and the
grouping all compile into ONE jitted XLA program — the host only dict-encodes
strings and reads back `capacity`-bounded results. This is the engine-side
realization of the reference's coprocessor pushdown (the whole DAG executes
storage-side there, device-side here).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..errors import TiDBError
from ..expression import phys_kind, K_DEC, K_FLOAT, K_STR, K_DATE
from ..expression.core import Column as ExprColumn
from ..ops import device as dev
from ..ops.device import DeviceUnsupported
from ..sqltypes import POW10
from ..utils.chunk import Chunk, Column, np_dtype_for


def engine_mode(ctx) -> str:
    try:
        return ctx.get_sysvar("tidb_executor_engine")
    except Exception:
        return "auto"


def want_device(ctx, n_rows: int) -> bool:
    mode = engine_mode(ctx)
    if mode == "host":
        return False
    if mode == "tpu":
        return True
    return n_rows >= 65536  # auto: device dispatch overhead beneath this


def device_agg(plan, chunk: Chunk, conds) -> Chunk:
    """Fused filter+group+aggregate on device. Raises DeviceUnsupported to
    trigger host fallback."""
    n = chunk.num_rows
    if n == 0:
        raise DeviceUnsupported("empty input")
    # device columns for everything referenced
    used = set()
    for e in plan.group_exprs:
        e.columns_used(used)
    for d in plan.aggs:
        for a in d.args:
            a.columns_used(used)
    for c in conds:
        c.columns_used(used)
    dcols = {}
    env = {}
    for idx in used:
        dc = dev.to_device_col(chunk.columns[idx])
        dcols[idx] = dc
        env[idx] = (dc.data, dc.nulls)
    if not env:
        raise DeviceUnsupported("no columns")

    # filter mask
    if conds:
        mask = None
        for c in conds:
            f = dev.compile_expr(c, dcols)
            d, nl = f(env)
            m = (d != 0) & ~nl
            mask = m if mask is None else (mask & m)
    else:
        mask = jnp.ones(n, dtype=bool)

    # group keys: must evaluate to int-representable arrays
    key_fns = []
    key_meta = []  # (expr, dictionary or None)
    for e in plan.group_exprs:
        k = phys_kind(e.ftype)
        if k == K_STR:
            if not isinstance(e, ExprColumn):
                raise DeviceUnsupported("string group key must be a column")
            dc = dcols[e.idx]
            key_meta.append((e, dc.dictionary))
            key_fns.append(dev.compile_expr(e, dcols))
        elif k == K_FLOAT:
            raise DeviceUnsupported("float group keys")
        else:
            key_meta.append((e, None))
            key_fns.append(dev.compile_expr(e, dcols))
    key_cols = []
    key_nulls = []
    for f in key_fns:
        d, nl = f(env)
        key_cols.append(d.astype(jnp.int64))
        key_nulls.append(nl)
    if not key_cols:
        # global aggregate: single group
        key_cols = [jnp.zeros(n, dtype=jnp.int64)]
        key_nulls = [jnp.zeros(n, dtype=bool)]

    # aggregate value columns + op names; avg = sum + count pair
    val_cols, val_nulls, agg_ops = [], [], []
    slots = []  # per desc: ("plain", j) | ("avg", j_sum, j_cnt)
    for desc in plan.aggs:
        if desc.distinct:
            raise DeviceUnsupported("distinct agg on device")
        arg = desc.args[0] if desc.args else None
        name = desc.name
        if name == "count":
            f = dev.compile_expr(arg, dcols)
            d, nl = f(env)
            val_cols.append(d.astype(jnp.int64))
            val_nulls.append(nl)
            agg_ops.append("count")
            slots.append(("plain", len(val_cols) - 1))
            continue
        if name not in ("sum", "avg", "min", "max", "first_row"):
            raise DeviceUnsupported(f"agg {name} on device")
        k = phys_kind(arg.ftype)
        if k == K_STR and name in ("min", "max", "first_row"):
            if not isinstance(arg, ExprColumn):
                raise DeviceUnsupported("string agg arg must be a column")
            # dictionary from np.unique is sorted → code order == byte order
            f = dev.compile_expr(arg, dcols)
            d, nl = f(env)
            val_cols.append(d.astype(jnp.int64))
            val_nulls.append(nl)
            agg_ops.append({"min": "min", "max": "max",
                            "first_row": "first"}[name])
            slots.append(("strcol", len(val_cols) - 1, arg.idx))
            continue
        if k == K_STR:
            raise DeviceUnsupported("string sum/avg")
        f = dev.compile_expr(arg, dcols)
        d, nl = f(env)
        is_float = d.dtype == jnp.float64
        if name in ("min", "max", "first_row"):
            val_cols.append(d)
            val_nulls.append(nl)
            agg_ops.append({"min": "min", "max": "max",
                            "first_row": "first"}[name])
            slots.append(("plain", len(val_cols) - 1))
        elif name == "sum":
            val_cols.append(d)
            val_nulls.append(nl)
            agg_ops.append("sum_f" if is_float else "sum_i")
            slots.append(("plain", len(val_cols) - 1))
        else:  # avg
            val_cols.append(d)
            val_nulls.append(nl)
            agg_ops.append("sum_f" if is_float else "sum_i")
            j_sum = len(val_cols) - 1
            val_cols.append(d.astype(jnp.int64) if not is_float else d)
            val_nulls.append(nl)
            agg_ops.append("count")
            slots.append(("avg", j_sum, len(val_cols) - 1))

    est = _estimate_groups(plan, n)
    capacity = dev.next_pow2(min(n, max(est, 16)))
    while True:
        out = dev._agg_kernel(tuple(key_cols), tuple(key_nulls),
                              tuple(val_cols), tuple(val_nulls), mask,
                              n_keys=len(key_cols), agg_ops=tuple(agg_ops),
                              capacity=capacity)
        key_out, key_null_out, results, result_nulls, n_groups, _valid = out
        ng = int(n_groups)
        if ng <= capacity:
            break
        capacity = dev.next_pow2(ng)
    if ng == 0 and not plan.group_exprs:
        # global aggregate over zero kept rows still yields ONE row
        # (count=0, sum/min/max NULL) — host path has the special case
        raise DeviceUnsupported("empty global aggregate")

    # assemble host chunk
    out_cols = []
    for (e, dictionary), kd, kn in zip(key_meta, key_out, key_null_out):
        kd = np.asarray(kd[:ng])
        kn = np.asarray(kn[:ng])
        if dictionary is not None:
            data = np.where(kn, b"", dictionary[np.clip(kd, 0, len(dictionary) - 1)])
            out_cols.append(Column(e.ftype, data, kn))
        else:
            dt = np_dtype_for(e.ftype)
            out_cols.append(Column(e.ftype, kd.astype(dt), kn))
    if not plan.group_exprs:
        out_cols = []
    for desc, slot in zip(plan.aggs, slots):
        ft = desc.ftype
        if slot[0] == "avg":
            _tag, j_sum, j_cnt = slot
            s = np.asarray(results[j_sum][:ng])
            c = np.asarray(results[j_cnt][:ng])
            nulls = np.asarray(result_nulls[j_sum][:ng])
            if phys_kind(ft) == K_FLOAT:
                vals = s / np.maximum(c, 1)
                out_cols.append(Column(ft, vals, nulls))
            else:
                arg = desc.args[0]
                s_arg = arg.ftype.scale if phys_kind(arg.ftype) == K_DEC else 0
                shift = POW10[ft.scale - s_arg]
                num = s.astype(object) * shift
                den = np.maximum(c, 1).astype(object)
                sign = np.where(num < 0, -1, 1)
                q = (2 * np.abs(num) + den) // (2 * den)
                vals = np.array([int(x) for x in sign * q], dtype=np.int64)
                out_cols.append(Column(ft, vals, nulls))
            continue
        if slot[0] == "strcol":
            _tag, j, col_idx = slot
            codes = np.asarray(results[j][:ng])
            nulls = np.asarray(result_nulls[j][:ng])
            dictionary = dcols[col_idx].dictionary
            data = np.where(nulls, b"", dictionary[np.clip(codes, 0, len(dictionary) - 1)])
            out_cols.append(Column(ft, data, nulls))
            continue
        _tag, j = slot
        vals = np.asarray(results[j][:ng])
        nulls = np.asarray(result_nulls[j][:ng])
        if desc.name == "count":
            nulls = np.zeros(ng, dtype=bool)
        dt = np_dtype_for(ft)
        if dt is not object and vals.dtype != dt:
            vals = vals.astype(dt)
        out_cols.append(Column(ft, vals, nulls))
    if not out_cols:
        raise DeviceUnsupported("agg with no outputs")
    return Chunk(out_cols)


def _estimate_groups(plan, n):
    est = 1
    for e in plan.group_exprs:
        est *= 64  # refined by stats-driven NDV once histograms land
    return min(est if plan.group_exprs else 1, n)


def device_join_keys(lkeys, rkeys):
    """Combine multi-column join keys into single int64 codes host-side
    (shared factorization), then match on device. Returns (li, ri)."""
    nb = len(rkeys[0][0])
    npr = len(lkeys[0][0])
    from ..ops import host as hops
    acc_b = np.zeros(nb, dtype=np.int64)
    acc_p = np.zeros(npr, dtype=np.int64)
    b_null = np.zeros(nb, dtype=bool)
    p_null = np.zeros(npr, dtype=bool)
    for (pd, pn), (bd, bn) in zip(lkeys, rkeys):
        both = np.concatenate([bd, pd])
        codes, card = hops.factorize_column(both, np.concatenate([bn, pn]))
        acc_b = acc_b * np.int64(card + 1) + (codes[:nb] + 1)
        acc_p = acc_p * np.int64(card + 1) + (codes[nb:] + 1)
        b_null |= bn
        p_null |= pn
    return dev.device_join_match((acc_b, b_null), (acc_p, p_null))
