"""Planner (reference: planner/ — AST → logical plan → optimized plan).

Round-1 shape: rule-based logical optimization (predicate pushdown, equi-join
extraction, greedy join reorder, column pruning, constant folding) and a thin
logical→physical mapping done in the executor builder (hash agg / hash join /
topn). The cost-based physical search over a {host, tpu, tpu-mpp} task model
(the reference's root/cop/mpp, planner/core/task.go) grows on top of this.
"""

from .logical import (
    Aggregation, DataSource, Dual, Join, Limit, LogicalPlan, MemSource,
    Projection, Selection, SetOp, Sort, TopN, Window,
)
from .builder import PlanBuilder
from .optimizer import optimize

__all__ = [
    "Aggregation", "DataSource", "Dual", "Join", "Limit", "LogicalPlan",
    "MemSource", "Projection", "Selection", "SetOp", "Sort", "TopN", "Window",
    "PlanBuilder", "optimize",
]
