"""Logical optimization rules (reference: planner/core/optimizer.go:73-91 —
the rule list; here: predicate pushdown, equi-join extraction + greedy join
reorder, column pruning; constant folding happens at expression build time)."""

from __future__ import annotations

from ..expression import Column, Schema
from ..expression.aggregation import AggFuncDesc
from ..expression.core import ScalarFunc
from .logical import (
    Aggregation, DataSource, Dual, Join, Limit, LogicalPlan, MemSource,
    Projection, Selection, SetOp, Sort, TopN, Window, explain_tree,
)


def optimize(plan: LogicalPlan, ctx=None, trace=None) -> LogicalPlan:
    """`trace`, when a list, receives (rule name, rendered plan) per rule —
    the optimizer trace (reference: planner/core/optimizer.go:93-126
    logical-rule step tracer + util/tracing/opt_trace.go), surfaced by
    TRACE FORMAT='opt' SELECT ..."""
    from .access import choose_access_paths
    from .physical import choose_join_algos

    def step(rule, p):
        if trace is not None:
            trace.append((rule, "\n".join(
                f"{name} | {info}" for name, info in explain_tree(p))))

    hints = collect_sql_hints(plan)
    step("initial", plan)
    plan = push_down_predicates(plan, [])
    step("predicate_push_down", plan)
    plan = eliminate_outer_joins(plan)
    step("outer_join_elimination", plan)
    plan = eliminate_aggregation(plan, ctx)
    step("aggregation_elimination", plan)
    plan = eliminate_max_min(plan)
    step("max_min_elimination", plan)
    plan = reorder_joins(plan, ctx)
    step("join_reorder", plan)
    plan = prune_group_keys(plan, ctx)
    step("group_key_pruning", plan)
    plan = prune_columns(plan)
    step("column_pruning", plan)
    plan = pull_proj_through_semi(plan)
    step("semi_join_projection_pull", plan)
    plan = prune_partitions_rule(plan)
    step("partition_pruning", plan)
    plan = choose_access_paths(plan, ctx)
    step("access_path_selection", plan)
    plan = choose_join_algos(plan, ctx, hints=hints)
    step("physical_join_selection", plan)
    plan = push_topn_into_agg(plan)
    step("topn_push_down", plan)
    if hints:
        apply_agg_hints(plan, hints)
        eng = engine_from_hints(hints)
        if eng:
            plan.engine_hint = eng
        step("hint_application", plan)
    return plan


#: READ_FROM_STORAGE engine names, with reference-dialect aliases so
#: ported SQL keeps working: TiKV was the row/host engine, TiFlash the
#: columnar accelerator engine
_ENGINE_ALIAS = {"tpu": "tpu", "host": "host", "tpu-mpp": "tpu-mpp",
                 "tpu_mpp": "tpu-mpp", "mpp": "tpu-mpp",
                 "tikv": "host", "tiflash": "tpu"}


def collect_sql_hints(plan) -> list:
    """Union of /*+ ... */ hint lists attached by the builder across the
    statement's query blocks (reference: planner/optimize.go hint
    collection before rule application)."""
    out = []

    def walk(p):
        h = getattr(p, "sql_hints", None)
        if h:
            out.extend(h)
        for c in p.children:
            walk(c)
    walk(plan)
    return out


def apply_agg_hints(plan, hints):
    """HASH_AGG / STREAM_AGG: annotate every Aggregation in scope. The
    executor reads agg_hint — 'stream' pins the host (streaming/spillable)
    path, 'hash' the default hash/device path (reference:
    planner/core/exhaust_physical_plans.go agg hint enforcement)."""
    mode = None
    for name, _args in hints:
        if name == "hash_agg":
            mode = "hash"
        elif name == "stream_agg":
            mode = "stream"
    if mode is None:
        return

    def walk(p):
        if isinstance(p, Aggregation):
            p.agg_hint = mode
        for c in p.children:
            walk(c)
    walk(plan)


def engine_from_hints(hints):
    """READ_FROM_STORAGE(ENGINE[tables...]) → a statement-scoped engine
    pin ('tpu' | 'host' | 'tpu-mpp'). Table lists are accepted for
    reference-syntax compatibility; the pin applies statement-wide (the
    engine here is a per-statement execution mode, not a per-table
    replica choice)."""
    for name, args in hints:
        if name != "read_from_storage":
            continue
        for a in args:
            eng = _ENGINE_ALIAS.get(a.split("[", 1)[0].strip().lower())
            if eng:
                return eng
    return None


#: aggregate functions the single-row-group rewrite knows how to project
_ELIM_AGGS = frozenset({"sum", "avg", "max", "min", "first_row", "count"})


def eliminate_aggregation(plan: LogicalPlan, ctx=None) -> LogicalPlan:
    """Aggregation elimination (reference: rule_aggregation_elimination.go):
    when the GROUP BY keys contain a unique key of the single underlying
    table, every group holds exactly one row — the aggregate collapses to
    a projection: sum/avg/max/min/first_row(x) → cast(x), count(x) →
    x IS NOT NULL, count(const) → 1.

    (Aggregation PUSHDOWN through joins is deliberately absent: only a
    partial/final split is sound through an inner join, and this engine's
    device path already fuses the whole join+aggregate tree into one
    program — the fusion IS the pushdown, reference
    rule_aggregation_push_down.go's benefit shape.)"""
    from ..sqltypes import FieldType, TYPE_LONGLONG

    def key_cols_of(agg):
        """Bare DataSource columns among the group keys + the source, when
        the child chain is DataSource (± Selection)."""
        child = agg.children[0]
        while isinstance(child, Selection):
            child = child.children[0]
        if not isinstance(child, DataSource):
            return None, None
        cols = {e.idx for e in agg.group_exprs if isinstance(e, Column)}
        return child, cols

    def has_unique_key(ds, col_idxs):
        names = {ds.col_infos[i].name for i in col_idxs
                 if i < len(ds.col_infos)}
        return any(ks <= names for ks in _unique_keysets(ds.table_info))

    def visit(p):
        for i, c in enumerate(p.children):
            p.children[i] = visit(c)
        if not isinstance(p, Aggregation) or not p.group_exprs:
            return p
        if any(d.name not in _ELIM_AGGS for d in p.aggs):
            return p
        if getattr(p, "topn_fetch", None):
            return p
        ds, cols = key_cols_of(p)
        if ds is None or not cols or not has_unique_key(ds, cols):
            return p
        ll = FieldType(tp=TYPE_LONGLONG)
        exprs = list(p.group_exprs)
        for d in p.aggs:
            arg = d.args[0] if d.args else None
            if d.name == "count":
                from ..expression.core import Constant as _Const
                if arg is None or (isinstance(arg, _Const)
                                   and arg.value is not None):
                    exprs.append(_Const(1, ll))
                elif isinstance(arg, _Const):  # count(NULL) is 0
                    exprs.append(_Const(0, ll))
                else:
                    exprs.append(ScalarFunc(
                        "not", [ScalarFunc("isnull", [arg], ll)], ll))
            else:
                exprs.append(ScalarFunc("cast", [arg], d.ftype))
        return Projection(p.children[0], exprs, p.schema)

    return visit(plan)


def _unique_keysets(info, require_not_null=True):
    """Frozenset column-name sets each proven unique on the table: the
    int handle PK, and PUBLIC unique indexes (non-PUBLIC ones may still
    hold duplicates mid-backfill). With require_not_null (the FD /
    agg-elimination case) every index column must be NOT NULL — a
    nullable unique index admits any number of all-NULL rows, which are
    distinct groups. Join-match uniqueness (right_unique) doesn't need
    it: NULL keys never equi-match, so duplicate NULL rows can't fan
    out a join. Shared by eliminate_aggregation, eliminate_outer_joins
    and prune_group_keys so uniqueness semantics stay in one place."""
    from .. import model as _model
    out = []
    if info.pk_is_handle:
        pk = next((c.name for c in info.columns
                   if c.id == info.pk_col_id), None)
        if pk:
            out.append(frozenset([pk]))
    not_null = {c.name for c in info.columns
                if c.ftype is not None and c.ftype.not_null}
    for idx in info.indexes:
        if (idx.unique and idx.columns
                and idx.state == _model.SchemaState.PUBLIC
                and (not require_not_null
                     or all(c.name in not_null for c in idx.columns))):
            out.append(frozenset(c.name for c in idx.columns))
    return out


def _col_eq_pair(cond, colmap):
    """(base_a, base_b) when `cond` is eq(Column, Column) with both sides
    resolving to base-table columns; else None."""
    if (not isinstance(cond, ScalarFunc) or cond.op != "eq"
            or len(cond.args) != 2):
        return None
    a, b = cond.args
    if not (isinstance(a, Column) and isinstance(b, Column)):
        return None
    if a.idx >= len(colmap) or b.idx >= len(colmap):
        return None
    ba, bb = colmap[a.idx], colmap[b.idx]
    return (ba, bb) if ba is not None and bb is not None else None


def _base_col_info(node):
    """Walk `node`'s tree collecting (colmap, tables, equivs):
    colmap[i] = (id(ds), col_name) when output position i forwards a base
    column unchanged (None otherwise); tables = {id(ds): ds} for every
    DataSource whose rows survive into the output row-wise (so per-table
    FDs hold on the output); equivs = [(base, base)] pairs equal on every
    output row (INNER-join equi keys and selection col=col filters only —
    an outer join's null-extended rows break condition equalities, but not
    either side's own key→column dependencies)."""
    if isinstance(node, DataSource):
        dsid = id(node)
        return ([(dsid, ci.name) for ci in node.col_infos],
                {dsid: node}, [])
    if isinstance(node, Selection):
        colmap, tables, eq = _base_col_info(node.child)
        for c in node.conds:
            pr = _col_eq_pair(c, colmap)
            if pr:
                eq.append(pr)
        return colmap, tables, eq
    if isinstance(node, Projection):
        cm, tables, eq = _base_col_info(node.child)
        colmap = [cm[e.idx] if isinstance(e, Column) and e.idx < len(cm)
                  else None for e in node.exprs]
        return colmap, tables, eq
    if isinstance(node, Join):
        lcm, lt, leq = _base_col_info(node.left)
        if node.kind in ("semi", "anti", "leftouter_semi"):
            # right side absent from the output schema (the mark column
            # of leftouter_semi pads with None)
            pad = len(node.schema) - len(lcm)
            return lcm + [None] * max(pad, 0), lt, leq
        rcm, rt, req = _base_col_info(node.right)
        colmap = lcm + rcm
        tables = {**lt, **rt}
        eq = leq + req
        if node.kind == "inner":
            for le, re_ in zip(node.left_keys, node.right_keys):
                if (isinstance(le, Column) and le.idx < len(lcm)
                        and isinstance(re_, Column) and re_.idx < len(rcm)):
                    a, b = lcm[le.idx], rcm[re_.idx]
                    if a is not None and b is not None:
                        eq.append((a, b))
            for c in node.other_conds:
                pr = _col_eq_pair(c, colmap)
                if pr:
                    eq.append(pr)
        return colmap, tables, eq
    # Aggregation / set ops / window / …: opaque boundary
    return [None] * len(node.schema), {}, []


def _det_cols(e):
    """Column idx set of `e` when every node is a deterministic
    Column/Constant/ScalarFunc; None when any node is nondeterministic
    (rand()/uuid() — a fresh value per row that no FD determines) or of
    an unknown kind (subquery apply, outer ref)."""
    from ..expression.builder import _NONDETERMINISTIC
    from ..expression.core import Constant
    out = set()

    def walk(x):
        if isinstance(x, Column):
            out.add(x.idx)
            return True
        if isinstance(x, Constant):
            return True
        if isinstance(x, ScalarFunc):
            if x.op in _NONDETERMINISTIC:
                return False
            return all(walk(a) for a in x.args)
        return False

    return out if walk(e) else None


def _fd_closure(seed, tables, equivs, keysets):
    """Fixpoint of: equivalence propagation + (unique keyset covered →
    every column of that table is determined)."""
    det = set(seed)
    changed = True
    while changed:
        changed = False
        for a, b in equivs:
            if a in det and b not in det:
                det.add(b)
                changed = True
            if b in det and a not in det:
                det.add(a)
                changed = True
        for dsid, ds in tables.items():
            names = {n for (i, n) in det if i == dsid}
            for ks in keysets.get(dsid, ()):
                if ks <= names:
                    new = {(dsid, c.name) for c in ds.table_info.columns}
                    if not new <= det:
                        det |= new
                        changed = True
                    break
    return det


def prune_group_keys(plan: LogicalPlan, ctx=None) -> LogicalPlan:
    """Functional-dependency group-key pruning (reference: the FD engine
    planner/funcdep/fd_graph.go feeding rule_aggregation_elimination.go):
    a GROUP BY key whose value is determined by the remaining keys —
    through a base table's unique key plus the inner-join equality
    closure — cannot split any group, so it demotes to a first_row()
    aggregate and the key set shrinks.

    TPC-H Q3 groups by (l_orderkey, o_orderdate, o_shippriority): with
    o_orderkey the orders handle PK and l_orderkey ≡ o_orderkey from the
    join, both orders columns demote — the device kernel then packs ONE
    26-bit key instead of a 39-bit triple, which keeps the dense-scatter
    aggregation path in range. Q18's five keys shrink to o_orderkey alone.

    Output positions are preserved by a Projection over the rewritten
    Aggregation (kept keys first, then original aggs, then the demoted
    first_rows), so HAVING/TopN above see an identical schema; TopN's
    candidate-fetch annotation already looks through pure projections."""
    def visit(p):
        for i, c in enumerate(p.children):
            p.children[i] = visit(c)
        if not isinstance(p, Aggregation) or len(p.group_exprs) < 2:
            return p
        child = p.children[0]
        colmap, tables, equivs = _base_col_info(child)
        if not tables:
            return p
        keysets = {dsid: _unique_keysets(ds.table_info)
                   for dsid, ds in tables.items()}
        if not any(keysets.values()):
            return p

        def key_bases(e):
            """Base columns a group key needs determined to be droppable:
            [base] for a bare column, every referenced column's base for
            an expression; None when any part is untraceable — including
            nondeterministic or opaque nodes (rand() yields a fresh value
            per row, so no FD can ever determine it; subquery applies and
            outer refs are equally beyond the closure) and column-free
            expressions (conservative: folding already turned genuine
            constants into Constant nodes)."""
            if isinstance(e, Column):
                b = colmap[e.idx] if e.idx < len(colmap) else None
                return None if b is None else [b]
            idxs = _det_cols(e)
            if not idxs:
                return None
            out = []
            for i in idxs:
                b = colmap[i] if i < len(colmap) else None
                if b is None:
                    return None
                out.append(b)
            return out

        bases = [key_bases(e) for e in p.group_exprs]
        kept = list(range(len(p.group_exprs)))
        dropped = []
        for j in range(len(p.group_exprs)):
            if bases[j] is None or len(kept) <= 1:
                continue
            rest = [k for k in kept if k != j]
            # only bare-column keys seed the closure: knowing f(x)
            # does not determine x
            seed = {bases[k][0] for k in rest
                    if bases[k] and isinstance(p.group_exprs[k], Column)}
            det = _fd_closure(seed, tables, equivs, keysets)
            if all(b in det for b in bases[j]):
                kept = rest
                dropped.append(j)
        if not dropped:
            return p
        new_keys = [p.group_exprs[k] for k in kept]
        new_aggs = list(p.aggs) + [
            AggFuncDesc("first_row", [p.group_exprs[j]]) for j in dropped]
        refs = ([p.schema.refs[k] for k in kept]
                + p.schema.refs[len(p.group_exprs):]
                + [p.schema.refs[j] for j in dropped])
        new_agg = Aggregation(child, new_keys, new_aggs, Schema(refs))
        new_agg.agg_hint = p.agg_hint
        s, a = len(kept), len(p.aggs)
        pos = {}
        for np_, j in enumerate(kept):
            pos[j] = np_
        for np_, j in enumerate(dropped):
            pos[j] = s + a + np_
        exprs = []
        for old in range(len(p.schema)):
            if old < len(p.group_exprs):
                new_idx = pos[old]
            else:
                new_idx = s + (old - len(p.group_exprs))
            r = p.schema.refs[old]
            exprs.append(Column(new_idx, r.ftype, r.name))
        return Projection(new_agg, exprs, p.schema)

    return visit(plan)


def eliminate_max_min(plan: LogicalPlan) -> LogicalPlan:
    """Global MAX/MIN rewrite (reference: rule_max_min_eliminate.go): a
    group-less aggregate whose ONLY function is one MAX or MIN feeds from
    TopN(1) over the non-null arg instead of the full input. The
    Aggregation stays on top — over ≤1 row it still produces the NULL row
    for empty input — so only the scan volume changes, not semantics. The
    ordered access path (or the device TopN candidate fetch) then serves
    the single row."""
    from ..sqltypes import FieldType, TYPE_LONGLONG
    from .logical import Selection as _Sel, TopN as _TopN

    def visit(p):
        for i, c in enumerate(p.children):
            p.children[i] = visit(c)
        if (isinstance(p, Aggregation) and not p.group_exprs
                and len(p.aggs) == 1 and p.aggs[0].name in ("max", "min")
                and p.aggs[0].args
                and not isinstance(p.children[0], TopN)):
            arg = p.aggs[0].args[0]
            ll = FieldType(tp=TYPE_LONGLONG)
            notnull = ScalarFunc(
                "not", [ScalarFunc("isnull", [arg], ll)], ll)
            inner = _Sel(p.children[0], [notnull])
            p.children[0] = _TopN(
                inner, [(arg, p.aggs[0].name == "max")], 0, 1)
        return p

    return visit(plan)


def eliminate_outer_joins(plan: LogicalPlan) -> LogicalPlan:
    """Outer-join elimination (reference: rule_join_elimination.go): a
    LEFT join whose right side contributes no columns to anything above
    it, and whose right keys are unique on the right table, can't change
    the left side's rows (every left row matches at most once and
    survives regardless) — drop the join, keep the left child. Runs
    before join reorder/pruning; prune_columns rebuilds the schemas the
    removal narrows."""

    def right_unique(join):
        ds = join.right
        if not isinstance(ds, DataSource):
            return False
        names = set()
        for k in join.right_keys:
            if not isinstance(k, Column) or k.idx >= len(ds.col_infos):
                return False
            names.add(ds.col_infos[k.idx].name)
        # NULL right keys never equi-match, so nullable unique still
        # caps the match count at one — require_not_null off
        return any(ks <= names for ks in
                   _unique_keysets(ds.table_info, require_not_null=False))

    def visit(p, needed):
        if isinstance(p, Join):
            L = len(p.left.schema)
            if (p.kind == "left" and not p.other_conds
                    and all(i < L for i in needed)
                    and right_unique(p)):
                return visit(p.left, needed)
            oc = _used(p.other_conds)
            left_needed = ({i for i in needed if i < L}
                           | {u for u in oc if u < L} | _used(p.left_keys))
            right_needed = ({i - L for i in needed if i >= L}
                            | {u - L for u in oc if u >= L}
                            | _used(p.right_keys))
            p.children[0] = visit(p.left, left_needed)
            p.children[1] = visit(p.right, right_needed)
            return p
        if isinstance(p, Projection):
            child_needed = set()
            for i in needed:
                if i < len(p.exprs):
                    p.exprs[i].columns_used(child_needed)
            p.children[0] = visit(p.children[0], child_needed)
            return p
        if isinstance(p, Selection):
            child_needed = set(needed) | _used(p.conds)
            p.children[0] = visit(p.children[0], child_needed)
            return p
        if isinstance(p, (Sort, TopN)):
            child_needed = set(needed) | _used(
                [e for e, _d in p.by])
            p.children[0] = visit(p.children[0], child_needed)
            return p
        if isinstance(p, Limit):
            p.children[0] = visit(p.children[0], set(needed))
            return p
        if isinstance(p, Aggregation):
            child_needed = _used(p.group_exprs)
            for d in p.aggs:
                child_needed |= _used(d.args)
            p.children[0] = visit(p.children[0], child_needed)
            return p
        # unknown operators: conservatively require every child column
        for i, c in enumerate(p.children):
            p.children[i] = visit(c, set(range(len(c.schema))))
        return p

    def _used(exprs):
        s: set = set()
        for e in exprs:
            e.columns_used(s)
        return s

    return visit(plan, set(range(len(plan.schema))))


def push_topn_into_agg(plan: LogicalPlan) -> LogicalPlan:
    """Annotate Aggregation nodes under a TopN (looking through pure
    projections) with a candidate-fetch bound (reference: TopN pushdown,
    planner/core/rule_topn_push_down.go — here the bound tells the device
    fragment how many grouped rows the host actually needs: a grouped
    TPC-H Q3/Q18 produces millions of groups but the query keeps 10).

    The device returns an OVERSAMPLED candidate set ordered by the TopN
    keys; the host TopN above re-sorts it with its exact comparator, so
    semantics (ties, NULL order, collation) stay identical to the full
    path. Oversampling covers boundary tie-groups."""
    def visit(p):
        if isinstance(p, TopN):
            _annotate_topn_agg(p)
        for c in p.children:
            visit(c)
    visit(plan)
    return plan


def _annotate_topn_agg(topn: TopN) -> None:
    from ..expression.core import Column as ExprColumn
    node = topn.child
    mappings = []
    while isinstance(node, Projection):
        mappings.append(node.exprs)
        node = node.child
    if not isinstance(node, Aggregation) or not node.group_exprs:
        return
    specs = []
    for e, desc in topn.by:
        for exprs in mappings:
            if not isinstance(e, ExprColumn) or e.idx >= len(exprs):
                return
            e = exprs[e.idx]
        if not isinstance(e, ExprColumn) or e.idx >= len(node.schema):
            return
        if e.idx >= len(node.group_exprs):
            a = node.aggs[e.idx - len(node.group_exprs)]
            # avg/variance are derived from two slots post-fetch; their
            # order isn't available on-device — leave those unfetched.
            # first_row (incl. group keys prune_group_keys demoted) IS a
            # materialized per-group slot, so ordering by it works
            if a.name not in ("sum", "min", "max", "count", "first_row"):
                return
        specs.append((e.idx, bool(desc)))
    k = topn.offset + topn.count
    fetch = 4 * k + 64  # oversample: boundary tie-groups
    if fetch > 1 << 20:
        return  # huge LIMIT: candidate fetch wouldn't save anything, and
        #         a clamped bound would silently truncate the result
    node.topn_fetch = (tuple(specs), fetch)


def prune_partitions_rule(plan: LogicalPlan) -> LogicalPlan:
    """Partition pruning on pushed-down scan predicates (reference:
    planner/core/rule_partition_processor.go)."""
    if isinstance(plan, DataSource) and plan.table_info.partition is not None:
        from ..partition import prune_partitions
        if plan.partitions is None:
            plan.partitions = list(plan.table_info.partition.defs)
        plan.partitions = prune_partitions(plan.table_info, plan.partitions,
                                           plan.pushed_conds)
    for c in plan.children:
        prune_partitions_rule(c)
    return plan


# ---------------------------------------------------------------------------
# predicate pushdown (reference: rule_predicate_push_down.go)
# ---------------------------------------------------------------------------

def push_down_predicates(plan, conds):
    """conds: expressions over plan's output schema pushed from above.
    Returns a plan that incorporates them as low as possible."""
    if isinstance(plan, Selection):
        return push_down_predicates(plan.child, conds + plan.conds)
    if isinstance(plan, Join):
        return _ppd_join(plan, conds)
    if isinstance(plan, DataSource):
        if conds:
            plan.pushed_conds.extend(conds)
        return plan
    if isinstance(plan, Projection):
        pushable, kept = [], []
        for c in conds:
            used = set()
            c.columns_used(used)
            if all(isinstance(plan.exprs[i], Column) for i in used):
                pushable.append(c.transform_columns(
                    lambda col: plan.exprs[col.idx]))
            else:
                kept.append(c)
        plan.children[0] = push_down_predicates(plan.child, pushable)
        return _wrap(plan, kept)
    if isinstance(plan, Aggregation):
        n_group = len(plan.group_exprs)
        pushable, kept = [], []
        for c in conds:
            used = set()
            c.columns_used(used)
            if used and all(i < n_group for i in used):
                pushable.append(c.transform_columns(
                    lambda col: plan.group_exprs[col.idx]))
            else:
                kept.append(c)
        plan.children[0] = push_down_predicates(plan.child, pushable)
        return _wrap(plan, kept)
    if isinstance(plan, Sort):
        plan.children[0] = push_down_predicates(plan.child, conds)
        return plan
    # Limit/TopN/SetOp/Window/MemSource/Dual: cannot push through
    plan.children = [push_down_predicates(c, []) for c in plan.children]
    return _wrap(plan, conds)


def _ppd_join(join: Join, conds):
    nl = len(join.left.schema)
    left_conds, right_conds, kept = [], [], []
    for cond in conds:
        used = set()
        cond.columns_used(used)
        left_only = all(i < nl for i in used)
        right_only = used and all(i >= nl for i in used)
        if join.kind == "inner":
            if left_only:
                left_conds.append(cond)
            elif right_only:
                right_conds.append(_shift(cond, -nl))
            elif _is_equi(cond, nl):
                lhs, rhs = _equi_sides(cond, nl)
                join.left_keys.append(lhs)
                join.right_keys.append(rhs)
            else:
                join.other_conds.append(cond)
        elif join.kind == "left":
            if left_only:
                left_conds.append(cond)
            else:
                kept.append(cond)  # filters null-extended rows: stay above
        elif join.kind in ("semi", "anti"):
            if left_only:
                left_conds.append(cond)
            else:
                kept.append(cond)
        else:
            kept.append(cond)
    join.children[0] = push_down_predicates(join.left, left_conds)
    join.children[1] = push_down_predicates(join.right, right_conds)
    return _wrap(join, kept)


def _is_equi(cond, nl):
    if not (isinstance(cond, ScalarFunc) and cond.op == "eq"):
        return False
    lu, ru = set(), set()
    cond.args[0].columns_used(lu)
    cond.args[1].columns_used(ru)
    if not lu or not ru:
        return False
    return ((all(i < nl for i in lu) and all(i >= nl for i in ru)) or
            (all(i < nl for i in ru) and all(i >= nl for i in lu)))


def _equi_sides(cond, nl):
    lu = set()
    cond.args[0].columns_used(lu)
    if all(i < nl for i in lu):
        return cond.args[0], _shift(cond.args[1], -nl)
    return cond.args[1], _shift(cond.args[0], -nl)


def _shift(expr, delta):
    return expr.transform_columns(
        lambda c: Column(c.idx + delta, c.ftype, name=c.name))


def _wrap(plan, conds):
    return Selection(plan, conds) if conds else plan


# ---------------------------------------------------------------------------
# join reorder (reference: rule_join_reorder.go — greedy variant)
# ---------------------------------------------------------------------------

def reorder_joins(plan, ctx):
    if isinstance(plan, Join) and plan.kind == "inner":
        items, conds = [], []
        _flatten_join(plan, items, conds, 0)
        if len(items) > 2:
            # reorder inside each leaf first; the greedy result is final —
            # recursing into its spine would flatten and reorder forever
            items = [(off, reorder_joins(p, ctx)) for off, p in items]
            new = _greedy_join(items, conds, ctx)
            if new is not None:
                return new
    plan.children = [reorder_joins(c, ctx) for c in plan.children]
    return plan


def _flatten_join(plan, items, conds, offset):
    """Collect inner-join leaves and all conds in *global* column indices.
    Returns width of this subtree."""
    if isinstance(plan, Join) and plan.kind == "inner":
        lw = _flatten_join(plan.left, items, conds, offset)
        rw = _flatten_join(plan.right, items, conds, offset + lw)
        for lk, rk in zip(plan.left_keys, plan.right_keys):
            conds.append(("eq", _shift(lk, offset), _shift(rk, offset + lw)))
        for oc in plan.other_conds:
            conds.append(("other", _shift_join_cond(oc, offset, lw), None))
        return lw + rw
    items.append((offset, plan))
    return len(plan.schema)


def _shift_join_cond(expr, offset, lw):
    # other_conds are over the join's concat schema: left part [0,lw) shifts
    # by offset; right part shifts by offset too (contiguous in global space)
    return _shift(expr, offset)


def _resolve_base(plan, idx, ctx):
    """Trace schema position `idx` of `plan` down to the base-table column
    it forwards, returning (table_stats, ColumnInfo) or None. Used for
    NDV lookups in join cardinality (reference: statistics/selectivity.go
    resolves expression columns to their UniqueID-keyed stats)."""
    if ctx is None or not hasattr(ctx, "table_stats"):
        return None
    while True:
        if isinstance(plan, DataSource):
            if idx >= len(plan.col_infos):
                return None
            stats = ctx.table_stats(plan.table_info.id)
            if stats is None:
                return None
            return stats, plan.col_infos[idx]
        if isinstance(plan, (Selection, Sort, Limit, TopN)):
            plan = plan.child
            continue
        if isinstance(plan, Projection):
            if idx >= len(plan.exprs) or not isinstance(plan.exprs[idx],
                                                        Column):
                return None
            idx = plan.exprs[idx].idx
            plan = plan.child
            continue
        if isinstance(plan, Join):
            nl = len(plan.left.schema)
            if idx < nl:
                plan = plan.left
            else:
                idx -= nl
                plan = plan.right
            continue
        if isinstance(plan, Aggregation):
            if (idx < len(plan.group_exprs)
                    and isinstance(plan.group_exprs[idx], Column)):
                idx = plan.group_exprs[idx].idx
                plan = plan.child
                continue
            return None
        return None


def _expr_ndv(plan, expr, ctx, est_rows):
    """NDV of a join-key expression over `plan`'s output, capped at the
    estimated row count; None when untraceable or no ANALYZE stats."""
    if not isinstance(expr, Column):
        return None
    base = _resolve_base(plan, expr.idx, ctx)
    if base is None:
        return None
    stats, ci = base
    cs = stats.get("columns", {}).get(str(ci.id))
    if not cs or not cs.get("ndv"):
        return None
    return min(cs["ndv"], max(est_rows, 1))


def _join_est(lr, rr, ndv_pairs):
    """|L ⋈ R| under containment: rows(L)·rows(R) / Π max(ndv_l, ndv_r)
    per equi-key (reference: statistics join cardinality in
    planner/core/stats.go; ndv None → pseudo max(ndv)=min(rows), which
    degenerates to the FK-join guess max(lr, rr))."""
    denom = 1.0
    for lndv, rndv in ndv_pairs:
        if lndv and rndv:
            denom *= max(lndv, rndv)
        else:
            denom *= max(min(lr, rr), 1)
    return max(int(lr * rr / denom), 1)


def _est_rows(plan, ctx):
    if isinstance(plan, DataSource):
        n = 1000
        if ctx is not None and hasattr(ctx, "table_rows"):
            n = max(ctx.table_rows(plan.table_info.id), 1)
        stats = (ctx.table_stats(plan.table_info.id)
                 if ctx is not None and hasattr(ctx, "table_stats") else None)
        if stats is not None and plan.pushed_conds:
            from ..statistics.selectivity import estimate_selectivity
            return max(int(n * estimate_selectivity(
                stats, plan.col_infos, plan.pushed_conds)), 1)
        for _ in plan.pushed_conds:
            n = max(n // 4, 1)
        return n
    if isinstance(plan, Selection):
        return max(_est_rows(plan.child, ctx) // 4, 1)
    if isinstance(plan, Aggregation):
        return max(_est_rows(plan.child, ctx) // 8, 1)
    if isinstance(plan, (Limit, TopN)):
        base = _est_rows(plan.child, ctx)
        return min(base, plan.count or base)
    if isinstance(plan, Join):
        lr = _est_rows(plan.left, ctx)
        rr = _est_rows(plan.right, ctx)
        if plan.kind in ("semi", "anti", "leftouter_semi"):
            return lr
        if plan.left_keys:
            pairs = [(_expr_ndv(plan.left, lk, ctx, lr),
                      _expr_ndv(plan.right, rk, ctx, rr))
                     for lk, rk in zip(plan.left_keys, plan.right_keys)]
            est = _join_est(lr, rr, pairs)
            return max(est, lr) if plan.kind == "left" else est
        return max(lr, rr) if plan.kind != "inner" else lr * rr
    if plan.children:
        return _est_rows(plan.children[0], ctx)
    return 1


def _greedy_join(items, conds, ctx):
    """items: [(global_offset, plan)]; conds: [("eq", l, r) | ("other", e, None)]
    in global indices. Greedy smallest-first join ordering."""
    n = len(items)
    sizes = [_est_rows(p, ctx) for _off, p in items]
    widths = [len(p.schema) for _off, p in items]
    # map global index -> (item, inner_idx)
    g2item = {}
    for it, (off, p) in enumerate(items):
        for i in range(widths[it]):
            g2item[off + i] = (it, i)

    def cond_items(e):
        used = set()
        e.columns_used(used)
        return {g2item[g][0] for g in used}, used

    def global_ndv(e, cap):
        """NDV of a join-cond side expr (global indices) via its item's
        base stats; None unless the expr IS a bare column (a transformed
        key's NDV bears no relation to the underlying column's)."""
        if not isinstance(e, Column):
            return None
        it, inner = g2item[e.idx]
        return _expr_ndv(items[it][1], Column(inner, e.ftype), ctx, cap)

    remaining = set(range(n))
    # seed with the item from the cheapest eq-connected pair (by estimated
    # join output), so a small-but-exploding dimension can't anchor the
    # spine; fall back to smallest-item when nothing connects
    start = None
    best_key = None
    for kind, a, b in conds:
        if kind != "eq":
            continue
        ia, _ = cond_items(a)
        ib, _ = cond_items(b)
        if len(ia) == 1 and len(ib) == 1 and ia != ib:
            (i,), (j,) = ia, ib
            est = _join_est(sizes[i], sizes[j],
                           [(global_ndv(a, sizes[i]),
                             global_ndv(b, sizes[j]))])
            key = (est, min(sizes[i], sizes[j]))
            if best_key is None or key < best_key:
                best_key = key
                start = i if sizes[i] <= sizes[j] else j
    if start is None:
        start = min(remaining, key=lambda i: sizes[i])
    remaining.discard(start)
    joined = {start}
    # current layout: list of item ids in concat order; plan built so far
    layout = [start]
    cur = items[start][1]
    cur_rows = sizes[start]
    pend = [(kind, a, b) for kind, a, b in conds]

    def gmap(g):
        it, inner = g2item[g]
        pos = 0
        for lid in layout:
            if lid == it:
                return pos + inner
            pos += widths[lid]
        raise KeyError(g)

    while remaining:
        # candidates connected via an eq cond, with the key exprs that
        # would connect them (joined-side, candidate-side)
        cand_keys = {}
        for kind, a, b in pend:
            if kind != "eq":
                continue
            ia, _ = cond_items(a)
            ib, _ = cond_items(b)
            if ia <= joined and len(ib) == 1:
                (c,) = ib
                if c in remaining:
                    cand_keys.setdefault(c, []).append((a, b))
            if ib <= joined and len(ia) == 1:
                (c,) = ia
                if c in remaining:
                    cand_keys.setdefault(c, []).append((b, a))
        if cand_keys:
            # pick the candidate minimizing the estimated join output
            # (reference: rule_join_reorder.go greedy by estimated rows)
            def join_score(c):
                pairs = [(global_ndv(a, cur_rows), global_ndv(b, sizes[c]))
                         for a, b in cand_keys[c]]
                return _join_est(cur_rows, sizes[c], pairs)
            nxt = min(cand_keys, key=lambda c: (join_score(c), sizes[c]))
            cur_rows = join_score(nxt)
        else:
            nxt = min(remaining, key=lambda i: sizes[i])
            cur_rows = max(cur_rows * sizes[nxt], 1)
        remaining.discard(nxt)
        right = items[nxt][1]
        new_joined = joined | {nxt}
        schema = Schema(cur.schema.refs + right.schema.refs)
        j = Join(cur, right, "inner", schema)
        lw = len(cur.schema)

        def gmap_new(g, _nxt=nxt, _lw=lw):
            it, inner = g2item[g]
            if it == _nxt:
                return _lw + inner
            return gmap(g)

        consumed = []
        for ci, (kind, a, b) in enumerate(pend):
            if kind == "eq":
                ia, _ua = cond_items(a)
                ib, _ub = cond_items(b)
                if not (ia | ib) <= new_joined:
                    continue
                if ia <= joined and ib == {nxt}:
                    lk, rk = a, b
                elif ib <= joined and ia == {nxt}:
                    lk, rk = b, a
                else:
                    # both sides now available but spanning: post-join filter
                    from ..sqltypes import FieldType, TYPE_LONGLONG
                    e = ScalarFunc("eq", [_remap_final(a, gmap_new),
                                          _remap_final(b, gmap_new)],
                                   FieldType(tp=TYPE_LONGLONG))
                    j.other_conds.append(e)
                    consumed.append(ci)
                    continue
                j.left_keys.append(_remap_final(lk, gmap))
                j.right_keys.append(_remap_inner(rk, g2item, nxt))
                consumed.append(ci)
            else:
                ia, _ = cond_items(a)
                if ia <= new_joined and not ia <= joined:
                    j.other_conds.append(_remap_final(a, gmap_new))
                    consumed.append(ci)
        pend = [c for i, c in enumerate(pend) if i not in set(consumed)]
        layout.append(nxt)
        joined = new_joined
        cur = j
    # leftover conds (e.g. left-only ones missed) -> selection on top
    leftovers = []
    for kind, a, b in pend:
        if kind == "eq":
            from ..sqltypes import FieldType, TYPE_LONGLONG
            e = ScalarFunc("eq", [_remap_final(a, gmap), _remap_final(b, gmap)],
                           FieldType(tp=TYPE_LONGLONG))
            leftovers.append(e)
        else:
            leftovers.append(_remap_final(a, gmap))
    if leftovers:
        cur = Selection(cur, leftovers)
    # restore original column order with a projection
    orig_order = []
    for off, p in items:
        for i in range(len(p.schema)):
            orig_order.append(off + i)
    perm = [gmap(g) for g in sorted(orig_order)]
    refs = [cur.schema.refs[i] for i in perm]
    exprs = [Column(i, cur.schema.refs[i].ftype, name=cur.schema.refs[i].name)
             for i in perm]
    return Projection(cur, exprs, Schema(refs))


def _remap_inner(expr, g2item, item_id):
    """Remap global indices to positions inside one item (the join's right)."""
    return expr.transform_columns(
        lambda c: Column(g2item[c.idx][1], c.ftype, name=c.name))


def _remap_final(expr, gmap):
    return expr.transform_columns(
        lambda c: Column(gmap(c.idx), c.ftype, name=c.name))


def pull_proj_through_semi(plan):
    """Projection(pure columns) under a semi/anti join's PROBE side pulls
    above the join (the join's output IS its left schema, so the pull is
    a pure rotation). Join reorder inserts such projections to restore
    column order; leaving one between the aggregate and the join blocks
    the fused device fragment (collect_tree sees ProjectionExec), while
    above the join it inlines into the aggregate
    (_inline_agg_projection)."""
    for i, c in enumerate(plan.children):
        plan.children[i] = pull_proj_through_semi(c)
    if (isinstance(plan, Join) and plan.kind in ("semi", "anti")
            and not plan.other_conds  # residuals index the concat schema
            #                           whose left half IS the projection's
            #                           output — rotating would misalign
            #                           them (null-aware NOT IN, Q17/Q20)
            and isinstance(plan.left, Projection)
            and all(isinstance(e, Column) for e in plan.left.exprs)):
        proj = plan.left
        plan.children[0] = proj.child
        plan.left_keys = [
            e.transform_columns(lambda c: proj.exprs[c.idx])
            for e in plan.left_keys]
        plan.schema = proj.child.schema
        proj.children[0] = plan
        return proj
    return plan


# ---------------------------------------------------------------------------
# column pruning (reference: rule_column_pruning.go)
# ---------------------------------------------------------------------------

def prune_columns(plan):
    new_plan, _mapping = _prune(plan, set(range(len(plan.schema))))
    return new_plan


def _prune(plan, needed):
    """Returns (new_plan, mapping old_idx -> new_idx). `needed` may not cover
    all outputs; nodes narrow their schemas accordingly."""
    if isinstance(plan, DataSource):
        used = set(needed)
        for c in plan.pushed_conds:
            c.columns_used(used)
        keep = sorted(used) if used else [0] if plan.schema.refs else []
        if not keep and plan.col_infos:
            keep = [0]  # scans need at least one column for row count
        mapping = {old: i for i, old in enumerate(keep)}
        plan.col_infos = [plan.col_infos[i] for i in keep]
        plan.schema = Schema([plan.schema.refs[i] for i in keep])
        plan.pushed_conds = [_remap_cols(c, mapping) for c in plan.pushed_conds]
        return plan, mapping
    if isinstance(plan, MemSource) or isinstance(plan, Dual):
        return plan, {i: i for i in range(len(plan.schema))}
    if isinstance(plan, Selection):
        child_needed = set(needed)
        for c in plan.conds:
            c.columns_used(child_needed)
        plan.children[0], mapping = _prune(plan.child, child_needed)
        plan.conds = [_remap_cols(c, mapping) for c in plan.conds]
        plan.schema = plan.child.schema
        return plan, mapping
    if isinstance(plan, Projection):
        keep = sorted(needed)
        child_needed = set()
        kept_exprs = [plan.exprs[i] for i in keep]
        for e in kept_exprs:
            e.columns_used(child_needed)
        plan.children[0], cmap = _prune(plan.child, child_needed)
        plan.exprs = [_remap_cols(e, cmap) for e in kept_exprs]
        plan.schema = Schema([plan.schema.refs[i] for i in keep])
        return plan, {old: i for i, old in enumerate(keep)}
    if isinstance(plan, Aggregation):
        n_group = len(plan.group_exprs)
        keep_aggs = [i for i in range(len(plan.aggs))
                     if (n_group + i) in needed]
        child_needed = set()
        for e in plan.group_exprs:
            e.columns_used(child_needed)
        kept_descs = [plan.aggs[i] for i in keep_aggs]
        for d in kept_descs:
            for a in d.args:
                a.columns_used(child_needed)
        plan.children[0], cmap = _prune(plan.child, child_needed)
        plan.group_exprs = [_remap_cols(e, cmap) for e in plan.group_exprs]
        for d in kept_descs:
            d.args = [_remap_cols(a, cmap) for a in d.args]
        plan.aggs = kept_descs
        keep = list(range(n_group)) + [n_group + i for i in keep_aggs]
        plan.schema = Schema([plan.schema.refs[i] for i in keep])
        return plan, {old: i for i, old in enumerate(keep)}
    if isinstance(plan, Join):
        nl = len(plan.left.schema)
        child_needed = set(needed)
        for e in plan.other_conds:
            e.columns_used(child_needed)
        lneed = {i for i in child_needed if i < nl}
        rneed = {i - nl for i in child_needed if i >= nl}
        for e in plan.left_keys:
            e.columns_used(lneed)
        for e in plan.right_keys:
            e.columns_used(rneed)
        plan.children[0], lmap = _prune(plan.left, lneed)
        plan.children[1], rmap = _prune(plan.right, rneed)
        new_nl = len(plan.left.schema)
        mapping = {}
        for old, new in lmap.items():
            mapping[old] = new
        for old, new in rmap.items():
            mapping[old + nl] = new + new_nl
        plan.left_keys = [_remap_cols(e, lmap) for e in plan.left_keys]
        plan.right_keys = [_remap_cols(e, rmap) for e in plan.right_keys]
        plan.other_conds = [_remap_cols(e, mapping) for e in plan.other_conds]
        plan.schema = plan.left.schema.concat(plan.right.schema)
        return plan, mapping
    if isinstance(plan, (Sort, TopN)):
        child_needed = set(needed)
        for e, _d in plan.by:
            e.columns_used(child_needed)
        plan.children[0], mapping = _prune(plan.child, child_needed)
        plan.by = [(_remap_cols(e, mapping), d) for e, d in plan.by]
        plan.schema = plan.child.schema
        return plan, mapping
    if isinstance(plan, Limit):
        plan.children[0], mapping = _prune(plan.child, needed)
        plan.schema = plan.child.schema
        return plan, mapping
    if isinstance(plan, SetOp):
        # children must keep identical layouts: prune nothing
        new_children = []
        for c in plan.children:
            nc, _m = _prune(c, set(range(len(c.schema))))
            new_children.append(nc)
        plan.children = new_children
        return plan, {i: i for i in range(len(plan.schema))}
    # unknown: no pruning
    plan.children = [(_prune(c, set(range(len(c.schema))))[0]) for c in plan.children]
    return plan, {i: i for i in range(len(plan.schema))}


def _remap_cols(expr, mapping):
    return expr.transform_columns(
        lambda c: Column(mapping[c.idx], c.ftype, name=c.name))
