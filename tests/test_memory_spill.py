"""Memory quota governance: hierarchical tracker, sort spill-to-disk under
pressure, bounded top-N, and the OOM cancel action (reference:
util/memory/tracker.go:54, util/memory/action.go, executor/sort.go:56,
util/chunk/disk.go:34)."""

import numpy as np
import pytest

from tidb_tpu.utils.chunk import Chunk, Column
from tidb_tpu.utils.disk import ChunkSpill
from tidb_tpu.utils.memory import MemQuotaExceeded, MemTracker
from tidb_tpu.sqltypes import TYPE_LONGLONG, TYPE_VARCHAR, FieldType
from tidb_tpu.testkit import TestKit


def test_tracker_hierarchy_and_limits():
    root = MemTracker("session", limit=1000)
    child = root.child("op")
    child.consume(400)
    assert root.consumed == 400 and child.consumed == 400
    child.release(100)
    assert root.consumed == 300
    with pytest.raises(MemQuotaExceeded):
        child.consume(800)


def test_tracker_spill_action_runs_before_cancel():
    root = MemTracker("stmt", limit=1000)
    freed = []

    def spill():
        freed.append(700)
        return 700
    root.register_spill(spill)
    root.consume(900)
    root.consume(200)   # over limit → spill frees 700 → under again
    assert freed == [700]
    assert root.consumed == 400


def test_chunk_spill_roundtrip(tmp_path):
    ft_i = FieldType(tp=TYPE_LONGLONG)
    ft_s = FieldType(tp=TYPE_VARCHAR)
    chunk = Chunk([
        Column(ft_i, np.arange(100, dtype=np.int64),
               np.zeros(100, dtype=bool)),
        Column(ft_s, np.array([b"v%d" % i for i in range(100)], dtype=object),
               np.array([i % 7 == 0 for i in range(100)])),
    ])
    sp = ChunkSpill(dir=str(tmp_path))
    sp.append(chunk)
    back = sp.read(0)
    assert back.num_rows == 100
    assert list(back.columns[0].data) == list(range(100))
    assert back.columns[1].data[3] == b"v3"
    assert bool(back.columns[1].nulls[7]) and not bool(back.columns[1].nulls[8])
    sp.close()


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("create table s (a int primary key, b int, c varchar(24))")
    vals = ",".join(f"({i}, {(i * 7919) % 100000}, 'pad-{i:08d}')"
                    for i in range(20000))
    tk.must_exec(f"insert into s values {vals}")
    tk.must_exec("set tidb_executor_engine = 'host'")
    return tk


def test_sort_spills_and_is_correct(tk):
    # quota far below the ~20k-row working set forces run spills
    tk.must_exec("set tidb_mem_quota_query = 200000")
    r = tk.must_query("select b from s order by b")
    got = [int(x[0]) for x in r.rows]
    assert got == sorted((i * 7919) % 100000 for i in range(20000))


def test_sort_spill_counters_in_explain_analyze(tk):
    tk.must_exec("set tidb_mem_quota_query = 200000")
    rows = tk.must_query("explain analyze select b, c from s order by b").rows
    sort_row = next(r for r in rows if "Sort" in r[0])
    assert "spilled_runs:" in sort_row[2] and "spill_bytes:" in sort_row[2]
    n_runs = int(sort_row[2].split("spilled_runs:")[1].split(",")[0])
    assert n_runs >= 2


def test_no_spill_under_quota(tk):
    tk.must_exec("set tidb_mem_quota_query = 0")  # unlimited
    rows = tk.must_query("explain analyze select b from s order by b").rows
    sort_row = next(r for r in rows if "Sort" in r[0])
    assert "spilled_runs:" not in sort_row[2]


def test_topn_memory_bounded(tk):
    tk.must_exec("set tidb_mem_quota_query = 150000")
    # top-N never buffers the table: completes under a quota sort would blow
    r = tk.must_query("select b from s order by b limit 5")
    assert [int(x[0]) for x in r.rows] == sorted(
        (i * 7919) % 100000 for i in range(20000))[:5]


def test_join_over_quota_spills_and_completes(tk):
    # round-3 contract (reference: executor/join.go build spill): a build
    # side over quota hash-partitions both sides and completes
    tk.must_exec("set tidb_mem_quota_query = 0")
    want = tk.must_query(
        "select count(*) from s t1, s t2 where t1.a = t2.b").rows
    assert int(want[0][0]) > 0
    tk.must_exec("set tidb_mem_quota_query = 300000")
    got = tk.must_query(
        "select count(*) from s t1, s t2 where t1.a = t2.b").rows
    assert got == want
    rows = tk.must_query(
        "explain analyze select count(*) from s t1, s t2 "
        "where t1.a = t2.b").rows
    join_row = next(r for r in rows if "Join" in r[0])
    assert "join_spill_partitions:" in join_row[2]


def test_hash_agg_over_quota_spills_and_completes(tk):
    # reference: executor/aggregate.go agg spill — big GROUP BY survives
    # the quota via hash-partitioned passes and matches the unlimited run
    tk.must_exec("set tidb_mem_quota_query = 0")
    want = tk.must_query(
        "select b, count(*), sum(a) from s group by b order by b").rows
    tk.must_exec("set tidb_mem_quota_query = 300000")
    got = tk.must_query(
        "select b, count(*), sum(a) from s group by b order by b").rows
    assert got == want
    rows = tk.must_query(
        "explain analyze select b, count(*) from s group by b").rows
    agg_row = next(r for r in rows if "HashAgg" in r[0])
    assert "agg_spill_partitions:" in agg_row[2]


def test_join_under_extreme_quota_cancelled(tk):
    # even one partition cannot fit: the cancel action still fires
    tk.must_exec("set tidb_mem_quota_query = 2000")
    with pytest.raises(MemQuotaExceeded) as ei:
        tk.must_query(
            "select count(*) from s t1, s t2 where t1.a = t2.a")
    assert "Out Of Memory Quota" in str(ei.value)


def test_quota_resets_per_statement(tk):
    tk.must_exec("set tidb_mem_quota_query = 2000")
    with pytest.raises(MemQuotaExceeded):
        tk.must_query("select count(*) from s t1, s t2 where t1.a = t2.a")
    # next (small) statement starts from a fresh tracker
    tk.must_query("select count(*) from s where a < 10").check([("10",)])


def test_agg_spill_respects_collation(tk):
    # review regression: _ci case-variants must stay one group when the
    # spill path partitions by group key
    tk.must_exec("""create table ci (c varchar(20)
                    collate utf8mb4_general_ci)""")
    vals = ",".join(f"('{'abc' if i % 2 else 'ABC'}xyz-{i % 3}')"
                    for i in range(9000))
    tk.must_exec(f"insert into ci values {vals}")
    tk.must_exec("set tidb_mem_quota_query = 0")
    want = tk.must_query(
        "select count(*) from ci group by c order by count(*)").rows
    tk.must_exec("set tidb_mem_quota_query = 200000")
    got = tk.must_query(
        "select count(*) from ci group by c order by count(*)").rows
    assert got == want
