"""DIAG — the per-worker diagnostics op on the direct MySQL port.

Every fleet worker already listens on a private DIRECT port
(fabric/worker.py); ``DIAG <kind>`` over that wire serves the process's
observability state as one JSON cell: its trace ring, slow-log items,
statement summaries, metrics snapshot, fragment-perf rows.  The cluster
memtables (session/memtables.py ``cluster_*``) are exactly this op
fanned out to every live peer's direct port — same statement an
operator can type by hand against one worker when the fan-out itself is
what's broken.

Statement forms (pre-parse intercept — DIAG is a diagnostics verb, not
SQL grammar):

    DIAG TRACES                recent finished traces (ring rows)
    DIAG TRACEJSON [<gid>]     full stitched trace dicts, optionally
                               only those this process recorded on
                               behalf of origin trace <gid>
    DIAG SLOW | STATEMENTS | PROCESSLIST | METRICS | PERF | STATUS
"""

from __future__ import annotations

import json
import logging
import threading

log = logging.getLogger("tidb_tpu.session.diag")

#: per-peer budget for a cluster fan-out hop: a dead worker costs this
#: long and contributes a tagged error row — never a hang, never a
#: failed query (the ISSUE 18 cluster-memtable contract)
PEER_TIMEOUT_S = 2.0

_KIND_TABLES = {
    "traces": ("information_schema", "trace_records"),
    "slow": ("information_schema", "slow_query"),
    "statements": ("information_schema", "statements_summary"),
    "processlist": ("information_schema", "processlist"),
}


def maybe_handle(session, sql: str):
    """Intercept a DIAG statement before the SQL parser; None when the
    text is not one (the caller parses normally)."""
    text = sql.strip().rstrip(";").strip()
    head = text[:4].upper()
    if head != "DIAG" or (len(text) > 4 and not text[4].isspace()):
        return None
    parts = text.split()
    kind = parts[1].lower() if len(parts) > 1 else "status"
    arg = parts[2] if len(parts) > 2 else ""
    from ..errors import TiDBError
    from ..sqltypes import TYPE_VARCHAR, FieldType
    from ..utils.chunk import Chunk
    from .session import Result
    try:
        out = payload(session, kind, arg)
    except KeyError:
        raise TiDBError(f"unknown DIAG kind {kind!r}") from None
    ft = FieldType(tp=TYPE_VARCHAR)
    cell = json.dumps(out, default=str).encode()
    return Result(names=["diag"], chunk=Chunk.from_rows([ft], [(cell,)]))


def _jsonify(v):
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    return v


def payload(session, kind: str, arg: str = "") -> dict:
    """The JSON body for one DIAG kind (KeyError on an unknown one)."""
    kind = kind.lower()
    if kind in _KIND_TABLES:
        from .memtables import mem_table
        cols, rows_fn = mem_table(session, *_KIND_TABLES[kind])
        return {"kind": kind, "cols": [n for n, _ft in cols],
                "rows": [[_jsonify(v) for v in r] for r in rows_fn()]}
    if kind == "tracejson":
        from . import tracing
        if arg:
            trs = tracing.traces_for_origin(arg)
        else:
            trs = tracing.recent_traces()
        return {"kind": kind,
                "rows": [tr.to_dict() for tr in trs]}
    if kind == "metrics":
        obs = session.domain.observe
        with obs._lock:
            counters = dict(obs.counters)
        # histograms ride along so a fleet parent can aggregate e.g.
        # freshness_wait_seconds percentiles across workers without
        # scraping each /metrics port (hist_snapshot takes obs._lock
        # itself — must not be called inside the block above)
        hists = {name: {"bounds": list(bounds), "counts": list(counts),
                        "sum": hsum, "count": count}
                 for name, (bounds, counts, hsum, count)
                 in obs.hist_snapshot().items()}
        from . import tracing
        return {"kind": kind, "counters": counters, "hists": hists,
                "tracing": tracing.snapshot()}
    if kind == "perf":
        from ..fabric import perf
        perf.flush()
        return {"kind": kind, "local": perf.local_rows(),
                "fleet": perf.fleet_rows(), "stats": perf.stats()}
    if kind == "status":
        from ..fabric import state
        return {"kind": kind, "fabric": state.snapshot()}
    raise KeyError(kind)


def cluster_fanout(session, kind: str, arg: str = "") -> list:
    """Run one DIAG kind against every live worker's direct port.
    Returns ``[(instance, payload-or-None, err), ...]`` — a dead or
    unreachable peer contributes ``(instance, None, "peer-lost: ...")``
    after at most PEER_TIMEOUT_S, so the cluster memtable row set is
    complete whatever the fleet's health.  Outside a fleet (no
    coordinator, or no published ports) the local process answers alone
    under instance ``"local"`` — single-process deployments keep the
    cluster_* surface."""
    from ..fabric import state
    coord = state.coordinator()
    ports = {}
    if coord is not None:
        try:
            ports = coord.direct_ports()
        except Exception as e:  # noqa: BLE001 — degrade to local,
            #   never fail the query
            log.debug("peer discovery failed, answering locally: %s", e)
            ports = {}
    if not ports:
        return [("local", payload(session, kind, arg), "")]

    results = {}

    def ask(slot, port):
        inst = f"slot{slot}:{port}"
        try:
            from ..fabric.client import FleetClient
            cli = FleetClient(port, timeout=PEER_TIMEOUT_S)
            try:
                stmt = f"DIAG {kind} {arg}".strip()
                _cols, rows = cli.must_query(stmt)
                results[slot] = (inst, json.loads(rows[0][0]), "")
            finally:
                cli.close()
        except Exception as e:  # noqa: BLE001 — the tagged error row
            results[slot] = (inst, None,
                             f"peer-lost: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=ask, args=(s, p), daemon=True)
               for s, p in sorted(ports.items())]
    for t in threads:
        t.start()
    for t in threads:
        # the socket timeout bounds each peer; the join margin only
        # covers scheduling, so a wedged thread can't hold the query
        t.join(PEER_TIMEOUT_S + 1.0)
    for slot, port in sorted(ports.items()):
        if slot not in results:
            results[slot] = (f"slot{slot}:{port}", None,
                             "peer-lost: timeout")
    # the fan-out's hops land on the statement's trace: a dead peer is
    # a visible span event, not just an error cell — the post-mortem
    # for "why is this cluster query partial" reads off the trace
    from . import tracing
    for s in sorted(results):
        inst, _payload, err = results[s]
        tracing.event("cluster.fanout", instance=inst,
                      status="peer-lost" if err else "ok")
    return [results[s] for s in sorted(results)]
