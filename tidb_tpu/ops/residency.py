"""HBM residency manager: device memory as a tracked, evictable,
epoch-scoped resource.

Why this exists (ROADMAP "Open items", ISSUE 5): the host side has a full
memory-quota tree (`utils/memory.py MemTracker` with spill actions and
`tidb_mem_oom_action`), but device memory had NOTHING — `Column._device`
uploads accumulated in HBM unaccounted and were never epoch-invalidated
after a backend fence, and an HBM ``RESOURCE_EXHAUSTED`` was merely
classified and charged to the circuit breaker with no eviction or retry.
The memory-adaptive-operator lesson of "Design Trade-offs for a Robust
Dynamic Hybrid Hash Join" (PAPERS.md) applies verbatim: an operator that
degrades gracefully under memory pressure beats one that dies.

Three jobs, one lock:

1. **Accounting + budget** — every cached device upload
   (`ops/device.to_device_col`) registers its byte size here.  The budget
   is the ``tidb_device_mem_budget`` sysvar (bytes; 0 = auto: the
   jax-reported device memory limit off-CPU, unlimited on the in-process
   CPU backend).  Crossing the budget evicts cold entries LRU-first —
   clearing the owning ``Column._device`` slot so the arrays (and their
   HBM buffers) become collectible.  The newest entry is never evicted
   for its own arrival: a single working column larger than the budget
   must still be usable (one-pass semantics beat a livelock).

2. **Device epoch** — a monotonically increasing counter bumped by every
   backend quarantine (`executor/supervisor.fence` / the hang-abandon
   path).  Every cached value is stamped with the epoch it was uploaded
   under and checked on read, so a restarted PJRT client can never serve
   a stale pre-fence buffer (the ROADMAP "device-epoch on Column caches"
   open item).  `executor/device_join._leaf_env` stamps its ``leaf.dcols``
   caches with the same epoch; their byte accounting rides on the
   underlying Column entries (the leaf dict holds views/slices of them).

3. **OOM recovery** — `recover_oom()` is step one of the ladder
   ``evict-all → single retry → host degradation`` that
   `executor/device_exec.run_device` walks when a classified device OOM
   (`utils/backoff.is_device_oom`) surfaces: drop every cached device
   value (freeing the HBM they pin), retry the fragment once against the
   emptied device, and only then let the existing per-shape circuit
   breaker degrade to the host engine.

**Tenancy (ISSUE 6)**: every entry is charged to the resource group of
the session that uploaded it (``tidb_resource_group``, bridged onto
supervisor worker threads), and ``tidb_device_mem_budget`` is enforced
as per-group SHARES under pressure: a tenant over its share evicts its
OWN cold entries before touching another tenant's (see
`_enforce_budget_locked`), so one tenant's upload storm cannot flush a
well-behaved neighbor's working set.

All ``._device`` reads/writes live in THIS module (AST-linted in
tests/test_residency.py) so HBM caching can never silently escape the
ledger.  Gauges — ``hbm_bytes_cached``, ``hbm_evictions``,
``hbm_oom_recoveries`` — surface in EXPLAIN ANALYZE, observe gauges, the
HTTP ``/status`` + ``/metrics`` endpoints, and bench.py lines.
"""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import weakref

log = logging.getLogger("tidb_tpu.residency")

#: one reentrant lock guards the ledger, the LRU order and the epoch —
#: reentrant because a weakref GC callback can fire while this module
#: already holds the lock on the same thread
_LOCK = threading.RLock()

#: the device epoch: bumped on every backend quarantine/fence.  Cached
#: device values are stamped with it and checked on read.
_EPOCH = [0]

#: resident bytes ledger (sum of every live entry's nbytes)
_BYTES = [0]

#: per-tenant slice of the ledger: resource group -> resident bytes.
#: Each entry is charged to the group that uploaded it (the session's
#: ``tidb_resource_group``, bridged per-dispatch via attach()), so the
#: budget can be enforced as per-group SHARES: a tenant over its share
#: evicts its OWN cold entries before touching another tenant's.
_GROUP_BYTES: "collections.Counter" = collections.Counter()

DEFAULT_GROUP = "default"

#: the uploading thread's resource group (set by attach() before each
#: dispatch; worker threads inherit "default" when nothing attached)
_TLS = threading.local()

#: configured budget in bytes (from tidb_device_mem_budget); 0 = auto
_BUDGET = [0]
#: memoized auto-derived budget (None = not yet probed)
_AUTO_BUDGET = [None]

_SEQ = itertools.count(1)

#: LRU of live cached uploads: token -> _Entry (insertion order = age;
#: move_to_end on every cache hit)
_ENTRIES: "collections.OrderedDict[int, _Entry]" = collections.OrderedDict()

STATS = {
    "uploads": 0,          # publishes that installed a new cached value
    "hits": 0,             # lookups served from cache
    "hbm_evictions": 0,    # entries evicted (budget, grow, epoch, OOM)
    "hbm_evicted_bytes": 0,
    "hbm_oom_recoveries": 0,  # evict-all passes taken for a device OOM
    "epoch_bumps": 0,
    "publish_races": 0,    # racing publish lost to an existing entry
    "gc_releases": 0,      # owners collected with their entry still live
}

#: Observability sinks (session/observe.py) mirroring the gauges —
#: registered from the contexts device dispatches run under
_SINKS: "weakref.WeakSet" = weakref.WeakSet()

#: the serving fabric's fleet hook (tidb_tpu/fabric/state.py installs a
#: _ResidencyFleet at worker boot): per-group byte DELTAS publish to the
#: coordination segment, and a group's share consumption reads
#: fleet-wide — a tenant filling worker A's HBM share is over-share on
#: worker B too, so its uploads there self-evict first instead of
#: squeezing B's light tenants.  None (all paths local) outside a fleet.
#: Lock order: the segment's flock nests inside the ledger _LOCK.
_FLEET = [None]


def set_fleet(hook):
    """Install (or clear, with None) the fleet residency hook."""
    with _LOCK:
        _FLEET[0] = hook


def _fleet_charge_locked(group: str, delta: int):
    fleet = _FLEET[0]
    if fleet is not None:
        try:
            fleet.charge(group, delta)
        except Exception as e:  # noqa: BLE001 — segment mirror only
            log.warning("fleet HBM charge failed for %r (%+d bytes; "
                        "local ledger stays exact): %s", group, delta, e)


def _fleet_remote_bytes(group: str) -> int:
    fleet = _FLEET[0]
    if fleet is None:
        return 0
    try:
        return fleet.remote_bytes(group)
    except Exception as e:  # noqa: BLE001 — degrade to local shares
        log.warning("fleet HBM read failed for %r (local share only): %s",
                    group, e)
        return 0


class _Resident:
    """The value stored on ``Column._device``: the padded device arrays
    plus the stamps the manager checks on every read."""

    __slots__ = ("data", "nulls", "rows", "epoch", "nbytes", "token")

    def __init__(self, data, nulls, rows, epoch, nbytes, token):
        self.data = data
        self.nulls = nulls
        self.rows = rows
        self.epoch = epoch
        self.nbytes = nbytes
        self.token = token


class _Entry:
    """Ledger entry for one cached upload: a weakref back to the owning
    Column (to clear its slot on eviction, and to release the bytes when
    the owner is garbage-collected) plus the byte charge and the resource
    group it is charged to."""

    __slots__ = ("ref", "nbytes", "token", "group")

    def __init__(self, ref, nbytes, token, group=DEFAULT_GROUP):
        self.ref = ref
        self.nbytes = nbytes
        self.token = token
        self.group = group


class CacheOwner:
    """Weakref-able owner object for cached device values whose natural
    owner is NOT a utils.chunk.Column — e.g. the MPP mesh placement cache
    (executor/mpp_exec.py), whose values are mesh-sharded global arrays.
    Holding the managed ``_device`` slot HERE keeps every HBM cache
    inside this module's lint boundary: lookup()/publish() work on a
    CacheOwner exactly as on a Column, so placement entries are
    byte-accounted, LRU-evictable, epoch-stamped and part of the OOM
    evict-all ladder like any other upload."""

    __slots__ = ("_device", "__weakref__")

    def __init__(self):
        self._device = None


def _nbytes(arr) -> int:
    try:
        return int(arr.nbytes)
    except Exception:
        try:
            return int(arr.size) * int(arr.dtype.itemsize)
        except Exception:
            return 0


# -- epoch -------------------------------------------------------------------

def device_epoch() -> int:
    """The current device epoch.  Caches stamped with an older epoch are
    stale (their buffers may belong to a torn-down PJRT client)."""
    return _EPOCH[0]


def bump_epoch(reason: str = "") -> int:
    """Invalidate every cached device value: bump the epoch and clear the
    ledger.  Called by the supervisor on every backend quarantine
    (fence / hang-abandon) BEFORE the reinit, so nothing uploaded against
    the suspect client can survive into the re-dialed one."""
    with _LOCK:
        _EPOCH[0] += 1
        epoch = _EPOCH[0]
        STATS["epoch_bumps"] += 1
        n = _evict_all_locked()
    if n:
        log.info("device epoch -> %d (%s): %d cached uploads invalidated",
                 epoch, reason or "fence", n)
    _publish_gauges()
    return epoch


# -- budget ------------------------------------------------------------------

def attach(ctx):
    """Per-dispatch hookup (called by run_device): resolve the budget
    from ``tidb_device_mem_budget`` and register the Domain's observe
    registry as a gauge sink.

    The budget is read from the Domain's GLOBAL variables (`SET GLOBAL
    tidb_device_mem_budget`), same discipline as the circuit-breaker
    knobs: the ledger is process-wide, so a session-scoped SET must not
    clobber the budget another session configured (last-dispatcher-wins
    on a shared resource).  Only a bare context with no Domain falls
    back to its own session view."""
    if ctx is None:
        return
    dom = getattr(ctx, "domain", None)
    try:
        if dom is not None:
            budget = max(
                int(dom.global_vars.get("tidb_device_mem_budget", 0)), 0)
        else:
            budget = max(
                int(ctx.get_sysvar("tidb_device_mem_budget")), 0)
        with _LOCK:
            _BUDGET[0] = budget
    except Exception:
        pass
    obs = getattr(dom, "observe", None)
    if obs is not None and hasattr(obs, "set_gauge"):
        with _LOCK:
            _SINKS.add(obs)
    # tenant identity for the uploads this dispatch will publish (the
    # session's tidb_resource_group, SESSION scope — tenancy is per
    # connection; the supervisor bridges it onto worker threads)
    try:
        set_group(str(ctx.get_sysvar("tidb_resource_group")).strip()
                  or DEFAULT_GROUP)
    except Exception:
        set_group(DEFAULT_GROUP)


def set_group(group: str):
    """Charge subsequent publishes on THIS thread to `group`."""
    _TLS.group = group or DEFAULT_GROUP


def current_group() -> str:
    return getattr(_TLS, "group", DEFAULT_GROUP)


def set_budget(n: int):
    """Set the budget in bytes directly (tests / embedders); 0 = auto."""
    with _LOCK:
        _BUDGET[0] = max(int(n), 0)


def _auto_budget() -> int:
    """jax-reported device memory limit, or 0 (unlimited) when the
    backend is the in-process CPU client (host RAM is governed by the
    MemTracker quota tree, not this manager) or unreported.

    Same discipline as every config refresh: the memo check and the
    publish happen under _LOCK, the device probe runs OUTSIDE it (a
    one-time PJRT memory_stats call must not serialize every concurrent
    lookup/evict behind it; a racing double-probe is idempotent and the
    first publish wins).  A caller already holding the reentrant ledger
    lock — _enforce_budget_locked's first-ever budget resolution —
    still probes under its own hold, once."""
    with _LOCK:
        if _AUTO_BUDGET[0] is not None:
            return _AUTO_BUDGET[0]
    budget = 0
    try:
        import jax
        if jax.default_backend() != "cpu":
            stats = jax.devices()[0].memory_stats() or {}
            budget = int(stats.get("bytes_limit", 0))
    except Exception:
        budget = 0
    with _LOCK:
        if _AUTO_BUDGET[0] is None:
            _AUTO_BUDGET[0] = budget
        return _AUTO_BUDGET[0]


def effective_budget() -> int:
    """Resolved budget in bytes (0 = unlimited)."""
    with _LOCK:
        override = _BUDGET[0]
    return override if override > 0 else _auto_budget()


# -- the cache protocol (ops/device.to_device_col) ---------------------------

def lookup(col, want_rows: int):
    """Cached ``(data, nulls)`` for `col` if present, epoch-current and at
    least `want_rows` long; else None (any stale/short entry is evicted
    so the caller rebuilds).  A hit touches the LRU."""
    with _LOCK:
        res = col._device
        if res is None:
            return None
        if res.epoch != _EPOCH[0]:
            # stale pre-fence buffer: evict eagerly — it must never be
            # served again NOR keep its bytes on the ledger
            _evict_token_locked(res.token)
            return None
        if res.rows < want_rows:
            # grow: miss WITHOUT evicting — the old entry keeps serving
            # shorter-bucket readers until publish() swaps it (the cache
            # stays write-once for concurrent consumers, and a rebuild
            # that fails mid-flight leaves the column still cached)
            return None
        ent = _ENTRIES.get(res.token)
        if ent is not None:
            _ENTRIES.move_to_end(res.token)
        STATS["hits"] += 1
        return res.data, res.nulls


def publish(col, data, nulls):
    """Install a freshly built upload as `col`'s cached device value and
    charge its bytes; returns the arrays to use.

    Compare-and-keep under the ledger lock: when a RACING builder already
    published an epoch-current entry at least as long, the existing entry
    WINS and this caller's arrays are discarded — the loser's bytes are
    counted as immediately evicted, never silently leaked outside the
    ledger (the pre-residency "last wins" publish leaked the loser's HBM
    buffer untracked until GC)."""
    nbytes = _nbytes(data) + _nbytes(nulls)
    rows = int(data.shape[0])
    budget_evicted = 0
    with _LOCK:
        cur = col._device
        if (cur is not None and cur.epoch == _EPOCH[0]
                and cur.rows >= rows and cur.token in _ENTRIES):
            # lost the publish race: keep the incumbent, account the loser
            STATS["publish_races"] += 1
            STATS["hbm_evictions"] += 1
            STATS["hbm_evicted_bytes"] += nbytes
            _ENTRIES.move_to_end(cur.token)
            out = cur.data, cur.nulls
        else:
            if cur is not None:
                _evict_token_locked(cur.token)
            token = next(_SEQ)
            group = current_group()
            res = _Resident(data, nulls, rows, _EPOCH[0], nbytes, token)
            col._device = res
            try:
                ref = weakref.ref(col, _make_gc_cb(token))
            except TypeError:
                ref = None  # owner not weakref-able: entry lives forever
            _ENTRIES[token] = _Entry(ref, nbytes, token, group)
            _BYTES[0] += nbytes
            _GROUP_BYTES[group] += nbytes
            _fleet_charge_locked(group, nbytes)
            STATS["uploads"] += 1
            ev0 = STATS["hbm_evictions"]
            _enforce_budget_locked(keep_token=token, group=group)
            budget_evicted = STATS["hbm_evictions"] - ev0
            out = data, nulls
    _publish_gauges()
    if budget_evicted:
        # span tracing (session/tracing.py): budget-pressure evictions on
        # the statement's timeline — recorded OUTSIDE the ledger lock
        from ..session.tracing import event as _trace_event
        _trace_event("residency.evict", n=budget_evicted, reason="budget")
    return out


def _make_gc_cb(token):
    def _cb(_ref, _token=token):
        with _LOCK:
            ent = _ENTRIES.pop(_token, None)
            if ent is not None:
                _BYTES[0] -= ent.nbytes
                _drop_group_bytes_locked(ent.group, ent.nbytes)
                STATS["gc_releases"] += 1
    return _cb


def _drop_group_bytes_locked(group: str, nbytes: int):
    _GROUP_BYTES[group] -= nbytes
    if _GROUP_BYTES[group] <= 0:
        del _GROUP_BYTES[group]
    _fleet_charge_locked(group, -nbytes)


# -- eviction ----------------------------------------------------------------

def _evict_token_locked(token: int):
    ent = _ENTRIES.pop(token, None)
    if ent is None:
        return
    _BYTES[0] -= ent.nbytes
    _drop_group_bytes_locked(ent.group, ent.nbytes)
    STATS["hbm_evictions"] += 1
    STATS["hbm_evicted_bytes"] += ent.nbytes
    col = ent.ref() if ent.ref is not None else None
    if col is not None:
        res = col._device
        if res is not None and res.token == token:
            col._device = None


def group_share() -> int:
    """Each active tenant's slice of the budget in bytes (0 = no budget):
    the budget divided equally among the groups that currently hold
    resident entries.  A lone tenant keeps the whole budget — shares are
    a pressure-time fairness rule, not a static partition."""
    with _LOCK:
        return _group_share_locked()


def free_share_bytes(group: str | None = None) -> int:
    """The LIVE headroom of `group`'s budget share (calling thread's
    group when None): share minus the bytes the group already holds
    resident, floored at a quarter of the share — a tenant whose cache
    is momentarily full must still be able to place a working set (the
    LRU will evict its own cold entries to make room), so the floor
    keeps memory-adaptive operators (the hybrid hash join's partition
    sizing, executor/hybrid_join.py) from collapsing to all-spill just
    because the previous query's uploads are still warm.  0 = no budget
    configured (unlimited)."""
    with _LOCK:
        share = _group_share_locked()
        if share <= 0:
            return 0
        g = group if group is not None else current_group()
        # under the serving fabric a tenant's consumption is FLEET-wide:
        # the share headroom that sizes memory-adaptive operators must
        # see the bytes this tenant holds in every sibling worker too
        held = _GROUP_BYTES.get(g, 0) + _fleet_remote_bytes(g)
        return max(share - held, share // 4)


def _group_share_locked() -> int:
    budget = effective_budget()
    if budget <= 0:
        return 0
    return budget // max(len(_GROUP_BYTES), 1)


def _enforce_budget_locked(keep_token: int, group: str = DEFAULT_GROUP):
    """Evict until under budget — SELF-FIRST, then over-share, then
    global LRU.  `keep_token` (the entry just published) is exempt: the
    working set of the CURRENT fragment must not be evicted out from
    under its own dispatch.

    Tenancy rule (ISSUE 6): one tenant's uploads evict its OWN cold
    entries before touching another tenant's — as long as the uploader
    holds more than its per-group share of the budget, its own LRU pays
    first.  Only when every group is back within its share (or the
    uploader has nothing left to give) does eviction fall back to the
    over-share groups and finally plain global LRU."""
    budget = effective_budget()
    if budget <= 0:
        return
    share = _group_share_locked()
    # phase 1 — self-first: the uploading tenant over its share evicts
    # its own cold entries (other tenants' working sets are protected).
    # Under the fabric "over its share" counts the tenant's bytes in
    # EVERY worker (one segment read per enforce, constant across the
    # loop — local evictions are what shrink the left side); phase 2's
    # per-entry checks stay local to keep eviction off the segment lock.
    remote = _fleet_remote_bytes(group)
    while (_BYTES[0] > budget
           and _GROUP_BYTES.get(group, 0) + remote > share):
        victim = None
        for token, ent in _ENTRIES.items():  # oldest first
            if token != keep_token and ent.group == group:
                victim = token
                break
        if victim is None:
            break
        _evict_token_locked(victim)
    # phase 2 — over-share tenants LRU-first, then global LRU
    while _BYTES[0] > budget:
        victim = None
        fallback = None
        for token, ent in _ENTRIES.items():  # oldest first
            if token == keep_token:
                continue
            if fallback is None:
                fallback = token
            if _GROUP_BYTES.get(ent.group, 0) > share:
                victim = token
                break
        victim = victim if victim is not None else fallback
        if victim is None:
            if _BYTES[0] > budget:
                log.warning(
                    "device upload of %d bytes exceeds "
                    "tidb_device_mem_budget=%d alone; kept (single "
                    "working column beats a livelock)", _BYTES[0], budget)
            return
        _evict_token_locked(victim)


def _evict_all_locked() -> int:
    n = len(_ENTRIES)
    for token in list(_ENTRIES):
        _evict_token_locked(token)
    return n


def evict_all(reason: str = "") -> int:
    """Drop every cached device value (ledger goes to zero).  Returns the
    number of entries evicted."""
    with _LOCK:
        n = _evict_all_locked()
    if n:
        log.info("evicted all %d cached device uploads (%s)",
                 n, reason or "explicit")
        from ..session.tracing import event as _trace_event
        _trace_event("residency.evict", n=n,
                     reason=reason or "explicit")
    _publish_gauges()
    return n


def recover_oom(err=None) -> int:
    """Step one of the OOM ladder (evict-all → retry → degrade): free
    every byte this manager pins so the retry dispatches against an
    emptied device.  The epoch is bumped TOO: a mid-flight join-leaf
    ``dcols`` dict holds references to the evicted arrays, and without an
    epoch change the retry's `_leaf_env` would hand the same dict back —
    re-pinning the very buffers this eviction freed.  The epoch mismatch
    forces every consumer to re-derive its device state from Columns."""
    with _LOCK:
        STATS["hbm_oom_recoveries"] += 1
        _EPOCH[0] += 1
        STATS["epoch_bumps"] += 1
        n = _evict_all_locked()
    log.warning("device OOM (%s): evicted %d cached uploads, retrying once "
                "before host degradation", err, n)
    _publish_gauges()
    from ..session.tracing import event as _trace_event
    _trace_event("residency.evict", n=n, reason="oom")
    return n


# -- introspection -----------------------------------------------------------

def resident_nbytes(owner) -> int:
    """Byte charge of `owner`'s cached value if it is live, epoch-current
    and still on the ledger; else 0.  Pure introspection: no LRU touch,
    no stats — gauge plumbing (e.g. the MPP placement-cache bytes gauge)
    must not look like cache traffic."""
    return resident_nbytes_total((owner,))


def resident_nbytes_total(owners) -> int:
    """Sum of resident_nbytes over `owners` under ONE ledger-lock
    acquisition — gauge plumbing runs on every query and every
    /status//metrics scrape, and must not contend the upload/evict lock
    once per cached owner."""
    total = 0
    with _LOCK:
        for owner in owners:
            res = owner._device
            if res is None or res.epoch != _EPOCH[0]:
                continue
            ent = _ENTRIES.get(res.token)
            if ent is not None:
                total += ent.nbytes
    return total


def resident_bytes() -> int:
    """The ``hbm_bytes_cached`` gauge."""
    with _LOCK:
        return _BYTES[0]


def snapshot() -> dict:
    with _LOCK:
        return {
            "epoch": _EPOCH[0],
            "hbm_bytes_cached": _BYTES[0],
            "entries": len(_ENTRIES),
            "budget_bytes": effective_budget(),
            "by_group": dict(_GROUP_BYTES),
            "group_share_bytes": _group_share_locked(),
            **STATS,
        }


def report_gauges() -> dict:
    """The surfacing policy shared by EXPLAIN ANALYZE annotations and
    bench.py lines: ``hbm_bytes_cached`` always; the eviction /
    OOM-recovery counters only once they have ever fired (pressure is
    the exception, not annotation noise on every healthy plan)."""
    s = snapshot()
    out = {"hbm_bytes_cached": s["hbm_bytes_cached"]}
    if s["hbm_evictions"]:
        out["hbm_evictions"] = s["hbm_evictions"]
    if s["hbm_oom_recoveries"]:
        out["hbm_oom_recoveries"] = s["hbm_oom_recoveries"]
    return out


def verify_ledger() -> dict:
    """Recompute the ledger from live entries (chaos-harness invariant:
    no budget-counter drift), INCLUDING the per-tenant slices: the group
    counters must sum from the live entries exactly, and their total must
    equal the global ledger.  Returns {"ok", "ledger", "recomputed",
    "by_group", "by_group_recomputed"}."""
    import collections as _c
    with _LOCK:
        recomputed = sum(e.nbytes for e in _ENTRIES.values())
        by_group_rec = _c.Counter()
        for e in _ENTRIES.values():
            by_group_rec[e.group] += e.nbytes
        groups_ok = (dict(by_group_rec) == dict(_GROUP_BYTES)
                     and sum(_GROUP_BYTES.values()) == _BYTES[0])
        return {"ok": (recomputed == _BYTES[0] and _BYTES[0] >= 0
                       and groups_ok),
                "ledger": _BYTES[0], "recomputed": recomputed,
                "by_group": dict(_GROUP_BYTES),
                "by_group_recomputed": dict(by_group_rec)}


def _publish_gauges():
    with _LOCK:
        sinks = list(_SINKS)
        vals = {"hbm_bytes_cached": _BYTES[0],
                "hbm_evictions": STATS["hbm_evictions"],
                "hbm_oom_recoveries": STATS["hbm_oom_recoveries"]}
    for obs in sinks:
        try:
            for k, v in vals.items():
                obs.set_gauge(k, v)
        except Exception:
            pass
