"""The fleet coordination segment: one shared-memory block + a tiny
lease-stamped coordinator file, giving N serving processes a common view
of admission state, tenant budgets and in-flight fragment dedup.

Why shared memory and not a coordination service: the hot operations are
admission-rate (one per device fragment) — a socket round trip per
admission would put a second serving queue in front of the scheduler.
A pinned struct layout over ``multiprocessing.shared_memory`` plus an
``fcntl.flock`` critical section costs ~a syscall pair per operation and
survives any worker's death: the flock drops with the process, and the
lease stamps let survivors reclaim the dead slot's counters.

Layout (little-endian, fixed offsets — no allocation after create):

    HEADER    magic, nslots, ntenants, ndedup, created
    COUNTERS  fleet-global u64 counters (dedup hits/leads/timeouts,
              lease reclaims, respawns, prewarm dedup, result-id seq)
              plus the durable-store CELLS: the fleet TSO high-water
              (``_tso`` — batched leases make every worker's timestamps
              fleet-monotonic), the published schema version
              (``_schema_ver`` — the schema-lease propagation cell) and
              the committed WAL length (``_wal_len`` — appenders
              truncate any torn garbage a SIGKILLed writer left past it
              before writing, and tailers never read beyond it)
    SLOTS     per-worker lease: pid, lease_ts, generation, plus the
              slot's MIN READ TS (oldest live snapshot — the fleet GC
              floor) and its APPLIED WAL LSN (how far its replica
              tailed — the log-truncation floor)
    TENANTS   per-tenant row: name, WFQ virtual clock, peak running,
              running[slot] and hbm_bytes[slot] COLUMNS — per-slot
              attribution is what makes crash reclaim exact: zeroing a
              dead slot's column cannot touch a survivor's counts
    DEDUP     fragment result-cache slots: key hash, state, owner slot,
              timestamp, result page id and the VERSION-VECTOR hash the
              page was computed under (0 = unversioned in-flight dedup).
              A versioned hit requires the claimant's current version-
              vector hash to match; a mismatch invalidates the entry and
              hands the claimant the OLD page id so it can fold only the
              delta since the cached version (dedup_claim "lead_delta")
    LOCKS     the shared 2PC lock/primary table (kv/shared_store.py):
              key-HASH entries stamped (start_ts, owner slot) make
              cross-worker write-write conflict detection synchronous —
              a prewrite claims here BEFORE its local checks, so two
              workers can never prewrite the same key concurrently; a
              dead slot's claims are freed by lease reclaim (the data
              locks themselves are resolved by WAL recovery)
    REGIONS   per-region ownership rows (fabric/region.py): epoch,
              owner slot (+1; 0 = unowned), lease_ts, committed WAL
              length and applied LSN — the per-region mirror of the
              global ``_wal_len``/slot cells.  The EPOCH is the fencing
              token: it bumps on every claim, every committed-length
              write carries it, and a stale epoch's write is rejected —
              a zombie host's appender can never land bytes in a region
              that failed over behind its back
    TABLEVERS per-table fleet version cells: (table id, version ts) —
              the CURRENT fleet version of each table, advanced forward-
              only (max) on every committed write by the committing
              worker and re-published by every tailer as it applies the
              log (a coordinator down-window on the writer is repaired
              by the first survivor to tail the record).  The result
              cache stamps pages with these and a hit requires every
              referenced table's cell to still match
    FRONTIERS per-slot committed-frontier cells: (frontier_ts,
              frontier_lsn) — the max commit_ts the slot's appender has
              made durable and the log length covering it.  Snapshot
              begin waits until the local applied LSN covers every live
              origin's frontier <= its ts: the fleet-wide
              snapshot-isolation fence (kv/shared_store.py)
    DDL       the single fleet DDL owner cell (epoch, owner+1,
              lease_ts): the region-ownership shape applied to the DDL
              job queue — one epoch-fenced owner at a time, failover by
              lease expiry, a deposed owner's commit fails the fence

Every mutation happens under the sidecar lock file (``<path>.lock``,
``fcntl.flock``) plus an in-process mutex (flock is per open file
description, so two THREADS of one process sharing the fd would not
exclude each other).  Lock order: callers holding subsystem locks
(scheduler._LOCK, the residency ledger lock) may take the segment lock;
the segment layer never calls back out, so no cycle can form.

The coordinator FILE is the discovery root: it names the segment and the
result-page directory, so any process (workers, the parent, a bench
verifier) can ``attach`` by path alone.  Invariant (chaos-asserted,
:meth:`Coordinator.verify_drained`): once the fleet drains, no lease is
live, every per-tenant running count is zero and no dedup slot is stuck
``building`` — a crashed worker's contributions are reclaimed by lease
expiry, never leaked.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import logging
import os
import secrets
import struct
import threading
import time
from multiprocessing import shared_memory

log = logging.getLogger("tidb_tpu.fabric.coord")

MAGIC = b"TPUFAB5\0"

#: segment geometry defaults (fixed at create; attach reads them from the
#: coordinator file)
NSLOTS_DEFAULT = 16
NTENANTS_DEFAULT = 48
NDEDUP_DEFAULT = 128
NLOCKS_DEFAULT = 256
#: regions default to 0: a single-host fleet pays nothing for the
#: section, and a region-sharded one sizes it explicitly at create
NREGIONS_DEFAULT = 0
#: per-table version cells; a fleet serving more distinct tables than
#: this simply stops version-stamping the overflow (cache-ineligible,
#: never stale)
NTABLEVERS_DEFAULT = 256
#: fragment performance-store rows (ISSUE 18): keyed
#: (fragment sig hash, row bucket, backend, duration kind); a full
#: table drops the overflow (counted fabric_perf_dropped) — the store
#: observes, it must never become a serving bottleneck or a leak
NPERF_DEFAULT = 512

#: fleet-global counter names, in segment order
COUNTER_NAMES = (
    "fabric_dedup_hits",        # follower served from a leader's page
    "fabric_dedup_leads",       # fragments that led a dedup slot
    "fabric_dedup_timeouts",    # waits that gave up and computed locally
    "fabric_lease_reclaims",    # dead-slot reclaims (leases expired)
    "fabric_respawns",          # parent worker respawns
    "fabric_prewarm_dedup",     # prewarm submissions skipped fleet-wide
    "fabric_cache_hits",        # version-stamped result-cache hits
    "fabric_cache_invalidations",  # cached pages dropped on version advance
    "fabric_cache_delta_folds",    # hits served by folding the WAL delta
    "fabric_cache_stale_reads",    # version-stale pages caught at serve
    "fabric_admissions",        # device admissions granted fleet-wide
    "fabric_perf_dropped",      # perf samples dropped (store full)
    "_result_id_seq",           # monotonic dedup result-page id
    "_tso",                     # fleet TSO high-water (batched leases)
    "_schema_ver",              # published schema version (schema lease)
    "_wal_len",                 # committed WAL length (torn-tail fence)
)

#: dedup slot states
DFREE, DBUILDING, DDONE, DFAILED = 0, 1, 2, 3

#: a building dedup entry whose leader lease is older than this is
#: considered abandoned (leader crashed mid-build) and can be taken over
BUILD_LEASE_S = 10.0

_HDR = struct.Struct("<8sIIIId")                         # + created f64
_SLOT = struct.Struct("<QdQQQ")                          # pid, lease, gen,
#                                                          min_read_ts,
#                                                          wal_applied
_DED = struct.Struct("<16sIIdQQ")                        # hash,state,owner,ts,
#                                                          rid, vv_hash
_TEN_FIXED = struct.Struct("<40sdII")                    # name,vtime,peak,pad
_LCK = struct.Struct("<16sQId")                          # hash,start_ts,slot,ts
_REG = struct.Struct("<QQdQQ")                           # epoch, owner+1,
#                                                          lease_ts,
#                                                          committed_len,
#                                                          applied_lsn
_TVER = struct.Struct("<QQ")                             # table_id, version_ts
#: per-slot committed-frontier cell: the max commit_ts this slot's WAL
#: appender has made DURABLE (fsync-acked) and the log length that
#: covers it.  Readers wait until their local applied LSN reaches every
#: live origin's frontier_lsn whose frontier_ts <= their snapshot ts —
#: the fleet-wide snapshot-isolation fence (ISSUE 19)
_FRONT = struct.Struct("<QQ")                            # frontier_ts,
#                                                          frontier_lsn
#: the single fleet DDL owner cell: epoch, owner slot (+1; 0 = unowned),
#: lease_ts — the region-ownership shape applied to the DDL job queue,
#: replacing serialize-by-conflict with an epoch-fenced lease
_DDL = struct.Struct("<QQd")
#: perf-store row: sig_hash, bucket, backend, kind, count, sum_s, max_s,
#: 16-bucket log2 duration sketch.  A row is FREE iff count == 0.
#: Crash-safety is by construction, not by reclaim: every update is one
#: commutative merge under the segment lock (no per-slot intermediate
#: state a dead worker could leak — unlike running counts, a crashed
#: worker's already-merged samples are real measurements and stay)
_PERF = struct.Struct("<QIIIQdd16I")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

#: perf sketch geometry: bucket i counts durations <= PERF_BASE_S * 2**i
#: (i = 15 is the +Inf tail).  100µs .. ~3.3s in 16 power-of-two steps —
#: spans admission waits through live-TPU compiles
PERF_BASE_S = 1e-4
PERF_SKETCH_N = 16

_NAME_SZ = 40


class Coordinator:
    """One attached view of the fleet coordination segment."""

    def __init__(self, path: str, shm, meta: dict, created: bool):
        self.path = path
        self._shm = shm
        self._buf = shm.buf
        self.nslots = meta["nslots"]
        self.ntenants = meta["ntenants"]
        self.ndedup = meta["ndedup"]
        self.nlocks = meta.get("nlocks", NLOCKS_DEFAULT)
        self.nregions = meta.get("nregions", NREGIONS_DEFAULT)
        self.ntablevers = meta.get("ntablevers", NTABLEVERS_DEFAULT)
        self.nperf = meta.get("nperf", NPERF_DEFAULT)
        self.pages_dir = meta["pages_dir"]
        self._created = created
        self._tlock = threading.Lock()
        self._lockf = open(path + ".lock", "a+b")  # noqa: SIM115 (held open)
        # offsets
        self._o_counters = _HDR.size
        self._o_slots = self._o_counters + 8 * len(COUNTER_NAMES)
        self._o_tenants = self._o_slots + self.nslots * _SLOT.size
        self._ten_sz = (_TEN_FIXED.size + 4 * self.nslots
                        + 8 * self.nslots)
        self._o_dedup = self._o_tenants + self.ntenants * self._ten_sz
        self._o_locks = self._o_dedup + self.ndedup * _DED.size
        self._o_regions = self._o_locks + self.nlocks * _LCK.size
        self._o_tvers = self._o_regions + self.nregions * _REG.size
        # per-slot direct-port cells (u64): each worker publishes its
        # diagnostics door so peers can fan cluster memtables out to it
        self._o_ports = self._o_tvers + self.ntablevers * _TVER.size
        # per-slot committed-frontier cells + the fleet DDL owner cell
        self._o_front = self._o_ports + self.nslots * 8
        self._o_ddl = self._o_front + self.nslots * _FRONT.size
        self._o_perf = self._o_ddl + _DDL.size
        self.size = self._o_perf + self.nperf * _PERF.size

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, path: str, nslots: int = NSLOTS_DEFAULT,
               ntenants: int = NTENANTS_DEFAULT,
               ndedup: int = NDEDUP_DEFAULT,
               nlocks: int = NLOCKS_DEFAULT,
               nregions: int = NREGIONS_DEFAULT,
               ntablevers: int = NTABLEVERS_DEFAULT,
               nperf: int = NPERF_DEFAULT,
               pages_dir: "str | None" = None) -> "Coordinator":
        """Create the segment + coordinator file (the fleet parent)."""
        if pages_dir is None:
            pages_dir = path + ".pages"
        os.makedirs(pages_dir, exist_ok=True)
        name = f"tpufab-{os.getpid()}-{secrets.token_hex(4)}"
        meta = {"segment": name, "nslots": nslots, "ntenants": ntenants,
                "ndedup": ndedup, "nlocks": nlocks, "nregions": nregions,
                "ntablevers": ntablevers, "nperf": nperf,
                "pages_dir": pages_dir, "created": time.time()}
        size = (_HDR.size + 8 * len(COUNTER_NAMES) + nslots * _SLOT.size
                + ntenants * (_TEN_FIXED.size + 12 * nslots)
                + ndedup * _DED.size + nlocks * _LCK.size
                + nregions * _REG.size + ntablevers * _TVER.size
                + nslots * 8 + nslots * _FRONT.size + _DDL.size
                + nperf * _PERF.size)
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        _untrack(shm)
        shm.buf[:size] = b"\0" * size
        _HDR.pack_into(shm.buf, 0, MAGIC, nslots, ntenants, ndedup, 0,
                       meta["created"])
        tmp = path + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)
        return cls(path, shm, meta, created=True)

    @classmethod
    def attach(cls, path: str) -> "Coordinator":
        """Attach to an existing segment by coordinator-file path."""
        with open(path) as f:
            meta = json.load(f)
        shm = shared_memory.SharedMemory(name=meta["segment"])
        _untrack(shm)
        if bytes(shm.buf[:8]) != MAGIC:
            shm.close()
            raise ValueError(f"{path}: segment {meta['segment']} has no "
                             "fabric magic (stale coordinator file?)")
        return cls(path, shm, meta, created=False)

    def close(self):
        try:
            self._buf = None
            self._shm.close()
        finally:
            with contextlib.suppress(Exception):
                self._lockf.close()

    def unlink(self):
        """Destroy the segment + coordinator file (parent, at shutdown).
        Raw shm_unlink, not SharedMemory.unlink(): every attachment was
        untracked (see _untrack), so the tracker holds no entry for its
        unregister call to find."""
        name = self._shm._name
        self.close()
        with contextlib.suppress(Exception):
            shared_memory._posixshmem.shm_unlink(name)
        for p in (self.path, self.path + ".lock"):
            with contextlib.suppress(OSError):
                os.remove(p)
        # every remaining result page goes with the segment (pages that
        # expired in place were GC'd at slot reuse; this is the tail)
        with contextlib.suppress(OSError):
            for f in os.listdir(self.pages_dir):
                if f.startswith("dedup-"):
                    with contextlib.suppress(OSError):
                        os.remove(os.path.join(self.pages_dir, f))
            os.rmdir(self.pages_dir)

    @contextlib.contextmanager
    def _locked(self):
        with self._tlock:
            fcntl.flock(self._lockf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._lockf, fcntl.LOCK_UN)

    # -- counters ------------------------------------------------------------

    def _ctr_off(self, name: str) -> int:
        return self._o_counters + 8 * COUNTER_NAMES.index(name)

    def bump(self, name: str, n: int = 1) -> int:
        with self._locked():
            return self._bump_locked(name, n)

    def _bump_locked(self, name: str, n: int = 1) -> int:
        off = self._ctr_off(name)
        v = _U64.unpack_from(self._buf, off)[0] + n
        _U64.pack_into(self._buf, off, v)
        return v

    def counters(self) -> dict:
        with self._locked():
            return {name: _U64.unpack_from(self._buf, self._ctr_off(name))[0]
                    for name in COUNTER_NAMES if not name.startswith("_")}

    # -- worker slots / leases -----------------------------------------------

    def _slot_off(self, slot: int) -> int:
        if not 0 <= slot < self.nslots:
            raise IndexError(f"slot {slot} out of range 0..{self.nslots - 1}")
        return self._o_slots + slot * _SLOT.size

    def claim_slot(self, slot: int, pid: "int | None" = None):
        """A worker takes its slot: stamps pid + lease, bumps the
        incarnation generation, and zeroes any leftovers from a previous
        incarnation (self-reclaim on respawn)."""
        pid = pid if pid is not None else os.getpid()
        with self._locked():
            off = self._slot_off(slot)
            _pid, _lease, gen, _mrt, _wa = _SLOT.unpack_from(self._buf, off)
            self._zero_slot_columns_locked(slot)
            self._drop_slot_published_locked(slot)
            _SLOT.pack_into(self._buf, off, pid, time.time(), gen + 1, 0, 0)

    def heartbeat(self, slot: int):
        with self._locked():
            off = self._slot_off(slot)
            pid, _lease, gen, mrt, wa = _SLOT.unpack_from(self._buf, off)
            if pid:
                _SLOT.pack_into(self._buf, off, pid, time.time(), gen,
                                mrt, wa)

    def release_slot(self, slot: int):
        """Clean worker exit: drop the lease and every per-slot count."""
        with self._locked():
            self._zero_slot_columns_locked(slot)
            self._drop_slot_published_locked(slot)
            _SLOT.pack_into(self._buf, self._slot_off(slot), 0, 0.0, 0,
                            0, 0)

    def live_slots(self, lease_timeout_s: float = 2.0) -> list:
        now = time.time()
        with self._locked():
            out = []
            for s in range(self.nslots):
                pid, lease = _SLOT.unpack_from(
                    self._buf, self._slot_off(s))[:2]
                if pid and now - lease <= lease_timeout_s:
                    out.append(s)
            return out

    def set_direct_port(self, slot: int, port: int):
        """Publish a worker's DIRECT (per-process) wire port — the
        diagnostics door cluster memtables fan out to.  Zeroed whenever
        the slot's lease drops (release/reclaim/re-claim): a dead
        worker's port must read as absent, never as a connectable peer."""
        with self._locked():
            self._slot_off(slot)  # range check
            _U64.pack_into(self._buf, self._o_ports + 8 * slot, int(port))

    def direct_ports(self, lease_timeout_s: float = 2.0) -> dict:
        """{slot: direct_port} for every LIVE slot that has published
        one (a worker between claim and publish is simply absent)."""
        now = time.time()
        with self._locked():
            out = {}
            for s in range(self.nslots):
                pid, lease = _SLOT.unpack_from(
                    self._buf, self._slot_off(s))[:2]
                if not pid or now - lease > lease_timeout_s:
                    continue
                port = _U64.unpack_from(self._buf,
                                        self._o_ports + 8 * s)[0]
                if port:
                    out[s] = port
            return out

    def reclaim_expired(self, lease_timeout_s: float = 2.0) -> int:
        """Reclaim every slot whose lease lapsed: zero its running/HBM
        columns (no orphaned WFQ weight or tenant running-cap leak), free
        its building dedup slots, drop the lease.  Any process may call
        this — the parent does on child death, workers do periodically."""
        now = time.time()
        n = 0
        with self._locked():
            for s in range(self.nslots):
                off = self._slot_off(s)
                pid, lease = _SLOT.unpack_from(self._buf, off)[:2]
                if pid and now - lease > lease_timeout_s:
                    self._zero_slot_columns_locked(s)
                    self._drop_slot_published_locked(s)
                    _SLOT.pack_into(self._buf, off, 0, 0.0, 0, 0, 0)
                    self._bump_locked("fabric_lease_reclaims")
                    n += 1
        return n

    def _zero_slot_columns_locked(self, slot: int):
        for t in range(self.ntenants):
            base = self._o_tenants + t * self._ten_sz
            name = bytes(self._buf[base:base + _NAME_SZ]).rstrip(b"\0")
            if not name:
                continue
            _U32.pack_into(self._buf, base + _TEN_FIXED.size + 4 * slot, 0)
            _U64.pack_into(self._buf, base + _TEN_FIXED.size
                           + 4 * self.nslots + 8 * slot, 0)
        for d in range(self.ndedup):
            off = self._o_dedup + d * _DED.size
            h, state, owner, ts, rid, vv = _DED.unpack_from(self._buf, off)
            if state == DBUILDING and owner == slot:
                _DED.pack_into(self._buf, off, h, DFAILED, owner, ts,
                               rid, vv)
        # free the dead slot's shared 2PC lock claims: the DATA locks
        # (the replicas' prewrite locks) are resolved by WAL recovery via
        # their primary; the claim entries only serialize live prewrites
        for i in range(self.nlocks):
            off = self._o_locks + i * _LCK.size
            h, start_ts, owner, _ts = _LCK.unpack_from(self._buf, off)
            if start_ts and owner == slot:
                _LCK.pack_into(self._buf, off, b"\0" * 16, 0, 0, 0.0)

    def _drop_slot_published_locked(self, slot: int):
        """Zero the slot's published cells on any lease transition
        (claim/release/reclaim): the direct port (a dead worker must not
        read as a connectable peer), the commit frontier (a dead origin
        must stop gating fleet reads — its durable records are already
        behind the committed WAL length), and its DDL ownership (the
        epoch stays: monotonic for the cell's lifetime, so a reclaimed
        owner's in-flight job fails the epoch fence at commit)."""
        _U64.pack_into(self._buf, self._o_ports + 8 * slot, 0)
        _FRONT.pack_into(self._buf, self._o_front + slot * _FRONT.size,
                         0, 0)
        epoch, owner_p1, _lease = _DDL.unpack_from(self._buf, self._o_ddl)
        if owner_p1 == slot + 1:
            _DDL.pack_into(self._buf, self._o_ddl, epoch, 0, 0.0)

    # -- tenants -------------------------------------------------------------

    def _ten_name(self, t: int) -> bytes:
        base = self._o_tenants + t * self._ten_sz
        return bytes(self._buf[base:base + _NAME_SZ]).rstrip(b"\0")

    def _tenant_idx_locked(self, group: str, alloc: bool) -> int:
        key = group.encode("utf-8")[:_NAME_SZ - 1]
        free = -1
        for t in range(self.ntenants):
            name = self._ten_name(t)
            if name == key:
                return t
            if not name and free < 0:
                free = t
        if not alloc:
            return -1
        if free < 0:
            return -1  # table full: callers fall back to local-only state
        base = self._o_tenants + free * self._ten_sz
        _TEN_FIXED.pack_into(self._buf, base, key, 0.0, 0, 0)
        return free

    def _run_off(self, t: int, slot: int) -> int:
        return (self._o_tenants + t * self._ten_sz + _TEN_FIXED.size
                + 4 * slot)

    def _hbm_off(self, t: int, slot: int) -> int:
        return (self._o_tenants + t * self._ten_sz + _TEN_FIXED.size
                + 4 * self.nslots + 8 * slot)

    def _running_total_locked(self, t: int) -> int:
        return sum(_U32.unpack_from(self._buf, self._run_off(t, s))[0]
                   for s in range(self.nslots))

    # admission: fleet-wide per-tenant running caps --------------------------

    def try_acquire_running(self, slot: int, group: str,
                            cap: int) -> bool:
        """Atomically check the FLEET-wide running count of `group`
        against `cap` and charge one fragment to `slot` when under it.
        cap <= 0 means unlimited (still counted, for gauges)."""
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=True)
            if t < 0:
                return True  # tenant table full: degrade to local caps
            total = self._running_total_locked(t)
            if cap > 0 and total >= cap:
                return False
            off = self._run_off(t, slot)
            _U32.pack_into(self._buf, off,
                           _U32.unpack_from(self._buf, off)[0] + 1)
            base = self._o_tenants + t * self._ten_sz
            _n, vt, peak, pad = _TEN_FIXED.unpack_from(self._buf, base)
            if total + 1 > peak:
                _TEN_FIXED.pack_into(self._buf, base, _n, vt, total + 1,
                                     pad)
            return True

    def release_running(self, slot: int, group: str):
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=False)
            if t < 0:
                return
            off = self._run_off(t, slot)
            cur = _U32.unpack_from(self._buf, off)[0]
            if cur > 0:
                _U32.pack_into(self._buf, off, cur - 1)

    def running_total(self, group: str) -> int:
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=False)
            return self._running_total_locked(t) if t >= 0 else 0

    def peak_running(self, group: str) -> int:
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=False)
            if t < 0:
                return 0
            base = self._o_tenants + t * self._ten_sz
            return _TEN_FIXED.unpack_from(self._buf, base)[2]

    # WFQ virtual clocks ------------------------------------------------------

    def vtimes(self, groups) -> dict:
        """The fleet virtual clocks for `groups` (0.0 for unknown)."""
        with self._locked():
            out = {}
            for g in groups:
                t = self._tenant_idx_locked(g, alloc=False)
                if t < 0:
                    out[g] = 0.0
                else:
                    base = self._o_tenants + t * self._ten_sz
                    out[g] = _TEN_FIXED.unpack_from(self._buf, base)[1]
            return out

    def vtime_advance(self, group: str, delta: float,
                      floor: float = 0.0) -> float:
        """One WFQ grant: the tenant's fleet clock advances by `delta`
        (1/weight) from max(current, floor) — the same floor re-entry
        rule as the in-process scheduler, but against the clock every
        process shares, so a tenant flooding process A is charged the
        virtual time its grants on A consumed when it next competes on
        process B."""
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=True)
            if t < 0:
                return 0.0
            base = self._o_tenants + t * self._ten_sz
            name, vt, peak, pad = _TEN_FIXED.unpack_from(self._buf, base)
            vt = max(vt, floor) + delta
            _TEN_FIXED.pack_into(self._buf, base, name, vt, peak, pad)
            return vt

    # per-tenant HBM charges --------------------------------------------------

    def charge_hbm(self, slot: int, group: str, delta: int):
        """Publish a residency-ledger delta for (slot, group) — the
        fleet-visible mirror of the in-process per-group byte counts."""
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=True)
            if t < 0:
                return
            off = self._hbm_off(t, slot)
            cur = _U64.unpack_from(self._buf, off)[0]
            _U64.pack_into(self._buf, off, max(cur + delta, 0))

    def hbm_remote_bytes(self, group: str, exclude_slot: int) -> int:
        """Bytes `group` holds resident in OTHER workers' ledgers."""
        with self._locked():
            t = self._tenant_idx_locked(group, alloc=False)
            if t < 0:
                return 0
            return sum(
                _U64.unpack_from(self._buf, self._hbm_off(t, s))[0]
                for s in range(self.nslots) if s != exclude_slot)

    # -- durable shared store (kv/wal.py + kv/shared_store.py) ----------------

    def tso_lease(self, n: int, floor: int = 0) -> tuple:
        """Allocate a batch of ``n`` fleet-monotonic timestamps: returns
        ``(base, base + n]`` — the caller hands them out locally without
        touching the segment again.  ``floor`` keeps the counter
        wall-clock anchored (the hybrid physical part), so GC's
        now-based safepoint arithmetic stays meaningful."""
        with self._locked():
            off = self._ctr_off("_tso")
            base = max(_U64.unpack_from(self._buf, off)[0], floor)
            _U64.pack_into(self._buf, off, base + n)
            return (base, base + n)

    def publish_schema_version(self, version: int) -> int:
        """Forward-only schema-version cell (the fleet schema lease):
        a DDL commit publishes here; workers whose local infoschema lags
        reload before serving, and a commit planned against an older
        version fails retriably (ErrInfoSchemaChanged)."""
        with self._locked():
            off = self._ctr_off("_schema_ver")
            cur = _U64.unpack_from(self._buf, off)[0]
            if version > cur:
                _U64.pack_into(self._buf, off, version)
                return version
            return cur

    def schema_version(self) -> int:
        with self._locked():
            return _U64.unpack_from(
                self._buf, self._ctr_off("_schema_ver"))[0]

    def wal_len(self) -> int:
        with self._locked():
            return _U64.unpack_from(self._buf, self._ctr_off("_wal_len"))[0]

    def set_wal_len(self, n: int):
        with self._locked():
            _U64.pack_into(self._buf, self._ctr_off("_wal_len"), n)

    def set_min_read_ts(self, slot: int, ts: int):
        """Publish this worker's oldest live snapshot ts (0 = none): the
        fleet GC safepoint floors at the minimum over live slots, so GC
        on any worker can never drop a version a sibling still reads."""
        with self._locked():
            off = self._slot_off(slot)
            pid, lease, gen, _mrt, wa = _SLOT.unpack_from(self._buf, off)
            if pid:
                _SLOT.pack_into(self._buf, off, pid, lease, gen,
                                max(int(ts), 0), wa)

    def fleet_min_read_ts(self, lease_timeout_s: float = 2.0) -> int:
        """min over LIVE slots' nonzero min-read-ts columns (0 = no
        reader pins the floor anywhere in the fleet)."""
        now = time.time()
        with self._locked():
            best = 0
            for s in range(self.nslots):
                pid, lease, _g, mrt, _wa = _SLOT.unpack_from(
                    self._buf, self._slot_off(s))
                if pid and now - lease <= lease_timeout_s and mrt:
                    best = mrt if not best else min(best, mrt)
            return best

    def set_wal_applied(self, slot: int, lsn: int):
        with self._locked():
            off = self._slot_off(slot)
            pid, lease, gen, mrt, _wa = _SLOT.unpack_from(self._buf, off)
            if pid:
                _SLOT.pack_into(self._buf, off, pid, lease, gen, mrt,
                                int(lsn))

    def min_wal_applied(self) -> "int | None":
        """The truncation floor: the smallest applied-LSN over every
        CLAIMED slot (pid stamped), or None when none is claimed.  A
        stalled-but-alive worker (lease momentarily old — a GIL-holding
        compile) still holds its slot, and truncating past its applied
        frontier would leave it permanently missing the records only
        the checkpoint now holds; a genuinely dead worker's slot is
        reclaimed (pid zeroed) and stops gating truncation then."""
        with self._locked():
            vals = []
            for s in range(self.nslots):
                pid, _lease, _g, _mrt, wa = _SLOT.unpack_from(
                    self._buf, self._slot_off(s))
                if pid:
                    vals.append(wa)
            return min(vals) if vals else None

    # the shared 2PC lock/primary table ---------------------------------------

    def _lck_off(self, i: int) -> int:
        return self._o_locks + i * _LCK.size

    def lock_claim(self, hashes, start_ts: int, slot: int) -> tuple:
        """All-or-nothing claim of key-hash entries for ``start_ts``.
        Returns ``(0, -1)`` on success, ``(holder_start_ts, idx)`` on a
        conflict with a foreign claim (idx = position in ``hashes``; the
        caller raises LockedError and walks the normal lock-wait
        ladder), or ``(-1, -1)`` when the table is too full to claim
        (the caller degrades to local-only conflict detection, the same
        graceful shape as a full tenant table)."""
        want = list(hashes)
        if not self.nlocks:
            return (-1, -1)
        with self._locked():
            by_hash = {}
            free = []
            for i in range(self.nlocks):
                off = self._lck_off(i)
                h, sts, owner, _ts = _LCK.unpack_from(self._buf, off)
                if sts:
                    by_hash[h] = sts
                else:
                    free.append(i)
            need = []
            for idx, h in enumerate(want):
                held = by_hash.get(h)
                if held is not None:
                    if held != start_ts:
                        return (held, idx)  # conflict: foreign claim
                    continue                # ours already (pessimistic)
                need.append(h)
            if len(need) > len(free):
                return (-1, -1)
            now = time.time()
            for h, i in zip(need, free):
                _LCK.pack_into(self._buf, self._lck_off(i), h,
                               start_ts, slot, now)
            return (0, -1)

    def lock_release(self, start_ts: int, hashes=None):
        """Free entries claimed by ``start_ts`` — all of them
        (commit/rollback), or only ``hashes`` (a failed claim batch of a
        txn that still holds earlier pessimistic claims)."""
        only = None if hashes is None else set(hashes)
        with self._locked():
            for i in range(self.nlocks):
                off = self._lck_off(i)
                h, sts, _owner, _ts = _LCK.unpack_from(self._buf, off)
                if sts == start_ts and (only is None or h in only):
                    _LCK.pack_into(self._buf, off, b"\0" * 16, 0, 0, 0.0)

    # -- per-origin committed frontiers (kv/shared_store.py reads) ------------

    def set_commit_frontier(self, slot: int, ts: int, lsn: int):
        """Publish this slot's durable commit frontier: the max commit_ts
        its appender has fsync-acked and the log length covering it.
        Forward-only and pid-guarded — a reclaimed slot's late publish
        (a zombie appender's final fsync) must not resurrect a gate the
        reclaim already dropped."""
        with self._locked():
            off = self._slot_off(slot)
            if not _SLOT.unpack_from(self._buf, off)[0]:
                return
            foff = self._o_front + slot * _FRONT.size
            cur_ts, cur_lsn = _FRONT.unpack_from(self._buf, foff)
            _FRONT.pack_into(self._buf, foff, max(int(ts), cur_ts),
                             max(int(lsn), cur_lsn))

    def commit_frontiers(self, lease_timeout_s: float = 2.0) -> dict:
        """{slot: (frontier_ts, frontier_lsn)} over LIVE slots with a
        published frontier.  A dead/reclaimed slot is absent — the
        dead-slot ungating rule: its durable records sit behind the
        committed WAL length, so the plain catch-up already covers them
        and no reader should block on a lease that cannot renew."""
        now = time.time()
        with self._locked():
            out = {}
            for s in range(self.nslots):
                pid, lease = _SLOT.unpack_from(
                    self._buf, self._slot_off(s))[:2]
                if not pid or now - lease > lease_timeout_s:
                    continue
                ts, lsn = _FRONT.unpack_from(
                    self._buf, self._o_front + s * _FRONT.size)
                if ts:
                    out[s] = (ts, lsn)
            return out

    # -- the fleet DDL owner lease (ddl.py _run_job) --------------------------

    def ddl_claim(self, slot: int, lease_timeout_s: float = 2.0) -> int:
        """Claim the single DDL owner cell for ``slot``: succeeds when
        unowned, already ours, or the owner's lease lapsed (failover —
        an owner SIGKILLed mid-CREATE).  Bumps and returns the epoch
        (the fence a deposed owner's commit fails); returns 0 while a
        foreign owner's lease is live (the caller backs off and
        retries)."""
        now = time.time()
        with self._locked():
            epoch, owner_p1, lease = _DDL.unpack_from(
                self._buf, self._o_ddl)
            if owner_p1 and owner_p1 != slot + 1 \
                    and now - lease <= lease_timeout_s:
                return 0
            epoch += 1
            _DDL.pack_into(self._buf, self._o_ddl, epoch, slot + 1, now)
            return epoch

    def ddl_heartbeat(self, slot: int, epoch: int) -> bool:
        """Refresh the DDL lease; False when ``slot`` no longer owns the
        cell at ``epoch`` (it failed over — abandon the job)."""
        with self._locked():
            cur_epoch, owner_p1, _lease = _DDL.unpack_from(
                self._buf, self._o_ddl)
            if owner_p1 != slot + 1 or cur_epoch != epoch:
                return False
            _DDL.pack_into(self._buf, self._o_ddl, cur_epoch, owner_p1,
                           time.time())
            return True

    def ddl_release(self, slot: int):
        """Clean handoff after a job: drop ownership, keep the epoch."""
        with self._locked():
            epoch, owner_p1, _lease = _DDL.unpack_from(
                self._buf, self._o_ddl)
            if owner_p1 == slot + 1:
                _DDL.pack_into(self._buf, self._o_ddl, epoch, 0, 0.0)

    def ddl_check(self, epoch: int) -> bool:
        """Is ``epoch`` still the DDL cell's current epoch?  The fence a
        deposed owner fails immediately before committing its job."""
        with self._locked():
            return _DDL.unpack_from(self._buf, self._o_ddl)[0] == epoch

    # -- region ownership / epoch fencing (fabric/region.py) ------------------

    def _reg_off(self, rid: int) -> int:
        if not 0 <= rid < self.nregions:
            raise IndexError(
                f"region {rid} out of range 0..{self.nregions - 1}")
        return self._o_regions + rid * _REG.size

    def region_claim(self, rid: int, slot: int,
                     lease_timeout_s: float = 2.0) -> int:
        """Claim region ``rid`` for ``slot``: succeeds when the region is
        unowned, already ours, or the current owner's lease has lapsed
        (the failover case).  Every successful claim BUMPS THE EPOCH and
        returns it — the fencing token the owner must present on every
        committed-length write.  Returns 0 while a foreign owner's lease
        is still live (the claimant backs off and re-scans)."""
        now = time.time()
        with self._locked():
            off = self._reg_off(rid)
            epoch, owner_p1, lease, clen, alsn = _REG.unpack_from(
                self._buf, off)
            if owner_p1 and owner_p1 != slot + 1 \
                    and now - lease <= lease_timeout_s:
                return 0
            epoch += 1
            _REG.pack_into(self._buf, off, epoch, slot + 1, now, clen,
                           alsn)
            return epoch

    def region_heartbeat(self, rid: int, slot: int, epoch: int) -> bool:
        """Refresh the region lease; False when the caller no longer owns
        the region at this epoch (it failed over — stop serving it)."""
        with self._locked():
            off = self._reg_off(rid)
            cur_epoch, owner_p1, _lease, clen, alsn = _REG.unpack_from(
                self._buf, off)
            if owner_p1 != slot + 1 or cur_epoch != epoch:
                return False
            _REG.pack_into(self._buf, off, cur_epoch, owner_p1,
                           time.time(), clen, alsn)
            return True

    def region_release(self, rid: int, slot: int):
        """Clean handoff: drop ownership (the epoch stays — it is
        monotonic for the region's lifetime, never reused)."""
        with self._locked():
            off = self._reg_off(rid)
            epoch, owner_p1, _lease, clen, alsn = _REG.unpack_from(
                self._buf, off)
            if owner_p1 == slot + 1:
                _REG.pack_into(self._buf, off, epoch, 0, 0.0, clen, alsn)

    def region_release_all(self, slot: int):
        for rid in range(self.nregions):
            self.region_release(rid, slot)

    def region_check(self, rid: int, epoch: int) -> bool:
        """Is ``epoch`` still the region's current epoch?  The fence a
        zombie appender fails after a failover bumped past it."""
        with self._locked():
            return _REG.unpack_from(
                self._buf, self._reg_off(rid))[0] == epoch

    def region_set_committed(self, rid: int, epoch: int, n: int) -> bool:
        """Epoch-fenced committed-length publish (the per-region
        ``set_wal_len``): False — and NO write — when ``epoch`` is stale,
        so a failed-over region's old owner cannot move the fence."""
        with self._locked():
            off = self._reg_off(rid)
            cur_epoch, owner_p1, lease, _clen, alsn = _REG.unpack_from(
                self._buf, off)
            if cur_epoch != epoch:
                return False
            _REG.pack_into(self._buf, off, cur_epoch, owner_p1, lease,
                           int(n), alsn)
            return True

    def region_committed_len(self, rid: int) -> int:
        with self._locked():
            return _REG.unpack_from(self._buf, self._reg_off(rid))[3]

    def region_set_applied(self, rid: int, epoch: int, lsn: int) -> bool:
        with self._locked():
            off = self._reg_off(rid)
            cur_epoch, owner_p1, lease, clen, _alsn = _REG.unpack_from(
                self._buf, off)
            if cur_epoch != epoch:
                return False
            _REG.pack_into(self._buf, off, cur_epoch, owner_p1, lease,
                           clen, int(lsn))
            return True

    def region_info(self, rid: int) -> dict:
        with self._locked():
            epoch, owner_p1, lease, clen, alsn = _REG.unpack_from(
                self._buf, self._reg_off(rid))
        return {"region": rid, "epoch": epoch, "owner": owner_p1 - 1,
                "lease_age_s": (round(time.time() - lease, 3)
                                if owner_p1 else None),
                "committed_len": clen, "applied_lsn": alsn}

    def regions_expired(self, lease_timeout_s: float = 2.0) -> list:
        """Owned regions whose lease lapsed — the failover work list."""
        now = time.time()
        with self._locked():
            out = []
            for rid in range(self.nregions):
                _e, owner_p1, lease, _c, _a = _REG.unpack_from(
                    self._buf, self._reg_off(rid))
                if owner_p1 and now - lease > lease_timeout_s:
                    out.append(rid)
            return out

    def region_owners(self) -> dict:
        """{rid: owner slot} over currently owned regions."""
        with self._locked():
            out = {}
            for rid in range(self.nregions):
                owner_p1 = _REG.unpack_from(
                    self._buf, self._reg_off(rid))[1]
                if owner_p1:
                    out[rid] = owner_p1 - 1
            return out

    # -- per-table fleet versions (the result cache's invalidation feed) -----

    def _tver_off(self, i: int) -> int:
        return self._o_tvers + i * _TVER.size

    def table_version_advance(self, pairs) -> None:
        """Advance table-version cells forward-only: for each
        ``(table_id, version_ts)`` the cell becomes ``max(cell, ts)``.
        Idempotent — the committing worker publishes at commit and every
        tailer re-publishes as it applies the log, so a down-window on
        any single worker is repaired by the next.  A full section drops
        the advance: the table simply has no fleet version (callers see
        it as cache-ineligible, never as stale)."""
        if not self.ntablevers:
            return
        with self._locked():
            for tid, ts in pairs:
                tid, ts = int(tid), int(ts)
                if tid <= 0 or ts <= 0:
                    continue
                free = -1
                for i in range(self.ntablevers):
                    off = self._tver_off(i)
                    cell_tid, cell_ts = _TVER.unpack_from(self._buf, off)
                    if cell_tid == tid:
                        if ts > cell_ts:
                            _TVER.pack_into(self._buf, off, tid, ts)
                        break
                    if not cell_tid and free < 0:
                        free = i
                else:
                    if free >= 0:
                        _TVER.pack_into(self._buf, self._tver_off(free),
                                        tid, ts)

    def table_versions(self, tids) -> dict:
        """{table_id: version_ts} for every requested table that has a
        cell (missing tables are absent — cache-ineligible)."""
        if not self.ntablevers:
            return {}
        want = {int(t) for t in tids}
        out = {}
        with self._locked():
            for i in range(self.ntablevers):
                cell_tid, cell_ts = _TVER.unpack_from(
                    self._buf, self._tver_off(i))
                if cell_tid in want:
                    out[cell_tid] = cell_ts
                    if len(out) == len(want):
                        break
        return out

    # -- fragment dedup -------------------------------------------------------

    def _ded_off(self, i: int) -> int:
        return self._o_dedup + i * _DED.size

    #: a versioned (vv_hash != 0) DDONE entry is evictable for slot reuse
    #: only after this long — a plain in-flight claimant's short ttl must
    #: not evict a live cache page (invalidation, not time, retires it)
    VERSIONED_EVICT_S = 120.0

    def dedup_claim(self, key_hash: bytes, ttl_s: float,
                    vv_hash: int = 0, check_vv: bool = True,
                    owner: "int | None" = None) -> tuple:
        """Claim or join the result-cache slot for `key_hash` (16 bytes).

        ``vv_hash`` is the claimant's version-vector hash (0 = plain
        in-flight dedup, no version stamping).  Returns one of::

            ("lead", idx, 0)            # this process computes + publishes
            ("lead_delta", idx, rid)    # version advanced: old page `rid`
                                        # is kept for a delta fold
            ("hit",  idx, result_id)    # a matching result page exists
            ("wait", idx, 0)            # another process is building: poll
            ("miss", -1, 0)             # table full — just compute locally

        A versioned entry hits only when its stored vv_hash equals the
        claimant's (``check_vv=False`` — the cache-stale-read failpoint —
        skips that check; the page-level verify downstream must catch it).
        """
        now = time.time()
        # net workers pass their slot explicitly: the server-side
        # Coordinator instance is shared by every TCP client, so
        # set_claim_owner's instance attribute cannot carry their identity
        who = self._claim_owner if owner is None else int(owner)
        with self._locked():
            free = -1
            for i in range(self.ndedup):
                off = self._ded_off(i)
                h, state, owner, ts, rid, vv = _DED.unpack_from(
                    self._buf, off)
                if h == key_hash and state != DFREE:
                    if state == DBUILDING:
                        if now - ts <= BUILD_LEASE_S:
                            return ("wait", i, 0)
                        # leader died mid-build: take the slot over (a
                        # kept old page rides along for the delta fold)
                        _DED.pack_into(self._buf, off, key_hash, DBUILDING,
                                       who, now, rid, vv)
                        self._bump_locked("fabric_dedup_leads")
                        if rid and vv and vv_hash:
                            return ("lead_delta", i, rid)
                        return ("lead", i, 0)
                    eff_ttl = self.VERSIONED_EVICT_S if vv else ttl_s
                    if state == DDONE and now - ts <= eff_ttl:
                        if vv and check_vv and vv != vv_hash:
                            # version advanced under the page: invalidate,
                            # but KEEP the page — the new leader folds the
                            # delta since the cached version through it
                            self._bump_locked("fabric_cache_invalidations")
                            self._bump_locked("fabric_dedup_leads")
                            _DED.pack_into(self._buf, off, key_hash,
                                           DBUILDING, who,
                                           now, rid, vv)
                            return ("lead_delta", i, rid)
                        self._bump_locked("fabric_dedup_hits")
                        if vv:
                            self._bump_locked("fabric_cache_hits")
                        return ("hit", i, rid)
                    # stale done / failed: re-lead (and GC the expired
                    # page — nothing can serve it again, and pages left
                    # behind are unbounded disk growth)
                    self._unlink_page(rid)
                    _DED.pack_into(self._buf, off, key_hash, DBUILDING,
                                   who, now, 0, 0)
                    self._bump_locked("fabric_dedup_leads")
                    return ("lead", i, 0)
                if free < 0 and (state == DFREE
                                 or (state == DDONE and now - ts
                                     > (self.VERSIONED_EVICT_S if vv
                                        else ttl_s))
                                 or state == DFAILED):
                    free = i
            if free < 0:
                return ("miss", -1, 0)
            off = self._ded_off(free)
            old_rid = _DED.unpack_from(self._buf, off)[4]
            self._unlink_page(old_rid)  # the reused slot's expired page
            _DED.pack_into(self._buf, off, key_hash,
                           DBUILDING, who, now, 0, 0)
            self._bump_locked("fabric_dedup_leads")
            return ("lead", free, 0)

    #: the owner id stamped on dedup claims.  Workers set their real
    #: slot via set_claim_owner (state.activate); any attachment that
    #: never does — the parent, a bench verifier, tests — claims as
    #: EXTERNAL_OWNER, a sentinel that matches no worker slot: a real
    #: slot's crash reclaim must never fail an external claimant's
    #: in-progress entry, and vice versa (an abandoned external claim is
    #: recovered by the BUILD_LEASE_S takeover, not by slot reclaim)
    EXTERNAL_OWNER = 0xFFFFFFFF
    _claim_owner = EXTERNAL_OWNER

    def set_claim_owner(self, slot: int):
        self._claim_owner = int(slot)

    def dedup_publish(self, idx: int, key_hash: bytes,
                      result_id: int, vv_hash: int = 0) -> None:
        with self._locked():
            off = self._ded_off(idx)
            h, state, owner, _ts, old_rid, _vv = _DED.unpack_from(
                self._buf, off)
            if h == key_hash and state == DBUILDING:
                if old_rid and old_rid != result_id:
                    # the delta fold's source page: superseded now
                    self._unlink_page(old_rid)
                _DED.pack_into(self._buf, off, h, DDONE, owner,
                               time.time(), result_id, vv_hash)

    def dedup_fail(self, idx: int, key_hash: bytes) -> None:
        with self._locked():
            off = self._ded_off(idx)
            h, state, owner, ts, rid, vv = _DED.unpack_from(self._buf, off)
            if h == key_hash and state == DBUILDING:
                _DED.pack_into(self._buf, off, h, DFAILED, owner, ts,
                               rid, vv)

    def dedup_poll(self, idx: int, key_hash: bytes) -> tuple:
        """-> ("building"|"done"|"gone", result_id)."""
        with self._locked():
            h, state, owner, ts, rid, _vv = _DED.unpack_from(
                self._buf, self._ded_off(idx))
            if h != key_hash or state in (DFREE, DFAILED):
                return ("gone", 0)
            if state == DDONE:
                return ("done", rid)
            if time.time() - ts > BUILD_LEASE_S:
                return ("gone", 0)  # leader presumed dead
            return ("building", 0)

    def next_result_id(self) -> int:
        with self._locked():
            return self._bump_locked("_result_id_seq")

    def result_page_path(self, result_id: int) -> str:
        return os.path.join(self.pages_dir, f"dedup-{result_id}.bin")

    def _unlink_page(self, result_id: int):
        if result_id:
            with contextlib.suppress(OSError):
                os.remove(self.result_page_path(result_id))

    def prewarm_claim(self, key_hash: bytes, ttl_s: float = 60.0) -> bool:
        """Fleet-wide prewarm dedup: True when THIS process should warm
        the signature, False when another worker claimed it within the
        window (counted ``fabric_prewarm_dedup``)."""
        kind, idx, _rid = self.dedup_claim(key_hash, ttl_s)
        if kind == "lead":
            # mark done immediately: the claim itself is the dedup —
            # prewarm needs no result page, only at-most-once submission
            self.dedup_publish(idx, key_hash, 0)
            with self._locked():
                # a claim is not a dedup LEAD in the gauge sense
                self._bump_locked("fabric_dedup_leads", -1)
            return True
        if kind in ("hit", "wait"):
            with self._locked():
                if kind == "hit":
                    self._bump_locked("fabric_dedup_hits", -1)
                self._bump_locked("fabric_prewarm_dedup")
            return False
        return True  # table full: warm locally rather than skip

    # -- fragment performance store (ISSUE 18, observe-only) ------------------

    def _perf_off(self, i: int) -> int:
        return self._o_perf + i * _PERF.size

    def perf_merge(self, rows) -> int:
        """Merge worker-local span-duration accumulators into the fleet
        store.  ``rows`` is a list of
        ``(sig_hash, bucket, backend, kind, count, sum_s, max_s, sketch)``
        deltas (sketch: PERF_SKETCH_N ints).  Linear probe by the 4-part
        key; a full table drops the row (counted fabric_perf_dropped).
        Returns rows merged.  Merge-only commutative math — there is no
        per-slot state here to crash-reclaim (see _PERF)."""
        if not self.nperf:
            return 0
        merged = 0
        with self._locked():
            for sig, bucket, backend, kind, cnt, s, mx, sketch in rows:
                if cnt <= 0:
                    continue
                key = (int(sig) & (2**64 - 1), int(bucket),
                       int(backend), int(kind))
                free = -1
                for i in range(self.nperf):
                    off = self._perf_off(i)
                    row = _PERF.unpack_from(self._buf, off)
                    if row[4] == 0:  # free (count == 0)
                        if free < 0:
                            free = i
                        continue
                    if row[:4] == key:
                        new_sketch = [a + b for a, b in
                                      zip(row[7:], sketch)]
                        _PERF.pack_into(
                            self._buf, off, *key, row[4] + int(cnt),
                            row[5] + float(s), max(row[6], float(mx)),
                            *new_sketch)
                        merged += 1
                        break
                else:
                    if free >= 0:
                        _PERF.pack_into(
                            self._buf, self._perf_off(free), *key,
                            int(cnt), float(s), float(mx),
                            *[int(x) for x in sketch])
                        merged += 1
                    else:
                        self._bump_locked("fabric_perf_dropped", int(cnt))
        return merged

    def perf_rows(self) -> list:
        """Every live perf row as a dict — the
        information_schema.tidb_fragment_perf / /status feed."""
        out = []
        with self._locked():
            for i in range(self.nperf):
                row = _PERF.unpack_from(self._buf, self._perf_off(i))
                if row[4] == 0:
                    continue
                out.append({"sig_hash": row[0], "bucket": row[1],
                            "backend": row[2], "kind": row[3],
                            "count": row[4], "sum_s": row[5],
                            "max_s": row[6], "sketch": list(row[7:])})
        return out

    def perf_lookup(self, sig_hash: int, bucket: int) -> list:
        """The perf rows for one (fragment sig, bucket) — what EXPLAIN
        ANALYZE renders as the fleet line."""
        want = (int(sig_hash) & (2**64 - 1), int(bucket))
        out = []
        with self._locked():
            for i in range(self.nperf):
                row = _PERF.unpack_from(self._buf, self._perf_off(i))
                if row[4] and row[0] == want[0] and row[1] == want[1]:
                    out.append({"backend": row[2], "kind": row[3],
                                "count": row[4], "sum_s": row[5],
                                "max_s": row[6], "sketch": list(row[7:])})
        return out

    # -- introspection / drain ------------------------------------------------

    def snapshot(self) -> dict:
        now = time.time()
        with self._locked():
            slots = []
            for s in range(self.nslots):
                pid, lease, gen, mrt, wa = _SLOT.unpack_from(
                    self._buf, self._slot_off(s))
                if pid:
                    fts, flsn = _FRONT.unpack_from(
                        self._buf, self._o_front + s * _FRONT.size)
                    slots.append({"slot": s, "pid": pid, "gen": gen,
                                  "lease_age_s": round(now - lease, 3),
                                  "min_read_ts": mrt, "wal_applied": wa,
                                  "frontier_ts": fts,
                                  "frontier_lsn": flsn})
            tenants = {}
            for t in range(self.ntenants):
                name = self._ten_name(t)
                if not name:
                    continue
                base = self._o_tenants + t * self._ten_sz
                _n, vt, peak, _pad = _TEN_FIXED.unpack_from(self._buf, base)
                tenants[name.decode("utf-8", "replace")] = {
                    "running": self._running_total_locked(t),
                    "peak_running": peak,
                    "vtime": round(vt, 4),
                    "hbm_bytes": sum(
                        _U64.unpack_from(self._buf, self._hbm_off(t, s))[0]
                        for s in range(self.nslots))}
            building = sum(
                1 for i in range(self.ndedup)
                if _DED.unpack_from(self._buf, self._ded_off(i))[1]
                == DBUILDING)
            held_locks = sum(
                1 for i in range(self.nlocks)
                if _LCK.unpack_from(self._buf,
                                    self._o_locks + i * _LCK.size)[1])
            ctrs = {name: _U64.unpack_from(
                self._buf, self._ctr_off(name))[0]
                for name in COUNTER_NAMES if not name.startswith("_")}
            ctrs["schema_version"] = _U64.unpack_from(
                self._buf, self._ctr_off("_schema_ver"))[0]
            regions = []
            for rid in range(self.nregions):
                epoch, owner_p1, _lease, clen, alsn = _REG.unpack_from(
                    self._buf, self._reg_off(rid))
                regions.append({"region": rid, "epoch": epoch,
                                "owner": owner_p1 - 1,
                                "committed_len": clen,
                                "applied_lsn": alsn})
            perf_rows_used = perf_samples = 0
            for i in range(self.nperf):
                row = _PERF.unpack_from(self._buf, self._perf_off(i))
                if row[4]:
                    perf_rows_used += 1
                    perf_samples += row[4]
            ddl_epoch, ddl_owner_p1, _dl = _DDL.unpack_from(
                self._buf, self._o_ddl)
        return {"slots": slots, "tenants": tenants,
                "dedup_building": building, "held_locks": held_locks,
                "regions": regions, "perf_rows_used": perf_rows_used,
                "perf_samples": perf_samples,
                "ddl_epoch": ddl_epoch, "ddl_owner": ddl_owner_p1 - 1,
                **ctrs}

    def verify_drained(self) -> dict:
        """Fleet drain invariant (the cross-process analog of
        scheduler.verify_drained): no live lease, zero running counts in
        every tenant row, no dedup slot stuck building, no shared 2PC
        lock claim held, every slot's min-read-ts column zeroed (an
        exited worker must not pin the fleet GC floor forever), and NO
        ORPHANED REGION LEASE — every region a worker owned was released
        on drain or failed over and then released; an owner entry at
        drain is a region no survivor can claim without waiting out a
        dead lease."""
        snap = self.snapshot()
        running = {g: t["running"] for g, t in snap["tenants"].items()
                   if t["running"]}
        pinned = [s["slot"] for s in snap["slots"] if s["min_read_ts"]]
        region_leases = [r["region"] for r in snap["regions"]
                         if r["owner"] >= 0]
        # the DDL cell must be unowned at drain: a held lease here is a
        # dead owner no survivor can claim without waiting out its lease
        ddl_owner = snap["ddl_owner"]
        return {"ok": not snap["slots"] and not running
                and snap["dedup_building"] == 0
                and snap["held_locks"] == 0 and not pinned
                and not region_leases and ddl_owner < 0,
                "live_slots": [s["slot"] for s in snap["slots"]],
                "running": running,
                "dedup_building": snap["dedup_building"],
                "held_locks": snap["held_locks"],
                "min_read_pinned": pinned,
                "region_leases": region_leases,
                "ddl_owner": ddl_owner,
                "lease_reclaims": snap["fabric_lease_reclaims"]}


def _untrack(shm) -> None:
    """Detach a SharedMemory from this process's resource tracker: this
    CPython registers segments on ATTACH too, and the tracker UNLINKS
    everything it tracks when its process exits — the first worker to
    die would tear the fleet's segment out from under the survivors.
    The fleet owns the lifecycle explicitly (Coordinator.unlink)."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception as e:  # noqa: BLE001 — tracker API drifts by version
        log.debug("resource-tracker unregister skipped: %s", e)
