"""AST → Expression building with name resolution + MySQL type inference
(reference: planner/core/expression_rewriter.go)."""

from __future__ import annotations

import numpy as np

from ..errors import ColumnError, TiDBError, ErrCode
from ..parser import ast
from ..sqltypes import (
    DEFAULT_DIV_PRECISION_INCREMENT, FLOAT_TYPES, INT_TYPES, MAX_DECIMAL_SCALE,
    STRING_TYPES, TYPE_DATE, TYPE_DATETIME, TYPE_DOUBLE, TYPE_DURATION,
    TYPE_LONGLONG, TYPE_NEWDATE, TYPE_NEWDECIMAL, TYPE_NULL, TYPE_TIMESTAMP,
    TYPE_VARCHAR, FieldType, UNSPECIFIED_LENGTH, parse_date_str,
    parse_datetime_str, str_to_decimal,
)
from .core import (
    Column, Constant, Expression, K_DATE, K_DEC, K_FLOAT, K_INT, K_STR,
    ScalarFunc, SubqueryApply, const_null, like_to_regex, phys_kind,
)

_BOOL_FT = FieldType(tp=TYPE_LONGLONG)

_OP_MAP = {
    "+": "add", "-": "sub", "*": "mul", "/": "div", "div": "intdiv",
    "mod": "mod", "%": "mod",
    "=": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge",
    "<=>": "nulleq", "and": "and", "or": "or", "xor": "xor",
}


class ColumnRef:
    """One name-resolvable output column of a plan node.

    origin: the CATALOG table name when it differs from `table` (a
    FROM-clause alias) — DEFAULT(col) must resolve the real table, not
    an alias that may shadow an unrelated one."""

    __slots__ = ("name", "table", "db", "ftype", "uid", "origin")

    def __init__(self, name, table, db, ftype, uid=0, origin=""):
        self.name = name.lower() if name else ""
        self.table = table.lower() if table else ""
        self.db = db.lower() if db else ""
        self.ftype = ftype
        self.uid = uid
        self.origin = origin.lower() if origin else ""

    def __repr__(self):
        return f"{self.table + '.' if self.table else ''}{self.name}"


class Schema:
    def __init__(self, refs: list[ColumnRef]):
        self.refs = refs

    def __len__(self):
        return len(self.refs)

    def find(self, cn: ast.ColumnName):
        name = cn.name.lower()
        table = cn.table.lower() if cn.table else ""
        db = cn.schema.lower() if cn.schema else ""
        matches = []
        for i, r in enumerate(self.refs):
            if r.name != name:
                continue
            if table and r.table != table:
                continue
            if db and r.db and r.db != db:
                continue
            matches.append(i)
        if not matches:
            return None
        if len(matches) > 1:
            # same table+name appearing twice is ambiguous; from different
            # tables without qualifier also ambiguous
            raise ColumnError(f"Column '{cn.name}' in field list is ambiguous",
                              code=ErrCode.NonUniq)
        return matches[0]

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.refs + other.refs)


def unify_types(fts: list[FieldType]) -> FieldType:
    """Result type for CASE/COALESCE/UNION column merging."""
    fts = [ft for ft in fts if ft.tp != TYPE_NULL]
    if not fts:
        return FieldType(tp=TYPE_NULL)
    kinds = [phys_kind(ft) for ft in fts]
    if all(k == K_STR for k in kinds):
        return FieldType(tp=TYPE_VARCHAR)
    if any(k == K_STR for k in kinds):
        return FieldType(tp=TYPE_VARCHAR)
    if any(k == K_FLOAT for k in kinds):
        return FieldType(tp=TYPE_DOUBLE)
    if any(k == K_DEC for k in kinds):
        s = max(ft.scale for ft in fts if phys_kind(ft) == K_DEC)
        return FieldType(tp=TYPE_NEWDECIMAL, flen=30, decimal=s)
    tps = {ft.tp for ft in fts}
    if tps <= {TYPE_DATE, TYPE_NEWDATE}:
        return FieldType(tp=TYPE_DATE)
    if tps <= {TYPE_DATE, TYPE_NEWDATE, TYPE_DATETIME, TYPE_TIMESTAMP}:
        return FieldType(tp=TYPE_DATETIME)
    return FieldType(tp=TYPE_LONGLONG)


def infer_arith_type(op: str, lft: FieldType, rft: FieldType) -> FieldType:
    lk, rk = phys_kind(lft), phys_kind(rft)
    if op in ("eq", "ne", "lt", "le", "gt", "ge", "nulleq", "and", "or",
              "xor", "in", "like", "not"):
        return _BOOL_FT.clone()
    if op == "intdiv":
        return FieldType(tp=TYPE_LONGLONG)
    float_in = (K_FLOAT in (lk, rk)) or (K_STR in (lk, rk))
    if op == "div":
        if float_in:
            return FieldType(tp=TYPE_DOUBLE)
        s1 = lft.scale if lk == K_DEC else 0
        return FieldType(tp=TYPE_NEWDECIMAL, flen=30,
                         decimal=min(s1 + DEFAULT_DIV_PRECISION_INCREMENT,
                                     MAX_DECIMAL_SCALE))
    if float_in:
        return FieldType(tp=TYPE_DOUBLE)
    if K_DEC in (lk, rk):
        s1 = lft.scale if lk == K_DEC else 0
        s2 = rft.scale if rk == K_DEC else 0
        if op == "mul":
            s = min(s1 + s2, MAX_DECIMAL_SCALE)
        else:
            s = max(s1, s2)
        return FieldType(tp=TYPE_NEWDECIMAL, flen=30, decimal=s)
    if op == "mod":
        return FieldType(tp=TYPE_LONGLONG)
    return FieldType(tp=TYPE_LONGLONG)


def refine_cmp_const(e, other):
    """Fold a comparison constant to the other side's physical type at plan
    time (reference: expression/builtin_compare.go refineArgs). A string
    constant compared with a temporal column becomes a date/datetime
    constant ONCE — instead of parsing the string per row at eval time —
    which also unlocks the device (TPU) compare path. Unparseable strings
    are left alone (eval-time semantics then apply, warnings included)."""
    if not isinstance(e, Constant) or e.value is None:
        return e
    if isinstance(other, Constant):
        return e
    if phys_kind(e.ftype) != K_STR:
        return e
    tk = other.ftype.tp
    v = e.value.decode() if isinstance(e.value, bytes) else str(e.value)

    def _refined(value, ft, conv):
        c = Constant(value, ft)
        if e.param_idx is not None:
            # keep param provenance + record the conversion so a plan-cache
            # hit can redo the refinement on the new raw value
            c.param_idx = e.param_idx
            c.param_conv = conv
        return c

    try:
        if tk in (TYPE_DATE, TYPE_NEWDATE):
            return _refined(parse_date_str(v), FieldType(tp=TYPE_DATE),
                            "date")
        if tk in (TYPE_DATETIME, TYPE_TIMESTAMP):
            return _refined(parse_datetime_str(v),
                            FieldType(tp=TYPE_DATETIME), "datetime")
        if phys_kind(other.ftype) in (K_INT, K_DEC, K_FLOAT):
            # MySQL compares string vs numeric as double; only refine when
            # the whole string parses (prefix-parse semantics stay at eval)
            return _refined(float(v), FieldType(tp=TYPE_DOUBLE), "float")
    except (ValueError, TiDBError):
        pass
    return e


def literal_to_constant(lit: ast.Literal) -> Constant:
    k = lit.kind
    if k == "null":
        return const_null()
    if k == "int":
        return Constant(int(lit.val), FieldType(tp=TYPE_LONGLONG))
    if k == "float":
        return Constant(float(lit.val), FieldType(tp=TYPE_DOUBLE))
    if k == "dec":
        text = str(lit.val)
        ip, _, frac = text.partition(".")
        scale = min(len(frac), MAX_DECIMAL_SCALE)
        # honest precision: digit count decides int64 vs wide-bigint repr
        prec = max(len(ip.lstrip("+-").lstrip("0")) + scale, scale, 1)
        return Constant(str_to_decimal(text, scale),
                        FieldType(tp=TYPE_NEWDECIMAL, flen=prec,
                                  decimal=scale))
    if k == "str":
        v = lit.val
        return Constant(v.encode() if isinstance(v, str) else v,
                        FieldType(tp=TYPE_VARCHAR))
    if k == "date":
        return Constant(parse_date_str(str(lit.val)), FieldType(tp=TYPE_DATE))
    if k == "datetime":
        return Constant(parse_datetime_str(str(lit.val)),
                        FieldType(tp=TYPE_DATETIME))
    if k == "time":
        from ..table import cast_value
        return Constant(cast_value(str(lit.val), FieldType(tp=TYPE_DURATION)),
                        FieldType(tp=TYPE_DURATION))
    raise TiDBError(f"unknown literal kind {k}")


# result type computation for scalar functions
_STR_FUNCS = {"concat", "concat_ws", "upper", "lower", "substring", "trim",
              "ltrim", "rtrim", "replace", "left", "right", "reverse",
              "repeat", "lpad", "rpad", "date_format", "hex", "md5", "sha1",
              "bin", "oct", "unhex", "sha2", "elt", "insert",
              "substring_index", "to_base64", "from_base64", "quote",
              "space", "char", "conv", "soundex", "format",
              "sec_to_time", "makedate", "maketime", "last_day", "dayname",
              "monthname", "str_to_date", "addtime", "subtime",
              "from_unixtime", "from_days",
              "json_extract", "json_unquote", "json_type", "json_object",
              "json_array", "json_keys", "json_set", "json_insert",
              "json_replace", "json_remove", "json_array_append",
              "json_merge_patch", "json_quote", "inet_ntoa", "uuid",
              "regexp_replace", "regexp_substr", "aes_encrypt",
              "aes_decrypt", "compress", "uncompress", "random_bytes",
              "password", "make_set", "export_set", "timediff",
              "timestampadd", "time", "timestamp", "time_format",
              "get_format", "uuid_to_bin", "bin_to_uuid", "format_bytes",
              "inet6_aton", "inet6_ntoa", "weight_string",
              "convert_tz", "json_search", "json_pretty",
              "json_merge_preserve", "json_merge", "json_array_insert",
              "json_append", "json_value", "load_file", "charset",
              "collation", "localtime", "localtimestamp", "current_time",
              "curtime", "utc_date", "utc_time", "tidb_version",
              "tidb_parse_tso", "tidb_decode_key", "format_nano_time",
              "master_pos_wait", "date_arith_fn", "substr", "sha",
              "gtid_subtract", "tidb_encode_sql_digest", "translate",
              "tidb_bounded_staleness", "tidb_decode_plan",
              "encode", "decode"}
_INT_FUNCS = {"length", "char_length", "character_length", "locate",
              "istrue_with_null", "year", "month", "day",
              "dayofmonth", "hour", "minute", "second", "quarter", "week",
              "dayofweek", "dayofyear", "extract", "datediff", "sign",
              "ascii", "instr", "isnull", "istrue", "isfalse", "found_rows",
              "row_count", "last_insert_id", "connection_id", "crc32",
              "ord", "strcmp", "field", "find_in_set", "bit_length",
              "bit_count", "unix_timestamp", "time_to_sec", "weekday",
              "weekofyear", "yearweek", "to_days", "period_add",
              "period_diff", "microsecond", "timestampdiff",
              "json_valid", "json_length", "json_contains", "json_depth",
              "json_contains_path", "regexp_like", "regexp_instr",
              "octet_length", "uncompressed_length", "uuid_short",
              "is_uuid", "benchmark", "is_ipv4_compat", "is_ipv4_mapped",
              "is_ipv4", "is_ipv6", "inet_aton", "sleep",
              "interval", "to_seconds", "json_overlaps",
              "json_storage_size", "json_member_of",
              "validate_password_strength", "coercibility", "get_lock",
              "release_lock", "is_free_lock", "is_used_lock",
              "tidb_is_ddl_owner", "tidb_shard", "gtid_subset",
              "release_all_locks", "ps_current_thread_id",
              "wait_for_executed_gtid_set", "vitess_hash"}
_FLOAT_FUNCS = {"sqrt", "exp", "ln", "log2", "log10", "pow", "power", "rand",
                "radians", "degrees", "sin", "cos", "tan", "atan", "asin",
                "acos", "pi", "atan2", "cot", "log"}


class OuterScope:
    """Name-resolution scope of an enclosing SELECT, used while building a
    (potentially correlated) subquery. Two phases share the class:
    - analysis: `bindings` is None; resolved outer columns are recorded in
      `used` (idx → ftype) and the built plan is discarded.
    - execution: `bindings` maps outer idx → the current outer row's value;
      resolution yields that value as a typed Constant.
    `parent` chains scopes for multi-level nesting."""

    def __init__(self, schema: Schema, bindings=None, parent=None,
                 mark=False):
        self.schema = schema
        self.bindings = bindings
        self.parent = parent
        self.used: dict = {}  # idx -> ftype (analysis phase)
        #: decorrelation-analysis mode: outer refs resolve to OuterRef
        #: markers (instead of NULL constants), so the planner can turn
        #: eq(outer, inner) predicates into join keys
        self.mark = mark

    def resolve(self, node):
        idx = self.schema.find(node)
        if idx is not None:
            ft = self.schema.refs[idx].ftype
            if self.bindings is not None:
                return Constant(self.bindings.get(idx), ft.clone())
            self.used[idx] = ft
            if self.mark:
                from .core import OuterRef
                return OuterRef(idx, ft.clone(),
                                name=self.schema.refs[idx].name)
            return Constant(None, ft.clone())
        if self.parent is not None:
            return self.parent.resolve(node)
        return None


class _SeqFunc(Expression):
    """NEXTVAL/LASTVAL/SETVAL over a sequence object: allocation is a
    session-level side effect per evaluated row (reference:
    expression/builtin_other.go builtinSequence*)."""

    def __init__(self, kind, session, info, val_expr=None):
        self.kind = kind
        self.session = session
        self.info = info
        self.val_expr = val_expr
        self.ftype = FieldType(tp=TYPE_LONGLONG)
        self.name = f"{kind}({info.name})"

    def eval(self, chunk):
        n = chunk.num_rows if chunk.num_cols else 1
        data = np.zeros(n, dtype=np.int64)
        nulls = np.zeros(n, dtype=bool)
        if self.kind == "nextval":
            for i in range(n):
                data[i] = self.session.seq_next(self.info)
        elif self.kind == "lastval":
            v = self.session.seq_lastval.get(self.info.id)
            if v is None:
                nulls[:] = True
            else:
                data[:] = v
        else:  # setval
            vd, vn = self.val_expr.eval(chunk)
            for i in range(n):
                if vn[i]:
                    nulls[i] = True
                else:
                    data[i] = self.session.seq_setval(self.info, int(vd[i]))
        return data, nulls

    def columns_used(self, acc):
        if self.val_expr is not None:
            self.val_expr.columns_used(acc)

    def transform_columns(self, fn):
        return self

    def __repr__(self):
        return self.name


class ExprBuilder:
    """Builds expressions against a schema. `ctx` (optional) provides:
    - eval_subquery(select_ast) -> (list of row tuples, [FieldType])
    - get_sysvar(name, scope) -> str value
    - get_uservar(name) -> value
    `outer` (optional OuterScope) resolves columns of enclosing SELECTs —
    the correlated-subquery path.
    """

    def __init__(self, schema: Schema, ctx=None, allow_agg=False, outer=None):
        self.schema = schema
        self.ctx = ctx
        self.allow_agg = allow_agg
        self.outer = outer

    def build(self, node: ast.ExprNode) -> Expression:
        m = getattr(self, "_b_" + type(node).__name__, None)
        if m is None:
            raise TiDBError(f"unsupported expression {type(node).__name__}")
        return fold_constant(m(node))

    # -- leaves -------------------------------------------------------------

    def _b_Literal(self, node):
        return literal_to_constant(node)

    def _b_ColumnName(self, node):
        idx = self.schema.find(node)
        if idx is None:
            if self.outer is not None:
                e = self.outer.resolve(node)
                if e is not None:
                    return e
            raise ColumnError(f"Unknown column '{node.name}' in 'field list'")
        r = self.schema.refs[idx]
        return Column(idx, r.ftype, name=r.name)

    def _b_ParamMarker(self, node):
        if self.ctx is not None and getattr(self.ctx, "params", None) is not None:
            try:
                v = self.ctx.params[node.index]
            except IndexError:
                raise TiDBError("missing prepared statement parameter")
            c = _python_value_to_constant(v)
            c.param_idx = node.index  # rebindable on plan-cache hits
            return c
        raise TiDBError("parameter marker outside prepared statement")

    def _b_VariableExpr(self, node):
        if self.ctx is None:
            raise TiDBError("variables not available in this context")
        if node.is_system:
            v = self.ctx.get_sysvar(node.name, node.scope or "session")
            return Constant(v.encode() if isinstance(v, str) else v,
                            FieldType(tp=TYPE_VARCHAR))
        if node.value is not None:
            val_expr = self.build(node.value)
            v = val_expr.eval_scalar()
            self.ctx.set_uservar(node.name, v)
            return val_expr
        return _python_value_to_constant(self.ctx.get_uservar(node.name))

    def _b_DefaultExpr(self, node):
        # SELECT DEFAULT(col): the column's catalog default as a constant
        # (reference: planner/core/expression_rewriter.go evalDefaultExpr).
        # Bare DEFAULT in INSERT/UPDATE value lists never reaches here —
        # the DML executors resolve it positionally first.
        if node.col is None or self.ctx is None:
            raise TiDBError("DEFAULT is only valid in INSERT/UPDATE")
        ref_i = self.schema.find(node.col)
        if ref_i is None:
            raise ColumnError(
                f"Unknown column '{node.col.name}' in 'field list'")
        r = self.schema.refs[ref_i]
        sess = getattr(self.ctx, "session", None)
        # r.origin names the CATALOG table even when r.table is a
        # FROM-clause alias (which may shadow an unrelated real table);
        # view-expanded / derived columns carry no origin → no default
        src = getattr(r, "origin", "") or ""
        if sess is None or not src:
            raise TiDBError("DEFAULT is only valid in INSERT/UPDATE")
        try:
            info = sess.infoschema().table_by_name(
                r.db or sess.current_db(), src)
        except Exception:
            raise TiDBError("DEFAULT is only valid in INSERT/UPDATE")
        ci = info.find_column(r.name)
        if ci is None:
            raise TiDBError("DEFAULT is only valid in INSERT/UPDATE")
        if ci.default_value is None:
            if ci.ftype is not None and ci.ftype.not_null:
                raise TiDBError(
                    f"Field '{ci.name}' doesn't have a default value",
                    code=ErrCode.NoDefaultValue)
            return Constant(None, ci.ftype)
        return Constant(ci.default_value, ci.ftype)

    # -- operators ----------------------------------------------------------

    def _b_BinaryOp(self, node):
        op = _OP_MAP.get(node.op)
        if op is None:
            if node.op in ("&", "|", "^", "<<", ">>"):
                return self._bitop(node)
            raise TiDBError(f"unsupported operator {node.op}")
        if (node.op in ("+", "-")
                and isinstance(node.right, ast.IntervalExpr)):
            # expr ± INTERVAL n UNIT ≡ DATE_ADD/DATE_SUB (MySQL temporal
            # arithmetic; reference: ast.DateArith)
            return self._b_FuncCall(ast.FuncCall(
                name="date_add" if node.op == "+" else "date_sub",
                args=[node.left, node.right]))
        if node.op == "+" and isinstance(node.left, ast.IntervalExpr):
            return self._b_FuncCall(ast.FuncCall(
                name="date_add", args=[node.right, node.left]))
        l = self.build(node.left)
        r = self.build(node.right)
        if op in ("eq", "ne", "lt", "le", "gt", "ge", "nulleq"):
            l, r = refine_cmp_const(l, r), refine_cmp_const(r, l)
        ft = infer_arith_type(op, l.ftype, r.ftype)
        return ScalarFunc(op, [l, r], ft)

    def _bitop(self, node):
        l = self.build(node.left)
        r = self.build(node.right)
        opname = {"&": "bitand", "|": "bitor", "^": "bitxor",
                  "<<": "shl", ">>": "shr"}[node.op]
        return ScalarFunc(opname, [l, r], FieldType(tp=TYPE_LONGLONG))

    def _b_UnaryOp(self, node):
        operand = self.build(node.operand)
        if node.op == "-":
            ft = operand.ftype.clone()
            if phys_kind(ft) == K_STR:
                ft = FieldType(tp=TYPE_DOUBLE)
            return ScalarFunc("neg", [operand], ft)
        if node.op == "not":
            return ScalarFunc("not", [operand], _BOOL_FT.clone())
        if node.op == "~":
            return ScalarFunc("bitneg", [operand], FieldType(tp=TYPE_LONGLONG))
        raise TiDBError(f"unsupported unary op {node.op}")

    def _b_IsNullExpr(self, node):
        e = ScalarFunc("isnull", [self.build(node.expr)], _BOOL_FT.clone())
        if node.negated:
            return ScalarFunc("not", [e], _BOOL_FT.clone())
        return e

    def _b_IsTruthExpr(self, node):
        op = "istrue" if node.truth else "isfalse"
        e = ScalarFunc(op, [self.build(node.expr)], _BOOL_FT.clone())
        if node.negated:
            return ScalarFunc("not", [e], _BOOL_FT.clone())
        return e

    def _b_BetweenExpr(self, node):
        e = self.build(node.expr)
        lo = refine_cmp_const(self.build(node.low), e)
        hi = refine_cmp_const(self.build(node.high), e)
        ge = ScalarFunc("ge", [e, lo], _BOOL_FT.clone())
        le = ScalarFunc("le", [e, hi], _BOOL_FT.clone())
        res = ScalarFunc("and", [ge, le], _BOOL_FT.clone())
        if node.negated:
            return ScalarFunc("not", [res], _BOOL_FT.clone())
        return res

    def _b_InExpr(self, node):
        target = self.build(node.expr)
        if len(node.items) == 1 and isinstance(node.items[0], ast.SubqueryExpr):
            sub_sel = node.items[0].query
            scope, plan = self._try_analyze(sub_sel)
            if scope is not None and scope.used:
                if len(plan.schema) != 1:
                    raise TiDBError("Operand should contain 1 column(s)",
                                    code=ErrCode.OperandColumns)
                e = self._make_apply(sub_sel, scope, "in", _BOOL_FT.clone(),
                                     target=target,
                                     sub_ft=plan.schema.refs[0].ftype)
                if node.negated:
                    return ScalarFunc("not", [e], _BOOL_FT.clone())
                return e
            if scope is not None:
                rows, fts = self._eval_analyzed(plan, sub_sel)
            else:
                rows, fts = self._run_subquery(sub_sel)
            if fts and len(fts) != 1:
                raise TiDBError("Operand should contain 1 column(s)",
                                code=ErrCode.OperandColumns)
            sub_ft = fts[0] if fts else target.ftype
            e = build_in_set(target, [r[0] for r in rows], sub_ft)
        else:
            items = [refine_cmp_const(self.build(i), target)
                     for i in node.items]
            consts = all(isinstance(i, Constant) for i in items)
            kinds = {phys_kind(i.ftype) for i in items if i.value is not None}
            if consts and (phys_kind(target.ftype) == K_STR) == (kinds <= {K_STR}):
                vals, vft = [], unify_types(
                    [i.ftype for i in items if i.value is not None] or [target.ftype])
                from ..table import convert_internal
                for i in items:
                    vals.append(None if i.value is None
                                else convert_internal(i.value, i.ftype, vft))
                e = build_in_set(target, vals, vft)
            else:
                e = ScalarFunc("in", [target] + items, _BOOL_FT.clone())
        if node.negated:
            return ScalarFunc("not", [e], _BOOL_FT.clone())
        return e

    def _b_LikeExpr(self, node):
        e = self.build(node.expr)
        pat = self.build(node.pattern)
        extra = None
        if isinstance(pat, Constant) and pat.value is not None:
            esc = node.escape.encode() if isinstance(node.escape, str) else node.escape
            extra = like_to_regex(pat.value, esc or b"\\")
        res = ScalarFunc("like", [e, pat], _BOOL_FT.clone(), extra=extra)
        if node.negated:
            return ScalarFunc("not", [res], _BOOL_FT.clone())
        return res

    def _b_RegexpExpr(self, node):
        e = self.build(node.expr)
        pat = self.build(node.pattern)
        res = ScalarFunc("regexp", [e, pat], _BOOL_FT.clone())
        if node.negated:
            return ScalarFunc("not", [res], _BOOL_FT.clone())
        return res

    def _b_CaseExpr(self, node):
        args = []
        result_fts = []
        for cond, res in node.whens:
            if node.operand is not None:
                c = ast.BinaryOp(op="=", left=node.operand, right=cond)
            else:
                c = cond
            args.append(self.build(c))
            r = self.build(res)
            args.append(r)
            result_fts.append(r.ftype)
        if node.else_ is not None:
            e = self.build(node.else_)
            args.append(e)
            result_fts.append(e.ftype)
        ft = unify_types(result_fts)
        return ScalarFunc("case", args, ft)

    def _b_CastExpr(self, node):
        e = self.build(node.expr)
        return ScalarFunc("cast", [e], node.ftype.clone())

    def _b_RowExpr(self, node):
        raise TiDBError("row expressions not supported in this context")

    def _b_SubqueryExpr(self, node):
        scope, plan = self._try_analyze(node.query)
        if scope is not None and scope.used:
            if len(plan.schema) != 1:
                raise TiDBError("Operand should contain 1 column(s)",
                                code=ErrCode.OperandColumns)
            return self._make_apply(node.query, scope, "scalar",
                                    plan.schema.refs[0].ftype.clone())
        if scope is not None:
            rows, fts = self._eval_analyzed(plan, node.query)
        else:
            rows, fts = self._run_subquery(node.query)
        if len(rows) > 1:
            raise TiDBError("Subquery returns more than 1 row",
                            code=ErrCode.SubqueryMoreThan1Row)
        if fts and len(fts) != 1:
            raise TiDBError("Operand should contain 1 column(s)",
                            code=ErrCode.OperandColumns)
        if not rows:
            return const_null()
        v = rows[0][0]
        return Constant(v, fts[0]) if v is not None else const_null()

    def _b_ExistsExpr(self, node):
        scope, plan = self._try_analyze(node.query.query)
        if scope is not None and scope.used:
            return self._make_apply(
                node.query.query, scope,
                "not_exists" if node.negated else "exists",
                _BOOL_FT.clone(), limit_one=True)
        if scope is not None:
            rows, _ = self._eval_analyzed(plan, node.query.query,
                                          limit_one=True)
        else:
            rows, _ = self._run_subquery(node.query.query, limit_one=True)
        v = 1 if rows else 0
        if node.negated:
            v = 1 - v
        return Constant(v, _BOOL_FT.clone())

    def _b_CompareSubquery(self, node):
        scope, plan = self._try_analyze(node.query.query)
        if scope is not None and scope.used:
            if len(plan.schema) != 1:
                raise TiDBError("Operand should contain 1 column(s)",
                                code=ErrCode.OperandColumns)
            target = self.build(node.expr)
            quant = "any" if node.quantifier == "any" else "all"
            return self._make_apply(
                node.query.query, scope, (quant, _OP_MAP[node.op]),
                _BOOL_FT.clone(), target=target,
                sub_ft=plan.schema.refs[0].ftype)
        if scope is not None:
            rows, fts = self._eval_analyzed(plan, node.query.query)
        else:
            rows, fts = self._run_subquery(node.query.query)
        vals = [r[0] for r in rows]
        target = self.build(node.expr)
        op = _OP_MAP[node.op]
        if node.quantifier == "any":
            if op == "eq":
                return build_in_set(target, vals)
            agg = "min" if op in ("gt", "ge") else "max"
        else:  # all
            if op == "ne":
                e = build_in_set(target, vals)
                return ScalarFunc("not", [e], _BOOL_FT.clone())
            agg = "max" if op in ("gt", "ge") else "min"
        if not vals:
            return Constant(1 if node.quantifier == "all" else 0, _BOOL_FT.clone())
        non_null = [v for v in vals if v is not None]
        if not non_null:
            return const_null()
        pick = min(non_null) if agg == "min" else max(non_null)
        return ScalarFunc(op, [target, Constant(pick, fts[0])], _BOOL_FT.clone())

    def _b_AggregateFunc(self, node):
        raise TiDBError("Invalid use of group function",
                        code=ErrCode.InvalidGroupFuncUse)

    def _b_WindowFunc(self, node):
        # the planner's window stage registers each OVER() expression's
        # output column here (planner/builder.py _build_window)
        wm = getattr(self, "window_map", None)
        if wm is not None:
            col = wm.get(node.restore())
            if col is not None:
                return col
        raise TiDBError("window function not valid here")

    def _b_IntervalExpr(self, node):
        raise TiDBError("INTERVAL is only valid in date arithmetic")

    def _b_StarExpr(self, node):
        raise TiDBError("'*' not valid here")

    # -- function calls -----------------------------------------------------

    def _b_FuncCall(self, node):
        name = node.name
        if name in ("date_add", "date_sub", "adddate", "subdate"):
            sign = 1 if name in ("date_add", "adddate") else -1
            src = self.build(node.args[0])
            iv = node.args[1]
            if isinstance(iv, ast.IntervalExpr):
                unit = iv.unit
                val = self.build(iv.value)
            else:
                unit = "day"
                val = self.build(iv)
            if unit in ("microsecond", "second", "minute", "hour",
                        "second_microsecond", "minute_second", "hour_minute"):
                out_ft = FieldType(tp=TYPE_DATETIME)
            else:
                out_ft = (FieldType(tp=TYPE_DATE)
                          if src.ftype.tp in (TYPE_DATE, TYPE_NEWDATE)
                          else FieldType(tp=src.ftype.tp if src.ftype.tp in
                                         (TYPE_DATETIME, TYPE_TIMESTAMP) else TYPE_DATETIME))
            return ScalarFunc("date_arith", [src, val], out_ft, extra=(unit, sign))
        if name == "extract":
            unit = node.args[0].val
            e = self.build(node.args[1])
            return ScalarFunc("extract", [Constant(str(unit).encode(), FieldType(tp=TYPE_VARCHAR)), e],
                              FieldType(tp=TYPE_LONGLONG), extra=str(unit))
        if name in ("now", "current_timestamp", "sysdate", "curdate",
                    "current_date", "curtime", "utc_timestamp"):
            import datetime as _dt
            from ..sqltypes import datetime_to_micros, date_to_days
            now = self.ctx.now() if self.ctx is not None and hasattr(self.ctx, "now") else _dt.datetime.now()
            if name in ("curdate", "current_date"):
                return Constant(date_to_days(now.year, now.month, now.day),
                                FieldType(tp=TYPE_DATE))
            fsp = 0
            if node.args and isinstance(node.args[0], ast.Literal):
                try:
                    fsp = max(0, min(int(node.args[0].val), 6))
                except (TypeError, ValueError):
                    fsp = 0
            micros = datetime_to_micros(now)
            micros -= micros % (10 ** (6 - fsp))  # MySQL truncates to fsp
            return Constant(micros,
                            FieldType(tp=TYPE_DATETIME, decimal=fsp))
        if name in ("database", "schema"):
            db = self.ctx.current_db() if self.ctx is not None else ""
            return (Constant(db.encode(), FieldType(tp=TYPE_VARCHAR))
                    if db else const_null())
        if name == "tidb_decode_sql_digests":
            # runtime eval needs the domain's statements summary; attach
            # it as extra (builtins_ext._eval_decode_sql_digests)
            args = [self.build(a) for a in node.args]
            sf = ScalarFunc(name, args, FieldType(tp=TYPE_VARCHAR))
            sess = getattr(self.ctx, "session", None)
            obs = getattr(getattr(sess, "domain", None), "observe", None)
            sf.extra = getattr(obs, "stmt_summary", None)
            return sf
        if name == "version":
            return Constant(b"8.0.11-tpu-htap", FieldType(tp=TYPE_VARCHAR))
        if name in ("user", "current_user", "session_user", "system_user"):
            u = self.ctx.current_user() if self.ctx is not None else "root@%"
            return Constant(u.encode(), FieldType(tp=TYPE_VARCHAR))
        if name == "current_role":
            # no SET ROLE support: the active-role list is always empty,
            # which MySQL renders as NONE (reference:
            # expression/builtin_info.go builtinCurrentRoleSig)
            return Constant(b"NONE", FieldType(tp=TYPE_VARCHAR))
        if name == "unix_timestamp" and not node.args:
            import datetime as _dt2
            now = (self.ctx.now() if self.ctx is not None
                   and hasattr(self.ctx, "now") else _dt2.datetime.now())
            return Constant(int(now.timestamp()),
                            FieldType(tp=TYPE_LONGLONG))
        if name in ("nextval", "lastval", "setval") and node.args:
            sess = getattr(self.ctx, "session", None)
            if sess is None:
                raise TiDBError(f"{name} requires a session")
            arg = node.args[0]
            if isinstance(arg, ast.ColumnName):
                db = arg.table or sess.current_db()
                seq_name = arg.name
            elif isinstance(arg, ast.Literal):
                v = arg.val
                if isinstance(v, bytes):
                    v = v.decode()
                db, _, seq_name = str(v).rpartition(".")
                db = db or sess.current_db()
            else:
                raise TiDBError(f"bad sequence reference in {name}")
            info = sess.infoschema().table_by_name(db, seq_name)
            if not info.is_sequence:
                raise TiDBError(f"'{db}.{seq_name}' is not SEQUENCE",
                                code=ErrCode.WrongObjectSequence)
            val = self.build(node.args[1]) if (name == "setval"
                                              and len(node.args) > 1) else None
            return _SeqFunc(name, sess, info, val)
        if name in ("connection_id", "found_rows", "row_count",
                    "last_insert_id") and not node.args:
            sess = getattr(self.ctx, "session", None)
            v = {"connection_id": getattr(sess, "conn_id", 0),
                 "found_rows": getattr(sess, "found_rows", 0),
                 "row_count": getattr(sess, "affected_rows", 0),
                 "last_insert_id": getattr(sess, "last_insert_id", 0),
                 }[name]
            return Constant(int(v or 0), FieldType(tp=TYPE_LONGLONG))
        if name in ("charset", "collation"):
            args = [self.build(a) for a in node.args]
            v = b"binary" if name == "collation" else b"utf8mb4"
            if args and args[0].ftype.tp in (TYPE_VARCHAR,):
                v = (args[0].ftype.collate or "utf8mb4_bin").encode() \
                    if name == "collation" else b"utf8mb4"
            return Constant(v, FieldType(tp=TYPE_VARCHAR))
        if name == "any_value" and node.args:
            return self.build(node.args[0])
        if name in ("lcase", "ucase", "mid"):
            node = ast.FuncCall(
                name={"lcase": "lower", "ucase": "upper",
                      "mid": "substring"}[name], args=node.args)
            return self._b_FuncCall(node)
        if name in ("if",):
            args = [self.build(a) for a in node.args]
            ft = unify_types([args[1].ftype, args[2].ftype])
            return ScalarFunc("if", args, ft)
        if name in ("ifnull", "coalesce"):
            args = [self.build(a) for a in node.args]
            ft = unify_types([a.ftype for a in args])
            return ScalarFunc("coalesce", args, ft)
        if name == "nullif":
            args = [self.build(a) for a in node.args]
            return ScalarFunc("nullif", args, args[0].ftype.clone())
        if name in ("greatest", "least"):
            args = [self.build(a) for a in node.args]
            ft = unify_types([a.ftype for a in args])
            return ScalarFunc(name, args, ft)
        if name in ("truncate",):
            # result typing mirrors ROUND (reference: builtin_math.go —
            # decimal in, decimal out): TRUNCATE on a wide decimal column
            # must not collapse to binary float
            args = [self.build(a) for a in node.args]
            nd_const = (len(args) <= 1
                        or isinstance(args[1], Constant))
            nd = 0
            if (len(args) > 1 and isinstance(args[1], Constant)
                    and args[1].value is not None):
                nd = int(args[1].value)
            src_ft = args[0].ftype
            if phys_kind(src_ft) == K_DEC and nd_const:
                ft = FieldType(tp=TYPE_NEWDECIMAL, flen=30,
                               decimal=max(min(nd, src_ft.scale), 0))
            elif (phys_kind(src_ft) in (K_FLOAT, K_STR) or not nd_const):
                # a column-valued digit count has no static scale; string
                # inputs coerce to a numeric double (MySQL: TRUNCATE
                # ('1.999', 1) -> 1.9, not an integer)
                ft = FieldType(tp=TYPE_DOUBLE)
            else:
                ft = FieldType(tp=TYPE_LONGLONG)
            return ScalarFunc("truncate", args, ft)
        if name == "name_const":
            # NAME_CONST(name, value) evaluates to its value with the
            # value's own type (reference: builtin_miscellaneous.go)
            args = [self.build(a) for a in node.args]
            return args[1]
        if name == "any_value":
            return self.build(node.args[0])
        if name == "round":
            args = [self.build(a) for a in node.args]
            nd = 0
            if len(args) > 1 and isinstance(args[1], Constant) and args[1].value is not None:
                nd = int(args[1].value)
            src_ft = args[0].ftype
            if phys_kind(src_ft) == K_DEC:
                ft = FieldType(tp=TYPE_NEWDECIMAL, flen=30,
                               decimal=max(min(nd, src_ft.scale), 0))
            elif phys_kind(src_ft) == K_FLOAT:
                ft = FieldType(tp=TYPE_DOUBLE)
            else:
                ft = FieldType(tp=TYPE_LONGLONG)
            return ScalarFunc("round", args, ft)
        if name in ("abs", "ceil", "ceiling", "floor"):
            args = [self.build(a) for a in node.args]
            src_ft = args[0].ftype
            if name == "abs":
                ft = src_ft.clone()
            else:
                ft = FieldType(tp=TYPE_LONGLONG)
            op = {"ceiling": "ceil"}.get(name, name)
            return ScalarFunc(op, args, ft)
        args = [self.build(a) for a in node.args]
        if name in _STR_FUNCS:
            ft = FieldType(tp=TYPE_VARCHAR)
        elif name in _INT_FUNCS:
            ft = FieldType(tp=TYPE_LONGLONG)
            if name == "vitess_hash":
                # a uint64 shard hash: stored wrapped in int64, rendered
                # back through the unsigned flag
                from ..sqltypes import FLAG_UNSIGNED
                ft.flag |= FLAG_UNSIGNED
        elif name in _FLOAT_FUNCS:
            ft = FieldType(tp=TYPE_DOUBLE)
        elif name == "date":
            ft = FieldType(tp=TYPE_DATE)
        else:
            raise TiDBError(f"unsupported function {name.upper()}")
        op = {"power": "pow", "substr": "substring"}.get(name, name)
        return ScalarFunc(op, args, ft)

    # -- helpers ------------------------------------------------------------

    def _run_subquery(self, select, limit_one=False):
        if self.ctx is None or not hasattr(self.ctx, "eval_subquery"):
            raise TiDBError("subqueries not available in this context")
        return self.ctx.eval_subquery(select, limit_one=limit_one,
                                      outer=self.outer)

    def _try_analyze(self, select):
        """Analysis pass for a subquery: build its plan with this SELECT's
        schema as the outer scope; the scope records which outer columns the
        subquery references (correlation). The plan is reused for execution
        when no correlation was found (avoids planning twice).

        `sub_memo` (installed by the planner's decorrelation rule) caches
        the rule's own analysis per AST node: without it, a decorrelation
        bail would re-analyze — and analysis EXECUTES eager nested
        uncorrelated subqueries, so the re-run would evaluate them twice
        per statement."""
        memo = getattr(self, "sub_memo", None)
        if memo is not None:
            hit = memo.get(id(select))
            if hit is not None:
                return hit
        if self.ctx is None or not hasattr(self.ctx, "analyze_subquery"):
            return None, None
        scope = OuterScope(self.schema, parent=self.outer)
        plan = self.ctx.analyze_subquery(select, scope)
        return scope, plan

    def _eval_analyzed(self, plan, select, limit_one=False):
        """Execute an uncorrelated subquery, reusing its analyzed plan when
        the context supports it (the analysis build already ran any eager
        nested subqueries — re-planning would run them twice)."""
        if hasattr(self.ctx, "eval_built_plan"):
            return self.ctx.eval_built_plan(plan, limit_one=limit_one)
        return self._run_subquery(select, limit_one=limit_one)

    def _make_apply(self, select, scope, mode, ftype, target=None,
                    limit_one=False, sub_ft=None):
        """Correlated subquery → Apply expression. The runner re-plans the
        subquery per distinct binding of the referenced outer columns; the
        outer chain (with any enclosing bindings) threads through so deeper
        nesting keeps resolving."""
        idxs = sorted(scope.used)
        outer_cols = [Column(i, scope.used[i],
                             name=self.schema.refs[i].name) for i in idxs]
        ctx = self.ctx
        parent = self.outer
        schema = self.schema

        def runner(key):
            bindings = dict(zip(idxs, key))
            rows, _fts = ctx.eval_subquery(
                select, limit_one=limit_one,
                outer=OuterScope(schema, bindings=bindings, parent=parent))
            return rows

        return SubqueryApply(runner, outer_cols, mode, ftype, target=target,
                             sub_ft=sub_ft)


_NONDETERMINISTIC = {"rand", "uuid", "sleep", "in_set"}


def fold_constant(expr: Expression) -> Expression:
    """Constant folding (reference: expression/constant_fold.go): a scalar
    function whose args are all constants evaluates once at build time —
    also what lets date arithmetic reach device kernels as scalars."""
    if not isinstance(expr, ScalarFunc) or expr.op in _NONDETERMINISTIC:
        return expr
    if not expr.args or not all(isinstance(a, Constant) for a in expr.args):
        return expr
    if any(a.param_idx is not None for a in expr.args):
        # never fold a prepared param into a derived constant — the param
        # leaf must survive so plan-cache hits can rebind it in place
        return expr
    try:
        # INTERNAL repr: a folded Constant's value contract is the
        # physical one (scaled int for decimals), same as
        # literal_to_constant
        v = expr.eval_scalar_internal()
    except Exception:
        return expr
    if v is None:
        c = const_null()
        c.ftype = expr.ftype.clone()
        return c
    return Constant(v, expr.ftype.clone())


def build_in_set(target: Expression, values, values_ft: FieldType = None) -> ScalarFunc:
    """IN against a materialized value list (semi-join materialization for
    uncorrelated IN-subqueries, reference: planner rewrites these to
    semi-joins — here the hash set *is* the join). The target is coerced to a
    comparison type unified with the value list's type."""
    if values_ft is None:
        values_ft = target.ftype
    common = unify_types([target.ftype, values_ft])
    has_null = any(v is None for v in values)
    non_null = [v for v in values if v is not None]
    k = phys_kind(common)
    from ..table import convert_internal
    conv = [convert_internal(v, values_ft, common) for v in non_null]
    if k == K_STR:
        vals = set(v if isinstance(v, bytes) else str(v).encode() for v in conv)
    elif k == K_FLOAT:
        vals = np.array([float(v) for v in conv], dtype=np.float64)
    else:
        vals = np.array([int(v) for v in conv], dtype=np.int64)
    cmp_target = target
    if (phys_kind(target.ftype), target.ftype.scale) != (k, common.scale):
        cmp_target = ScalarFunc("cast", [target], common)
    return ScalarFunc("in_set", [cmp_target], _BOOL_FT.clone(),
                      extra=(vals, has_null))


def _python_value_to_constant(v):
    import decimal
    if v is None:
        return const_null()
    if isinstance(v, bool):
        return Constant(int(v), FieldType(tp=TYPE_LONGLONG))
    if isinstance(v, decimal.Decimal):
        # user-var decimals (eval_scalar products) re-enter as exact
        # decimal constants: internal scaled int at the value's own scale
        text = format(v, "f")
        ip, _, frac = text.partition(".")
        scale = min(len(frac), MAX_DECIMAL_SCALE)
        prec = max(len(ip.lstrip("+-").lstrip("0")) + scale, scale, 1)
        return Constant(str_to_decimal(text, scale),
                        FieldType(tp=TYPE_NEWDECIMAL, flen=prec,
                                  decimal=scale))
    if isinstance(v, int):
        return Constant(v, FieldType(tp=TYPE_LONGLONG))
    if isinstance(v, float):
        return Constant(v, FieldType(tp=TYPE_DOUBLE))
    if isinstance(v, str):
        return Constant(v.encode(), FieldType(tp=TYPE_VARCHAR))
    if isinstance(v, bytes):
        return Constant(v, FieldType(tp=TYPE_VARCHAR))
    raise TiDBError(f"cannot convert {type(v)} to constant")
