"""Per-table columnar snapshots with incremental delta maintenance.

Scans are the hot read path of the analytical engine; decoding rows per query
would drown the device in host work. The cache materializes a table once into
column arrays (plus the handle column) and then keeps the snapshot fresh by
applying each commit's row mutations as a delta — appended row versions plus
tombstones over older ones — compacting periodically. This is the TiFlash
delta-tree role (stable layer + delta layer + background merge) rather than
the rebuild-on-version-bump v1: a single-row write no longer re-decodes the
table. Bulk loaders (the Lightning role) can still install columns directly,
bypassing row encode/decode entirely.
"""

from __future__ import annotations

import threading

import numpy as np

from ..model import TableInfo
from ..sqltypes import TYPE_LONGLONG, FieldType
from ..table import Table, rows_to_chunk
from ..utils.chunk import Chunk, Column

#: compact when the delta exceeds this many rows or this fraction of the base
_COMPACT_MIN = 4096
_COMPACT_FRAC = 8  # base_n // _COMPACT_FRAC


class _Seg:
    """One commit's appended row versions (the delta layer)."""

    __slots__ = ("handles", "live", "columns")

    def __init__(self, handles, live, columns):
        self.handles = handles    # np.int64
        self.live = live          # np.bool (False = superseded later)
        self.columns = columns    # {col_id: Column}


class _Entry:
    __slots__ = ("version", "col_sig", "columns", "handles", "base_live",
                 "base_all_live", "segs", "delta_pos", "nrows",
                 "_merged", "_merged_handles", "_base_idx", "lock")

    def __init__(self, version, col_sig, columns, handles, nrows):
        self.lock = threading.Lock()   # per-entry: merge/apply/compact
        self.version = version
        self.col_sig = col_sig
        self.columns = columns    # base layer {col_id: Column}
        self.handles = handles    # base handles, ASCENDING (KV scan order)
        self.base_live = None     # lazily created bool mask (None = all live)
        self.base_all_live = True
        self.segs: list[_Seg] = []
        self.delta_pos: dict[int, tuple[int, int]] = {}  # handle->(seg,pos)
        self.nrows = nrows        # live row count across base + delta
        self._merged = {}         # col_id -> merged Column cache
        self._merged_handles = None
        self._base_idx = None     # cached np.nonzero(base_live)

    # -- invariant helpers --------------------------------------------------

    def delta_rows(self) -> int:
        return sum(len(s.handles) for s in self.segs)

    def _invalidate_merge(self):
        self._merged = {}
        self._merged_handles = None
        self._base_idx = None

    def _base_indices(self):
        if self.base_all_live:
            return None  # whole base
        if self._base_idx is None:
            self._base_idx = np.nonzero(self.base_live)[0]
        return self._base_idx

    def _tombstone(self, h: int) -> bool:
        """Mark any live occurrence of handle h dead. True if one existed."""
        pos = self.delta_pos.pop(h, None)
        if pos is not None:
            seg, i = pos
            if self.segs[seg].live[i]:
                self.segs[seg].live[i] = False
                self.nrows -= 1
                return True
        i = int(np.searchsorted(self.handles, h))
        if i < len(self.handles) and self.handles[i] == h:
            if self.base_live is None:
                self.base_live = np.ones(len(self.handles), dtype=bool)
            if self.base_live[i]:
                self.base_live[i] = False
                self.base_all_live = False
                self.nrows -= 1
                return True
        return False

    def merged_column(self, col_id: int, fallback_fn) -> Column:
        """Column over live rows: base[live] ++ seg0[live] ++ ... Cached
        until the next delta so repeated scans after one write stay
        zero-decode AND zero-copy."""
        col = self._merged.get(col_id)
        if col is not None:
            return col
        base = self.columns.get(col_id)
        if base is None:
            return fallback_fn(col_id)
        if not self.segs and self.base_all_live:
            self._merged[col_id] = base
            return base
        idx = self._base_indices()
        datas, nulls = [], []
        d = base.data if idx is None else base.data[idx]
        n = base.nulls if idx is None else base.nulls[idx]
        datas.append(d)
        nulls.append(n)
        for s in self.segs:
            sc = s.columns[col_id]
            if s.live.all():
                datas.append(sc.data)
                nulls.append(sc.nulls)
            else:
                li = np.nonzero(s.live)[0]
                datas.append(sc.data[li])
                nulls.append(sc.nulls[li])
        col = Column(base.ftype, np.concatenate(datas), np.concatenate(nulls))
        self._merged[col_id] = col
        return col

    def merged_handles(self) -> np.ndarray:
        if self._merged_handles is not None:
            return self._merged_handles
        if not self.segs and self.base_all_live:
            self._merged_handles = self.handles
            return self.handles
        idx = self._base_indices()
        parts = [self.handles if idx is None else self.handles[idx]]
        for s in self.segs:
            parts.append(s.handles if s.live.all()
                         else s.handles[np.nonzero(s.live)[0]])
        self._merged_handles = np.concatenate(parts)
        return self._merged_handles


class ColumnarCache:
    def __init__(self, storage):
        self.storage = storage
        self._lock = threading.Lock()
        self._entries: dict[int, _Entry] = {}

    def invalidate(self, table_id: int):
        with self._lock:
            self._entries.pop(table_id, None)

    def get(self, info: TableInfo, snapshot) -> _Entry | None:
        """Materialized columns for the table at the current write watermark.
        `snapshot` must be a kv view with .scan (Snapshot or Transaction).

        Returns None when the reader's snapshot ts predates the last commit
        the cache reflects (an explicit txn holding an old read view after
        another session committed): serving the cache would leak post-
        snapshot rows, so the caller must scan through its own snapshot."""
        tid = info.id
        reader_ts = getattr(snapshot, "ts", None)
        if reader_ts is None:
            reader_ts = getattr(snapshot, "start_ts", 0)
        version, last_commit_ts = self.storage.mvcc.table_version_info(tid)
        if reader_ts < last_commit_ts:
            return None
        col_sig = tuple(c.id for c in info.public_columns())
        with self._lock:
            e = self._entries.get(tid)
            if e is not None and e.version == version and e.col_sig == col_sig:
                return e
        # build from the caller's snapshot: reader_ts >= last_commit_ts, so
        # it sees exactly the content of `version` (a commit racing in is
        # invisible to this ts; if the version counter advanced meanwhile,
        # apply_delta's version chain check heals by idempotent re-apply
        # or drop-and-rebuild)
        e = self._build(info, snapshot, version, col_sig)
        with self._lock:
            cur = self._entries.get(tid)
            # a concurrent apply_delta may have advanced the entry past our
            # snapshot — never clobber a newer entry with an older build
            if cur is None or cur.version <= e.version:
                self._entries[tid] = e
            else:
                e = cur
        return e

    def _build(self, info, snapshot, version, col_sig):
        tbl = Table(info, snapshot)
        cols = info.public_columns()
        handles = []
        rowdicts = []
        for handle, row in tbl.iter_rows():
            handles.append(handle)
            rowdicts.append(row)
        chunk = rows_to_chunk(info, cols, handles, rowdicts)
        columns = {c.id: chunk.columns[i] for i, c in enumerate(cols)}
        return _Entry(version, col_sig, columns,
                      np.array(handles, dtype=np.int64), len(handles))

    # -- delta maintenance (reference analog: TiFlash delta tree;
    #    v1 behavior was rebuild-on-invalidate) ------------------------------

    def apply_delta(self, info: TableInfo, muts, new_version: int):
        """Apply one committed txn's record mutations.

        muts: [(handle, encoded_row_bytes | None)] — None is a delete.
        new_version: the table version this commit produced; the entry must
        be exactly one behind, otherwise it is stale (a concurrent commit's
        delta was missed) and is dropped for rebuild-on-next-read."""
        tid = info.id
        col_sig = tuple(c.id for c in info.public_columns())
        with self._lock:
            e = self._entries.get(tid)
        if e is None:
            return
        with e.lock:
            if e.version != new_version - 1 or e.col_sig != col_sig:
                self.invalidate(tid)
                return
            try:
                self._apply_locked(e, info, muts)
            except Exception:
                self.invalidate(tid)
                return
            e.version = new_version
            if e.delta_rows() > max(_COMPACT_MIN,
                                    len(e.handles) // _COMPACT_FRAC):
                self._compact_locked(e, info)

    def _apply_locked(self, e: _Entry, info: TableInfo, muts):
        from .. import tablecodec
        up_handles, up_rows = [], []
        for h, val in muts:
            e._tombstone(h)
            if val is not None:
                up_handles.append(h)
                up_rows.append(tablecodec.decode_row(val))
        e._invalidate_merge()
        if not up_handles:
            return
        cols = info.public_columns()
        chunk = rows_to_chunk(info, cols, up_handles, up_rows)
        seg_cols = {c.id: chunk.columns[i] for i, c in enumerate(cols)}
        seg = _Seg(np.array(up_handles, dtype=np.int64),
                   np.ones(len(up_handles), dtype=bool), seg_cols)
        e.segs.append(seg)
        si = len(e.segs) - 1
        for i, h in enumerate(up_handles):
            e.delta_pos[h] = (si, i)
        e.nrows += len(up_handles)

    def _compact_locked(self, e: _Entry, info: TableInfo):
        """Merge delta into a new handle-sorted base (memcpy-level: no row
        decode). Restores the sorted-handles invariant _tombstone relies on."""
        handles = e.merged_handles()
        order = np.argsort(handles, kind="stable")
        new_cols = {}
        for cid in e.col_sig:
            col = e.merged_column(cid, lambda _cid: None)
            if col is None:
                continue  # base predates this column; project() defaults it
            new_cols[cid] = Column(col.ftype, col.data[order],
                                   col.nulls[order])
        e.handles = handles[order]
        e.columns = new_cols
        e.base_live = None
        e.base_all_live = True
        e.segs = []
        e.delta_pos = {}
        e.nrows = len(e.handles)
        e._invalidate_merge()

    def install_bulk(self, info: TableInfo, columns: dict, handles: np.ndarray):
        """Bulk-load path (the Lightning physical-import role): install
        column arrays directly and mark the table version as current."""
        tid = info.id
        version = self.storage.mvcc.table_version(tid)
        col_sig = tuple(c.id for c in info.public_columns())
        e = _Entry(version, col_sig, columns, handles, len(handles))
        with self._lock:
            self._entries[tid] = e
        return e

    def project(self, entry: _Entry, col_infos, info: TableInfo) -> Chunk:
        out = []
        with entry.lock:  # per-entry: scans of other tables stay parallel
            for c in col_infos:
                col = entry.merged_column(c.id, lambda cid: None)
                if col is None:
                    # column added after materialization: all default/null
                    col = _default_column(c, entry.nrows)
                out.append(col)
        return Chunk(out)

    def handle_column(self, entry: _Entry) -> Column:
        with entry.lock:
            h = entry.merged_handles()
        return Column(FieldType(tp=TYPE_LONGLONG),
                      h, np.zeros(len(h), dtype=bool))


def _default_column(c, n: int) -> Column:
    from ..utils.chunk import np_dtype_for
    dt = np_dtype_for(c.ftype)
    if c.default_value is not None:
        if dt is object:
            data = np.full(n, c.default_value, dtype=object)
        else:
            data = np.full(n, c.default_value, dtype=dt)
        nulls = np.zeros(n, dtype=bool)
    else:
        data = (np.full(n, b"", dtype=object) if dt is object
                else np.zeros(n, dtype=dt))
        nulls = np.ones(n, dtype=bool)
    return Column(c.ftype, data, nulls)
