"""Host shuffle repartitioner (reference: executor/shuffle.go:77
ShuffleExec): hash-split rows into N worker shards keyed on partition
columns — every row of one partition group lands in exactly one shard —
then run a per-shard pipeline on a thread pool and scatter the results
back to the input row order.

The reference uses this to parallelize window / stream-agg / merge-join
over goroutine pipelines; here the shard workers are threads over
vectorized numpy kernels (which release the GIL in their hot loops), and
the device path remains the preferred engine for large inputs.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..utils.chunk import Chunk, Column


def shard_by_groups(gids: np.ndarray, n_shards: int) -> np.ndarray:
    """Group id per row → shard id per row (splitByItems hashing)."""
    return (gids % np.int64(n_shards)).astype(np.int64)


def shuffle_execute(chunk: Chunk, gids: np.ndarray, n_shards: int,
                    worker_fn) -> Chunk:
    """Partition `chunk` into shards by group id, run `worker_fn(sub_chunk)`
    per shard concurrently, and reassemble outputs into the original row
    order. worker_fn must return a Chunk whose rows parallel its input."""
    n = chunk.num_rows
    shard_ids = shard_by_groups(gids, n_shards)
    row_sets = [np.nonzero(shard_ids == s)[0] for s in range(n_shards)]
    row_sets = [rs for rs in row_sets if len(rs)]
    if len(row_sets) <= 1:
        return worker_fn(chunk)

    def run(rs):
        return rs, worker_fn(chunk.take(rs))

    with ThreadPoolExecutor(max_workers=len(row_sets),
                            thread_name_prefix="shuffle") as pool:
        parts = list(pool.map(run, row_sets))

    # scatter each shard's rows back to their original positions
    first = parts[0][1]
    out_cols = []
    for ci, proto in enumerate(first.columns):
        if proto.data.dtype == object:
            data = np.empty(n, dtype=object)
            data[:] = b""
        else:
            data = np.zeros(n, dtype=proto.data.dtype)
        nulls = np.zeros(n, dtype=bool)
        for rs, sub in parts:
            data[rs] = sub.columns[ci].data
            nulls[rs] = sub.columns[ci].nulls
        out_cols.append(Column(proto.ftype, data, nulls))
    return Chunk(out_cols)
