"""Fused device join+aggregate fragments — the engine's answer to the
reference's MPP fragment execution (planner/core/fragment.go cuts plans at
exchange boundaries; unistore/cophandler/mpp_exec.go runs join/agg
fragments storage-side). Here the whole scan→filter→join→…→aggregate tree
compiles into ONE jitted XLA program over HBM-resident base tables:

- joins whose build side is a base-table leaf use HOST-BUILT indexes
  (executor/join_index.py): the ordering work runs once per table version
  in numpy and the compiled program only gathers / binary-searches. A
  UNIQUE build side (every TPC-H fact⋈dim join) adds nothing to the
  output shape — the join is a gather with the probe side's exact
  capacity, no expansion pass and no overflow retry at all.
- non-unique indexed builds expand through a static-capacity CSR walk
  (cnt → cumsum → searchsorted), still sort-free on device.
- joins outside the index language (bushy subtrees, computed keys) fall
  back to the in-program lexsort + searchsorted expansion.
- intermediate results are row-index vectors into the base tables, not
  materialized rows: each join composes gathers lazily, and only the
  aggregate at the top reads actual column values.
- ONE host↔device round trip per execution (batched device_get of the
  aggregate outputs + overflow/validity scalars).
- expansion capacities and the aggregate group capacity are LEARNED: the
  exact totals observed on a run are remembered per fragment signature,
  so the overflow (or shrink-to-fit) recompile happens once per fragment
  ever, not once per session — and repeat executions jump straight to
  tight shapes (reference analog: the plan cache reusing learned sizes,
  planner/core/cache.go).

Supported fragment shape: equi-joins over table scans with pushed-down
filters, topped by a group-by aggregate. Join kinds:
- inner: anywhere in the tree (reorderable, any strategy);
- left outer: anywhere, with an indexed build side — the build side
  null-extends in-program (nullmaps thread the ~matched flags through the
  gathers), ON-residuals fold into the match on the unique-gather path;
- semi / anti: at the fragment ROOT only (probe-shaped existence counts —
  exactly the decorrelated EXISTS/IN plans), no residual conds.
Anything else raises DeviceUnsupported and falls back to the host path.
"""

from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..expression import phys_kind, K_FLOAT, K_STR
from ..expression.core import Column as ExprColumn, ScalarFunc as _SF
from ..ops import device as dev
from ..ops.device import DeviceUnsupported
from .device_exec import (
    _assemble_agg, _count_trace, _estimate_groups, _expr_sig,
    _plan_agg, _timed_jit, acquire_pipeline)
from .join_index import build_join_index


class _Leaf:
    __slots__ = ("leaf_id", "chunk", "conds", "offset", "ncols", "dcols",
                 "dcols_bucket", "dcols_epoch", "leaf_ids", "bucket")

    def __init__(self, leaf_id, chunk, conds, offset):
        self.leaf_id = leaf_id
        self.chunk = chunk
        self.conds = conds
        self.offset = offset
        self.ncols = chunk.num_cols
        self.dcols = None  # {local_idx: DeviceCol}
        self.dcols_bucket = None  # bucket the cached dcols were built at
        self.dcols_epoch = None  # device epoch the dcols were built under
        self.leaf_ids = frozenset((leaf_id,))
        self.bucket = None  # padded upload rows (ops/device.py bucket_rows)


class _JoinNode:
    def __init__(self, left, right, left_keys, right_keys, other_conds,
                 offset, kind="inner"):
        self.left = left
        self.right = right
        self.left_keys = left_keys    # exprs over left subtree schema
        self.right_keys = right_keys  # exprs over right subtree schema
        self.other_conds = other_conds
        self.offset = offset
        self.kind = kind          # inner | left | semi | anti
        self.ncols = left.ncols + right.ncols
        self.leaf_ids = left.leaf_ids | right.leaf_ids
        self.cap = 0            # static output capacity (set by _fill_caps)
        self.pos = 0            # index into the fragment's join list
        self.strategy = None    # None | (kind, side, JoinIndex)
        self.exp_cap = None     # requested capacity for expansion joins
        self.global_keys = False  # keys/conds already in global indices


def collect_tree(node):
    """executor node → (_Leaf | _JoinNode) tree; DeviceUnsupported if the
    shape is outside the fragment language."""
    from .exec_select import HashJoinExec, SelectionExec, TableScanExec

    leaves = []
    joins = []

    def walk(n, offset):
        if isinstance(n, TableScanExec):
            raw, conds = n.execute_raw()
            leaf = _Leaf(len(leaves), raw, list(conds), offset)
            leaves.append(leaf)
            return leaf
        if isinstance(n, SelectionExec) and isinstance(
                n.children[0], TableScanExec):
            raw, conds = n.children[0].execute_raw()
            leaf = _Leaf(len(leaves), raw,
                         list(conds) + list(n.plan.conds), offset)
            leaves.append(leaf)
            return leaf
        if isinstance(n, HashJoinExec):
            p = n.plan
            if p.kind not in ("inner", "left", "semi", "anti"):
                raise DeviceUnsupported(
                    f"{p.kind} join in device fragment")
            if not p.left_keys:
                raise DeviceUnsupported(
                    "cartesian join (no equi keys) in device fragment")
            _scan_shaped = (isinstance(n.children[1], TableScanExec)
                            or (isinstance(n.children[1], SelectionExec)
                                and isinstance(n.children[1].children[0],
                                               TableScanExec)))
            if (p.kind == "semi" and not _scan_shaped
                    and len(p.left_keys) == 1 and not p.other_conds):
                # mid-tree semi join over a non-scan build (the
                # uncorrelated IN→semi rewrite: an aggregate subquery):
                # materialize the build side — through its own
                # (device-capable) executor — and fold the membership
                # into an in-set filter on the probe subtree, restoring
                # the fused single-program fragment (Q18's shape).
                # Anti is excluded: NOT IN's NULL semantics differ from
                # a negated in-set.
                # Probe walks FIRST: a DeviceUnsupported below must not
                # discard an already-executed aggregate subquery (the
                # fallback would run it again — and tpu-mpp a third time)
                lnode = walk(n.children[0], offset)
                if (isinstance(lnode, _JoinNode)
                        and lnode.kind != "inner"):
                    # other_conds on an outer join are ON-residuals (part
                    # of the MATCH), not a WHERE filter — attaching the
                    # membership there would null-extend instead of drop
                    raise DeviceUnsupported(
                        "semi membership over a non-inner probe")
                values_chunk = n.children[1].execute()
                from .exec_select import eval_expr_to_column
                col = eval_expr_to_column(p.right_keys[0], values_chunk)
                vals = [None if col.nulls[i] else col.value_at(i)
                        for i in range(len(col.data))]
                from ..expression.builder import build_in_set
                cond = build_in_set(p.left_keys[0], vals,
                                    p.right_keys[0].ftype)
                if isinstance(lnode, _Leaf):
                    lnode.conds.append(cond)  # left-local schema == leaf's
                else:
                    # over the left subtree's schema, which starts at the
                    # node's own offset — exactly other_conds' convention
                    lnode.other_conds.append(cond)
                return lnode
            left = walk(n.children[0], offset)
            right = walk(n.children[1], offset + left.ncols)
            for lk, rk in zip(p.left_keys, p.right_keys):
                kl, kr = phys_kind(lk.ftype), phys_kind(rk.ftype)
                if K_STR in (kl, kr) or K_FLOAT in (kl, kr):
                    raise DeviceUnsupported("string/float join keys")
                if (lk.ftype.scale or 0) != (rk.ftype.scale or 0):
                    raise DeviceUnsupported("mismatched decimal key scales")
            jn = _JoinNode(left, right, list(p.left_keys),
                           list(p.right_keys), list(p.other_conds), offset,
                           kind=p.kind)
            jn.pos = len(joins)
            joins.append(jn)
            return jn
        raise DeviceUnsupported(
            f"{type(n).__name__} not supported in device fragment")

    root = walk(node, 0)
    if not joins:
        raise DeviceUnsupported("no joins in fragment")
    # semi/anti joins expose only their probe (left) schema, so upstream
    # column indices stay valid only when such a join is the fragment ROOT
    # (the aggregate's direct child — exactly the decorrelated-subquery
    # shape); anywhere deeper, sibling offsets would collide
    for jn in joins:
        if jn.kind in ("semi", "anti") and jn is not root:
            raise DeviceUnsupported("semi/anti join below fragment root")
        if jn.kind in ("semi", "anti") and jn.other_conds:
            # probe-shaped existence checks cannot evaluate residuals over
            # build columns (null-aware NOT IN etc.) — host path instead
            raise DeviceUnsupported("semi/anti join with residual conds")
    return root, leaves, joins


def _leaf_env(leaf, bucket=None):
    """Device columns for one leaf, cached on the host Columns. `bucket`
    pads the upload to a canonical row bucket (ops/device.py bucket_rows);
    the compiled fragment masks rows past the leaf's traced live count.
    The cache is keyed by the bucket it was built at: a declined earlier
    attempt (mpp, paged) must not leave exact-shape dcols that the
    bucketed resident path would silently trace against (to_device_col
    reuses/slices the underlying column upload, so a rebuild is cheap).
    It is also stamped with the DEVICE EPOCH (ops/residency.py): a
    backend fence or OOM evict-all mid-query invalidates the dict, so no
    pre-fence DeviceCol array can reach a post-fence dispatch.  The byte
    accounting rides on the underlying Column entries — the dict holds
    views/slices of the residency-tracked uploads, no extra HBM."""
    from ..ops import residency
    epoch = residency.device_epoch()
    if (leaf.dcols is None or leaf.dcols_bucket != bucket
            or leaf.dcols_epoch != epoch):
        leaf.dcols = {i: dev.to_device_col(c, bucket=bucket)
                      for i, c in enumerate(leaf.chunk.columns)}
        leaf.dcols_bucket = bucket
        leaf.dcols_epoch = epoch
    return leaf.dcols


def _leaf_meta(leaf):
    """Metadata-only DeviceCols for one leaf (no HBM transfer): what the
    expression compiler and agg planner read. The actual arrays reach the
    compiled program through `env` — whole columns on the resident path,
    page slices on the paged path."""
    return {i: dev.meta_device_col(c)[0]
            for i, c in enumerate(leaf.chunk.columns)}


def _global_dcols(leaves, meta_leaf_ids=frozenset(), buckets=None):
    """DeviceCol lookup keyed by global (join-output) column index.
    Leaves in `meta_leaf_ids` contribute metadata-only DeviceCols —
    their columns must never be uploaded whole (paged probe side)."""
    out = {}
    for leaf in leaves:
        dcs = (_leaf_meta(leaf) if leaf.leaf_id in meta_leaf_ids
               else _leaf_env(leaf, (buckets or {}).get(leaf.leaf_id)))
        for i, dc in dcs.items():
            out[leaf.offset + i] = dc
    return out


# ---------------------------------------------------------------------------
# join strategy planning (host-side, once per fragment)
# ---------------------------------------------------------------------------

def _leaf_key_cols(side, keys):
    """Host Columns for `keys` when `side` is a leaf and every key is a
    bare integer column of it; None otherwise."""
    if not isinstance(side, _Leaf):
        return None
    cols = []
    for k in keys:
        if not isinstance(k, ExprColumn) or not 0 <= k.idx < side.ncols:
            return None
        c = side.chunk.columns[k.idx]
        if (c.is_object()
                or not np.issubdtype(c.data.dtype, np.integer)):
            return None
        from ..storage.paged import is_paged
        if (is_paged(c)
                and side.chunk.num_rows * 16 > _dim_resident_budget()):
            # indexing (argsort + order arrays) a fact-sized memmap would
            # materialize it into RAM at PLAN time — a paged fact is only
            # ever the streamed probe, never a build index (an oversized
            # build instead goes through the hybrid partitioned path)
            return None
        cols.append(c)
    return cols


def _leaf_index(side, keys):
    """Host join index for `side` (a leaf with bare int keys), built over
    the rows passing the leaf's pushed-down filters — evaluated host-side
    with the host engine's own predicate path, so index membership matches
    the device mask exactly. None when out of the index language."""
    cols = _leaf_key_cols(side, keys)
    if cols is None:
        return None
    tag = ""
    mask_fn = None
    if side.conds:
        try:
            tag = ";".join(_expr_sig(c) for c in side.conds)
        except DeviceUnsupported:
            tag = ""
        if tag:
            def mask_fn():
                from .exec_select import eval_conds_mask
                return eval_conds_mask(side.conds, side.chunk)
    return build_join_index(cols, mask_fn=mask_fn, cache_tag=tag)


def _plan_strategy(jn):
    """Pick the cheapest build layout: a UNIQUE host index wins outright
    (gather join, probe-shaped output); a non-unique one still beats the
    in-program sort (CSR expansion); neither → device lexsort. The right
    (conventional build) side indexes first, and a unique hit returns
    before the left index is ever built — indexing the probe side would
    argsort the (typically huge) fact table for nothing.

    Non-inner kinds (left/semi/anti) preserve their LEFT side: the probe
    must be the left relation, so only right-side builds qualify."""
    ridx = _leaf_index(jn.right, jn.right_keys)
    if jn.kind != "inner":
        if ridx is None:
            return None
        return ("uniq" if ridx.unique else "expand", "right", ridx)
    if ridx is not None and ridx.unique:
        return ("uniq", "right", ridx)
    lidx = None
    if (not isinstance(jn.right, _Leaf) or not isinstance(jn.left, _Leaf)
            or ridx is None
            or jn.left.chunk.num_rows <= jn.right.chunk.num_rows):
        # only index the left side when it could plausibly win: a left
        # leaf LARGER than a non-unique right leaf is the fact side — a
        # fact-sized argsort would buy nothing over ('expand', 'right')
        lidx = _leaf_index(jn.left, jn.left_keys)
    if lidx is not None and lidx.unique:
        return ("uniq", "left", lidx)
    if ridx is not None:
        return ("expand", "right", ridx)
    if lidx is not None:
        return ("expand", "left", lidx)
    return None


def _reorder_fact_first(leaves, joins, assume_unique=frozenset()):
    """Rebuild the fragment's inner-join tree as a FACT-FIRST left-deep
    chain of unique-build gather joins. The device cost model inverts the
    host planner's greedy smallest-intermediate order (optimizer.py
    _greedy_join, reference rule_join_reorder.go): starting from the
    LARGEST leaf and attaching each dimension through its unique key makes
    every join a probe-shaped gather — the 'intermediate result' never
    grows, selectivity lives in the validity mask, and no expansion
    capacity (or overflow recompile) exists anywhere in the program. Inner
    equi-joins reorder freely, so this is pure engine-side physical
    planning.

    assume_unique: leaf ids whose whole-table index must NOT be built at
    plan time (it would exceed the residency budget — exactly the hybrid
    hash join's partitioned build, executor/hybrid_join.py).  Such a leaf
    joins the chain with a DEFERRED strategy ``("uniq", "right", None)``
    on bare-integer-column keys; the hybrid path builds per-partition
    indexes at execution and verifies uniqueness there.  A deferred node
    must never reach the resident/paged dispatch paths — device_join_agg
    raises if the hybrid attempt falls through.

    Returns (root, new_joins) with strategies assigned, or None when the
    chain can't be built expansion-free (multi-leaf key exprs, a
    disconnected graph, or a non-unique build somewhere) — the caller
    keeps the planner's tree and per-join strategy planning."""
    if len(joins) < 2 and not assume_unique:
        return None
    from ..sqltypes import FieldType, TYPE_LONGLONG
    by_id = {leaf.leaf_id: leaf for leaf in leaves}

    def cover_of(e):
        used = set()
        e.columns_used(used)
        ids = set()
        for g in used:
            for leaf in leaves:
                if leaf.offset <= g < leaf.offset + leaf.ncols:
                    ids.add(leaf.leaf_id)
                    break
        return ids

    pairs = []   # [gl_expr, gr_expr, l_leaf, r_leaf]
    others = []  # [g_expr, cover_set]
    for jn in joins:
        off_l = 0 if jn.global_keys else jn.left.offset
        off_r = 0 if jn.global_keys else jn.right.offset
        off_o = 0 if jn.global_keys else jn.offset
        for lk, rk in zip(jn.left_keys, jn.right_keys):
            gl = _shift_expr(lk, off_l)
            gr = _shift_expr(rk, off_r)
            cl, cr = cover_of(gl), cover_of(gr)
            if len(cl) != 1 or len(cr) != 1:
                return None
            pairs.append((gl, gr, next(iter(cl)), next(iter(cr))))
        for c in jn.other_conds:
            g = _shift_expr(c, off_o)
            others.append((g, cover_of(g)))

    remaining = set(by_id)
    start = max(remaining, key=lambda i: by_id[i].chunk.num_rows)
    remaining.discard(start)
    spine_ids = {start}
    cur = by_id[start]
    new_joins = []
    pend_pairs = list(pairs)
    pend_others = list(others)
    bool_ft = FieldType(tp=TYPE_LONGLONG)
    while remaining:
        cands = {}  # leaf_id -> [(pair, spine_expr, leaf_expr)]
        for p in pend_pairs:
            gl, gr, cl, cr = p
            if cl in spine_ids and cr in remaining:
                cands.setdefault(cr, []).append((p, gl, gr))
            elif cr in spine_ids and cl in remaining:
                cands.setdefault(cl, []).append((p, gr, gl))
        if not cands:
            return None
        best = None
        for lid, kps in cands.items():
            leaf = by_id[lid]
            if lid in assume_unique:
                # deferred partition-indexed build: accept on bare int
                # leaf columns without materializing the whole index
                local = [_shift_expr(lx, -leaf.offset)
                         for _p, _s, lx in kps]
                if any(not isinstance(e, ExprColumn)
                       or not 0 <= e.idx < leaf.ncols
                       or leaf.chunk.columns[e.idx].is_object()
                       or not np.issubdtype(
                           leaf.chunk.columns[e.idx].data.dtype,
                           np.integer)
                       for e in local):
                    continue
                key = (leaf.chunk.num_rows, lid)
                if best is None or key < best[0]:
                    best = (key, lid, kps, None)
                continue
            # the index builder addresses the leaf's LOCAL schema; the
            # chain's key exprs are global — rebase before the lookup
            idx = _leaf_index(leaf, [_shift_expr(lx, -leaf.offset)
                                     for _p, _s, lx in kps])
            if idx is None or not idx.unique:
                continue
            key = (leaf.chunk.num_rows, lid)
            if best is None or key < best[0]:
                best = (key, lid, kps, idx)
        if best is None:
            return None  # a non-unique build would expand: keep the
            #              planner's tree instead
        _key, lid, kps, idx = best
        leaf = by_id[lid]
        jn = _JoinNode(cur, leaf,
                       [s for _p, s, _l in kps], [l for _p, _s, l in kps],
                       [], 0)
        jn.global_keys = True
        jn.strategy = ("uniq", "right", idx)
        spine_ids.add(lid)
        remaining.discard(lid)
        consumed = {id(p) for p, _s, _l in kps}
        rest = []
        for p in pend_pairs:
            gl, gr, cl, cr = p
            if id(p) in consumed:
                continue
            if cl in spine_ids and cr in spine_ids:
                # an equi-cond between two already-joined leaves (Q5's
                # c_nationkey = s_nationkey shape) becomes a plain filter
                # at the first node covering both sides
                jn.other_conds.append(_SF("eq", [gl, gr], bool_ft))
            else:
                rest.append(p)
        pend_pairs = rest
        keep_o = []
        for o in pend_others:
            g, cov = o
            if cov <= spine_ids:
                jn.other_conds.append(g)
            else:
                keep_o.append(o)
        pend_others = keep_o
        jn.pos = len(new_joins)
        new_joins.append(jn)
        cur = jn
    if pend_pairs or pend_others:
        return None  # anything unplaced means the rewrite lost a predicate
    return cur, new_joins


def _strategy_sig(jn):
    st = jn.strategy
    if st is None:
        return f"S{jn.pos}:-"
    kind, side, idx = st
    # n_valid is a TRACED runtime input (it rides in jidx next to the
    # lookup arrays) and the arrays pad to geometric buckets, so the
    # signature carries only the BUCKETED shape identity (rows_len +
    # dtype) and the structural unique flag — a within-bucket build-side
    # INSERT rebuilds the cheap numpy index and reuses the compiled
    # program with zero new XLA compiles (the last recompile trigger,
    # ROADMAP item 1)
    return (f"S{jn.pos}:{kind}/{side}/{idx.kind}/{idx.packs}/"
            f"{int(idx.unique)}/{idx.rows_len}/{idx.rows.dtype}")


#: learned exact sizes per fragment: (sig, join_pos) → last observed match
#: total; (sig, "agg") → last observed group count. In-process, LRU-bounded
#: like _PIPE_CACHE (sig strings embed data-dependent packs, so stale data
#: versions must age out); repeat fragments (bench steady state, plan-cache
#: hits) start tight and never pay a discovery recompile again.
import collections as _collections

_CAP_STORE: "_collections.OrderedDict" = _collections.OrderedDict()
_CAP_STORE_MAX = 4096


def _cap_store_put(key, val):
    _CAP_STORE[key] = val
    _CAP_STORE.move_to_end(key)
    if len(_CAP_STORE) > _CAP_STORE_MAX:
        _CAP_STORE.popitem(last=False)


def _null_extend(nulls, bidx_map, hit):
    """Left-join null extension: every build-side leaf's columns read as
    NULL on rows without a surviving match (shared by the uniq-gather and
    CSR-expand paths so their semantics can never diverge)."""
    for lid in bidx_map:
        prev = nulls.get(lid)
        nulls[lid] = ~hit if prev is None else (prev | ~hit)


def _join_expand(bk, bvalid, pk, pvalid, cap):
    """Static-capacity inner equi-join expansion (device-sort fallback).
    Returns (probe_slot, build_slot, valid, total): slot arrays index the
    *input relations* (length cap; garbage where ~valid).

    Join keys are arbitrary user int64 columns, so invalid rows are pushed
    behind ALL valid rows by a (validity, key) lexsort and the searchsorted
    bounds are clamped to the valid prefix — a plain int64.max sentinel
    would interleave genuine max-valued keys with padding and overcount."""
    nb = bk.shape[0]
    npr = pk.shape[0]
    nb_valid = jnp.sum(bvalid)
    order = jnp.lexsort((bk, ~bvalid))  # valid-first, then key-sorted
    in_prefix = jnp.arange(nb) < nb_valid
    sb = jnp.where(in_prefix, bk[order], jnp.iinfo(jnp.int64).max)
    lo = jnp.minimum(jnp.searchsorted(sb, pk, side="left"), nb_valid)
    hi = jnp.minimum(jnp.searchsorted(sb, pk, side="right"), nb_valid)
    cnt = jnp.where(pvalid, hi - lo, 0)
    cum = jnp.concatenate([jnp.zeros(1, dtype=cnt.dtype), jnp.cumsum(cnt)])
    total = cum[-1]
    pos = jnp.arange(cap)
    pi = jnp.clip(jnp.searchsorted(cum, pos, side="right") - 1, 0, npr - 1)
    valid = pos < total
    within = pos - cum[pi]
    bpos = lo[pi] + within
    bi = order[jnp.clip(bpos, 0, jnp.maximum(nb - 1, 0))]
    valid = valid & bvalid[bi] & pvalid[pi]
    # report the EXACT required size, not a boolean: an overflow retry can
    # then jump straight to next_pow2(total) instead of doubling — each
    # doubling is a full XLA recompile, and starting from a tiny dimension
    # table the doublings (12+ recompiles at TPC-H scale) dwarf the query
    return pi, bi, valid, total


def _combined_join_keys(lkds, lknulls, lvalid, rkds, rknulls, rvalid):
    """Fold multi-column equi-join keys into ONE int64 key per side using
    DATA-DEPENDENT range packing: per key column, [min, max] over both
    sides' valid rows gives a span; combined = Σ (kᵢ - mnᵢ)·Π spanⱼ.
    Dynamic VALUES are free under jit (only shapes must be static), so no
    host round trip and no host-side factorization (reference: hash join
    builds a multi-column hash key, executor/join.go:192).

    Returns (pk, pvalid, bk, bvalid, span_ovf) — span_ovf is a traced
    flag set when Π span exceeds int64 (caller must fall back, not
    retry)."""
    pvalid, bvalid = lvalid, rvalid
    for nl in lknulls:
        pvalid = pvalid & ~nl
    for nl in rknulls:
        bvalid = bvalid & ~nl
    if len(lkds) == 1:
        return (lkds[0].astype(jnp.int64), pvalid,
                rkds[0].astype(jnp.int64), bvalid,
                jnp.zeros((), dtype=bool))
    big = jnp.iinfo(jnp.int64).max
    small = jnp.iinfo(jnp.int64).min
    pk = jnp.zeros(lvalid.shape[0], dtype=jnp.int64)
    bk = jnp.zeros(rvalid.shape[0], dtype=jnp.int64)
    total = jnp.ones((), dtype=jnp.float64)
    for lk, rk in zip(lkds, rkds):
        lk = lk.astype(jnp.int64)
        rk = rk.astype(jnp.int64)
        mn = jnp.minimum(jnp.min(jnp.where(pvalid, lk, big)),
                         jnp.min(jnp.where(bvalid, rk, big)))
        mx = jnp.maximum(jnp.max(jnp.where(pvalid, lk, small)),
                         jnp.max(jnp.where(bvalid, rk, small)))
        mn = jnp.minimum(mn, mx)  # both-empty guard
        # guard span in float64 FIRST: `mx - mn + 1` wraps in int64 when a
        # key column spans more than half the int64 range, which would
        # collapse the span to 1 and silently defeat the overflow flag
        span_f = jnp.maximum(
            mx.astype(jnp.float64) - mn.astype(jnp.float64) + 1.0, 1.0)
        total = total * span_f
        span = jnp.maximum(mx - mn + 1, 1)
        pk = pk * span + jnp.where(pvalid, lk - mn, 0)
        bk = bk * span + jnp.where(bvalid, rk - mn, 0)
    return pk, pvalid, bk, bvalid, total > jnp.asarray(2.0**62)


def _pack_probe(kds, knulls, pvalid, packs):
    """Probe-side key folding with the BUILD index's static (min, span)
    per column. Rows whose key falls outside the build range (or is NULL)
    can't match; they're excluded via `ok` and clamped so the packing
    arithmetic never overflows."""
    ok = pvalid
    key = jnp.zeros(pvalid.shape, dtype=jnp.int64)
    for d, nl, (mn, span) in zip(kds, knulls, packs):
        v = d.astype(jnp.int64) - mn
        ok = ok & ~nl & (v >= 0) & (v < span)
        key = key * span + jnp.clip(v, 0, span - 1)
    return key, ok


def compile_fragment(root, leaves, joins, agg_plan, agg_conds, caps,
                     capacity, key_pack, agg_meta, compact_cap=None,
                     raw_tail=False, strategies=None):
    """Build the jitted end-to-end program. caps: per-join static
    capacities aligned with `joins`. Returns jitted fn(env, jidx, n_lives)
    where env is {global_col: (data, nulls)} and jidx is a per-join tuple
    of host-index device arrays (passed as arguments, not baked, so a data
    refresh with unchanged shapes reuses the compiled program).

    n_lives: per-leaf traced live-row counts, ordered by leaf_id. Env
    arrays may be padded past them — bucket-padded resident uploads, the
    paged probe's last page — and every leaf masks its rows at
    `arange(n) < n_lives[leaf_id]`, so padding can never survive the scan
    filter, probe a join, or reach the aggregate. Traced scalars: a
    within-bucket row-count change re-dispatches without recompiling.

    compact_cap: when set (CPU backend, learned from a prior run), the
    post-join/filter rows are scatter-compacted to this static width
    before the aggregate — a fact-shaped fragment output with a sparse
    validity mask (the price of the gather-join design) would otherwise
    drag the full fact length through the group-by sort.

    raw_tail: stop BEFORE the in-kernel aggregate and return the evaluated
    (key_cols, key_nulls, val_cols, val_nulls, mask) row arrays instead.
    CPU-backend paged paths aggregate those in numpy: the XLA-CPU
    group-by pays in the packed-key SPAN (dense buckets) or a serial
    sort, both dwarfing a host reduceat over one page (measured: 26s of
    SF10 Q3's device time was 15 pages of in-kernel scatter-agg against
    a 67M-slot orderkey space). The join/filter/expression work — the
    part XLA is good at — stays fused in the program."""
    for jn, cap in zip(joins, caps):
        jn.cap = cap
    if strategies is None:
        # snapshot NOW: the traced body must never read the mutable
        # .strategy slot at dispatch/trace time — a deferred background
        # build (compile service) can trace long after the originating
        # execution restored or replaced it (the hybrid join swaps a
        # partition-shaped stub in and out around its run)
        strategies = tuple(jn.strategy for jn in joins)

    # metadata-only planning view: compiling expressions must not upload
    # any column (the paged probe's columns never transfer whole)
    leaf_metas = [_leaf_meta(leaf) for leaf in leaves]
    dcols = {leaf.offset + i: dc
             for leaf, m in zip(leaves, leaf_metas) for i, dc in m.items()}
    # compile every expression up-front (host-side planning); leaf conds
    # are written against the scan's LOCAL schema → rebase to global
    leaf_cond_fns = [
        [dev.compile_expr(_shift_expr(c, leaf.offset),
                          {leaf.offset + i: dc
                           for i, dc in leaf_metas[leaf.leaf_id].items()})
         for c in leaf.conds] for leaf in leaves]
    # key/other-cond/agg expressions are compiled against global offsets
    # (reordered nodes carry globally-indexed exprs already)
    for jn in joins:
        off_l = 0 if jn.global_keys else jn.left.offset
        off_r = 0 if jn.global_keys else jn.right.offset
        off_o = 0 if jn.global_keys else jn.offset
        jn._lk_fns = [dev.compile_expr(_shift_expr(k, off_l), dcols)
                      for k in jn.left_keys]
        jn._rk_fns = [dev.compile_expr(_shift_expr(k, off_r), dcols)
                      for k in jn.right_keys]
        jn._oc_fns = [dev.compile_expr(_shift_expr(c, off_o), dcols)
                      for c in jn.other_conds]
    cond_fns = [dev.compile_expr(c, dcols) for c in agg_conds]
    key_fns, val_plan, agg_ops, slots = agg_meta

    def run(env, jidx, n_lives):
        _count_trace()

        # env keyed by global column index → (data, nulls) on device
        def leaf_rel(leaf):
            # row count off the leaf's first env-present column (a pruned
            # env — paged path — carries only the fragment's used columns)
            n = next(env[leaf.offset + i][0].shape[0]
                     for i in range(leaf.ncols)
                     if leaf.offset + i in env)
            if leaf_cond_fns[leaf.leaf_id]:
                mask = None
                for f in leaf_cond_fns[leaf.leaf_id]:
                    d, nl = f(env)
                    m = (d != 0) & ~nl
                    mask = m if mask is None else mask & m
                mask = jnp.broadcast_to(mask, (n,))
                mask = mask & (jnp.arange(n) < n_lives[leaf.leaf_id])
            else:
                mask = jnp.arange(n) < n_lives[leaf.leaf_id]
            return {leaf.leaf_id: jnp.arange(n)}, mask

        overflows = []
        span_ovfs = []

        def gather_env(idxmap, valid, node, nullmaps=None):
            """env of gathered (relation-space) columns for `node`'s
            subtree, keyed by global column index. Unused columns' gathers
            are dead code XLA eliminates — laziness here is free.
            nullmaps[leaf_id] marks rows where that leaf contributed no
            match (left-join null extension): its columns read as NULL."""
            out = {}
            for leaf in leaves:
                if leaf.leaf_id in idxmap and leaf.leaf_id in node.leaf_ids:
                    idx = idxmap[leaf.leaf_id]
                    ext = (nullmaps or {}).get(leaf.leaf_id)
                    for i in range(leaf.ncols):
                        hit = env.get(leaf.offset + i)
                        if hit is None:  # pruned (unused) column
                            continue
                        d, nl = hit
                        nli = nl[idx]
                        if ext is not None:
                            nli = nli | ext
                        out[leaf.offset + i] = (d[idx], nli)
            return out

        def eval_indexed(node, lidx_map, lvalid, lnull, ridx_map, rvalid,
                         rnull):
            """Host-indexed join paths ('uniq' gather / 'expand' CSR), for
            inner / left / semi / anti kinds. Output row space:
            probe-shaped for uniq and for semi/anti (existence is a count,
            never an expansion), CSR-expanded otherwise."""
            kind, side, idx = strategies[node.pos]
            jkind = node.kind
            if side == "right":
                pidx_map, pvalid, pside = lidx_map, lvalid, node.left
                bidx_map, bvalid = ridx_map, rvalid
                pnull, bnull = lnull, rnull
                key_fns_p = node._lk_fns
            else:
                pidx_map, pvalid, pside = ridx_map, rvalid, node.right
                bidx_map, bvalid = lidx_map, lvalid
                pnull, bnull = rnull, lnull
                key_fns_p = node._rk_fns
            penv = gather_env(pidx_map, pvalid, pside, pnull)
            n_probe = pvalid.shape[0]
            kds, knulls = zip(*[
                dev.broadcast_1d(*f(penv), n_probe) for f in key_fns_p])
            key, ok = _pack_probe(kds, knulls, pvalid, idx.packs)
            # nv is TRACED (a same-bucket index refresh re-dispatches
            # without retracing); every bound derived from it is traced
            a0, a1, nv = jidx[node.pos]
            safe_hi = jnp.maximum(nv - 1, 0)
            if idx.kind == "dense":
                k_c = jnp.clip(key, 0, idx.span - 1)
                pos0 = a0[k_c].astype(jnp.int64)
                cnt = jnp.where(ok, (a0[k_c + 1] - a0[k_c]).astype(jnp.int64),
                                0)
            else:
                lo = jnp.searchsorted(a0, key, side="left")
                lo_c = jnp.clip(lo, 0, a0.shape[0] - 1)
                pos0 = jnp.minimum(lo, nv).astype(jnp.int64)
                if kind == "uniq":
                    cnt = jnp.where(ok & (lo < nv) & (a0[lo_c] == key), 1, 0)
                else:
                    hi = jnp.searchsorted(a0, key, side="right")
                    cnt = jnp.where(
                        ok, jnp.minimum(hi, nv) - jnp.minimum(lo, nv), 0)

            if jkind in ("semi", "anti") and kind != "uniq":
                # existence only: probe-shaped regardless of match counts
                hit = cnt > 0
                valid = pvalid & (hit if jkind == "semi" else ~hit)
                overflows.append(jnp.sum(valid))
                return dict(pidx_map), valid, dict(pnull)

            if kind == "uniq":
                bi = a1[jnp.clip(pos0, 0, safe_hi)].astype(jnp.int64)
                hit = (cnt > 0) & bvalid[bi]
                if node._oc_fns and jkind == "left":
                    # ON-clause residuals are part of the MATCH for outer
                    # joins — evaluate on the joined candidate row first
                    cand_idx = dict(pidx_map)
                    for lid, v in bidx_map.items():
                        cand_idx[lid] = v[bi]
                    cand_null = dict(pnull)
                    for lid, v in bnull.items():
                        cand_null[lid] = v[bi]
                    jenv = gather_env(cand_idx, hit, node, cand_null)
                    for f in node._oc_fns:
                        d, nl = f(jenv)
                        hit = hit & (d != 0) & ~nl
                if jkind == "semi":
                    overflows.append(jnp.sum(pvalid & hit))
                    return dict(pidx_map), pvalid & hit, dict(pnull)
                if jkind == "anti":
                    overflows.append(jnp.sum(pvalid & ~hit))
                    return dict(pidx_map), pvalid & ~hit, dict(pnull)
                valid = pvalid if jkind == "left" else (pvalid & hit)
                out = dict(pidx_map)
                nulls = dict(pnull)
                for lid, v in bidx_map.items():
                    out[lid] = v[bi]
                for lid, v in bnull.items():
                    nulls[lid] = v[bi]
                if jkind == "left":
                    _null_extend(nulls, bidx_map, hit)
                overflows.append(jnp.sum(valid))  # ≤ cap by construction
                return out, valid, nulls

            # CSR expansion (non-unique build)
            cap = node.cap
            if jkind == "left":
                # unmatched probe rows emit exactly one null-extended row
                cnt_eff = jnp.where(pvalid, jnp.maximum(cnt, 1), 0)
            else:
                cnt_eff = cnt
            cum = jnp.concatenate(
                [jnp.zeros(1, dtype=jnp.int64), jnp.cumsum(cnt_eff)])
            total = cum[-1]
            posn = jnp.arange(cap)
            pi = jnp.clip(jnp.searchsorted(cum, posn, side="right") - 1,
                          0, n_probe - 1)
            within = posn - cum[pi]
            real = within < cnt[pi]  # False on a left join's null emission
            bpos = pos0[pi] + jnp.minimum(within,
                                          jnp.maximum(cnt[pi] - 1, 0))
            bi = a1[jnp.clip(bpos, 0, safe_hi)].astype(jnp.int64)
            hit = real & bvalid[bi]
            if jkind == "left":
                valid = (posn < total) & pvalid[pi]
            else:
                valid = (posn < total) & hit & pvalid[pi]
            overflows.append(total)
            out = {k: v[pi] for k, v in pidx_map.items()}
            nulls = {k: v[pi] for k, v in pnull.items()}
            for lid, v in bidx_map.items():
                out[lid] = v[bi]
            for lid, v in bnull.items():
                nulls[lid] = v[bi]
            if jkind == "left":
                _null_extend(nulls, bidx_map, hit)
            return out, valid, nulls

        def eval_node(node):
            if isinstance(node, _Leaf):
                idxmap, mask = leaf_rel(node)
                return idxmap, mask, {}
            # children always evaluate left-then-right so the overflow
            # list order matches the `joins` list (postorder walk)
            lidx, lvalid, lnull = eval_node(node.left)
            ridx, rvalid, rnull = eval_node(node.right)
            if strategies[node.pos] is not None:
                idxmap, valid, nullmaps = eval_indexed(
                    node, lidx, lvalid, lnull, ridx, rvalid, rnull)
                if node.kind == "left":
                    return idxmap, valid, nullmaps  # conds folded already
            else:
                if node.kind != "inner":
                    raise DeviceUnsupported(
                        f"{node.kind} join needs an indexed build side")
                lenv = gather_env(lidx, lvalid, node.left, lnull)
                renv = gather_env(ridx, rvalid, node.right, rnull)
                lkds, lknulls = zip(*[
                    dev.broadcast_1d(*f(lenv), lvalid.shape[0])
                    for f in node._lk_fns])
                rkds, rknulls = zip(*[
                    dev.broadcast_1d(*f(renv), rvalid.shape[0])
                    for f in node._rk_fns])
                pk_d, pvalid, bk_d, bvalid, sovf = _combined_join_keys(
                    lkds, lknulls, lvalid, rkds, rknulls, rvalid)
                span_ovfs.append(sovf)
                pi, bi, valid, total = _join_expand(
                    bk_d, bvalid, pk_d, pvalid, node.cap)
                overflows.append(total)
                idxmap = {k: v[pi] for k, v in lidx.items()}
                idxmap.update({k: v[bi] for k, v in ridx.items()})
                nullmaps = {k: v[pi] for k, v in lnull.items()}
                nullmaps.update({k: v[bi] for k, v in rnull.items()})
            if node._oc_fns and node.kind == "inner":
                jenv = gather_env(idxmap, valid, node, nullmaps)
                for f in node._oc_fns:
                    d, nl = f(jenv)
                    valid = valid & (d != 0) & ~nl
            return idxmap, valid, nullmaps

        idxmap, valid, nullmaps = eval_node(root)
        fenv = gather_env(idxmap, valid, root, nullmaps)
        mask = valid
        for f in cond_fns:
            d, nl = f(fenv)
            mask = mask & (d != 0) & ~nl
        kept_total = jnp.sum(mask)
        if compact_cap is not None:
            # scatter-compact kept rows to the front: the aggregate then
            # sorts/buckets compact_cap rows instead of the fact length.
            # kept_total > compact_cap is detected host-side (extras) and
            # recompiled — same contract as a join-capacity overflow.
            cidx = jnp.cumsum(mask) - 1
            tgt = jnp.where(mask, cidx, compact_cap)
            sel = jnp.zeros(compact_cap, dtype=jnp.int64).at[tgt].set(
                jnp.arange(mask.shape[0]), mode="drop")
            fenv = {k: (d[sel], nl[sel]) for k, (d, nl) in fenv.items()}
            mask = jnp.arange(compact_cap) < kept_total
        n_out = mask.shape[0]
        key_cols, key_nulls = [], []
        for f in key_fns:
            d, nl = dev.broadcast_1d(*f(fenv), n_out)
            key_cols.append(d.astype(jnp.int64))
            key_nulls.append(nl)
        if not key_cols:
            key_cols = [jnp.zeros(n_out, dtype=jnp.int64)]
            key_nulls = [jnp.zeros(n_out, dtype=bool)]
        val_cols, val_nulls = [], []
        for f, conv in val_plan:
            d, nl = dev.broadcast_1d(*f(fenv), n_out)
            if conv == "int":
                d = d.astype(jnp.int64)
            val_cols.append(d)
            val_nulls.append(nl)
        if raw_tail:
            raw = (tuple(key_cols), tuple(key_nulls), tuple(val_cols),
                   tuple(val_nulls), mask)
            return raw, tuple(overflows), tuple(span_ovfs), kept_total
        agg_out = dev._agg_impl(tuple(key_cols), tuple(key_nulls),
                                tuple(val_cols), tuple(val_nulls), mask,
                                n_keys=len(key_cols),
                                agg_ops=tuple(agg_ops),
                                capacity=capacity, pack=key_pack)
        return agg_out, tuple(overflows), tuple(span_ovfs), kept_total

    return _timed_jit(run)


def _shift_expr(e, offset):
    """Rebase column refs from subtree-local to global column indices."""
    if offset == 0:
        return e
    return e.transform_columns(
        lambda c: ExprColumn(c.idx + offset, c.ftype, name=c.name))


def _fill_caps(node, sig):
    """Bottom-up static output capacities. Unique-indexed joins inherit
    the probe side's capacity exactly. Expansion joins take (in order):
    the retry-adjusted/learned size, a stats-free estimate from the build
    index's average match count, or (device-sort fallback) the FK-join
    upper heuristic — a key-FK join emits about as many rows as its
    LARGER input, composed bottom-up over RAW leaf sizes. Estimates
    deliberately overshoot: undershoot costs a full recompile (minutes
    over a device tunnel), overshoot only pads the kernels; the learned
    store tightens the shapes from the second compile on."""
    if isinstance(node, _Leaf):
        # BUCKET space, not the live row count: probe-shaped capacities
        # flow into the compiled program's static shapes and the pipeline
        # cache key, and must stay stable across within-bucket deltas
        return node.bucket or node.chunk.num_rows

    lc = _fill_caps(node.left, sig)
    rc = _fill_caps(node.right, sig)
    st = node.strategy
    if node.kind in ("semi", "anti") or (
            st is not None and st[0] == "uniq"):
        # probe-shaped: semi/anti are existence counts; uniq is a gather
        node.cap = lc if (node.kind != "inner"
                          or st[1] == "right") else rc
        return node.cap
    if node.exp_cap is None:
        learned = _CAP_STORE.get((sig, node.pos))
        if learned is not None:
            node.exp_cap = dev.next_pow2(max(learned, 8))
        elif st is not None:
            probe_cap = lc if st[1] == "right" else rc
            est = int(probe_cap * st[2].avg_cnt * 1.5)
            if node.kind == "left":
                est += probe_cap  # every unmatched probe row still emits
            node.exp_cap = dev.next_pow2(max(est, 1024))
        else:
            def fk_est(nd):
                if isinstance(nd, _Leaf):
                    return max(nd.chunk.num_rows, 8)
                return max(fk_est(nd.left), fk_est(nd.right))
            node.exp_cap = dev.next_pow2(fk_est(node))
    node.cap = node.exp_cap
    return node.cap


def device_join_agg(agg_plan, agg_conds, child_exec, ctx):
    """Entry: compile + run the fused join+agg fragment for a HashAgg whose
    child is a join tree over table scans. Raises DeviceUnsupported when
    out of scope (caller falls back to the host executors)."""
    from ..utils import failpoint
    # chaos/supervisor hook: a `sleep(...)` here models a backend hang at
    # the join-fragment boundary, `panic` a runtime failure
    failpoint.inject("device-join-exec")
    from .device_exec import want_device
    root, leaves, joins = collect_tree(child_exec)
    if not want_device(ctx, max(leaf.chunk.num_rows for leaf in leaves)):
        raise DeviceUnsupported("below device threshold")
    all_inner = all(jn.kind == "inner" for jn in joins)
    reordered = _reorder_fact_first(leaves, joins) if all_inner else None
    hybrid_deferred = None
    if reordered is None and all_inner:
        # a build side too big to index whole (the paged-budget guard in
        # _leaf_key_cols) may still chain with a DEFERRED strategy — the
        # hybrid hash join partitions it at execution time
        over = _over_budget_builds(leaves, joins, agg_plan, agg_conds)
        if len(over) == 1:
            reordered = _reorder_fact_first(leaves, joins,
                                            assume_unique=over)
            if reordered is not None:
                hybrid_deferred = next(iter(over))
    if reordered is not None:
        root, joins = reordered  # strategies assigned (all uniq)
    else:
        for jn in joins:
            jn.strategy = _plan_strategy(jn)
        for jn in joins:
            if jn.kind == "inner":
                continue
            if jn.strategy is None:
                raise DeviceUnsupported(
                    f"{jn.kind} join needs an indexed build side")
            if (jn.kind == "left" and jn.other_conds
                    and jn.strategy[0] != "uniq"):
                # ON-residuals fold into the match only on the gather
                # path; dropping them on the CSR path would change results
                raise DeviceUnsupported(
                    "left join residual conds need a unique build")

    # paged-probe dispatch: a disk-backed (or huge) fact side must stream
    # pages — uploading it whole would exceed HBM (and at SF100, RAM)
    from ..storage.paged import chunk_is_paged, DEFAULT_PAGE_ROWS
    probe = _probe_spine(root)
    any_paged = any(chunk_is_paged(leaf.chunk) for leaf in leaves)
    pageable = (isinstance(probe, _Leaf) and all(
        jn.kind == "inner" and jn.strategy is not None
        and jn.strategy[0] == "uniq"
        and jn.strategy[1] == "right" for jn in joins))
    if any_paged and not pageable:
        # the resident path would read entire memmaps into RAM + HBM; a
        # fragment shape outside the paged language goes to the host
        # executors, which stream
        raise DeviceUnsupported("paged leaf outside streamed-probe language")
    if pageable:
        # hybrid hash join: a build side larger than the residency budget
        # radix-partitions — fitting partitions stay device-resident,
        # overflow spills to host pages and probes CONCURRENTLY on a
        # supervisor worker — instead of surrendering the whole fragment
        hj = _maybe_hybrid(root, leaves, joins, probe, agg_plan,
                           agg_conds, ctx, deferred=hybrid_deferred)
        if hj is not None:
            return hj
    if hybrid_deferred is not None:
        # the deferred (partition-indexed) strategy exists ONLY for the
        # hybrid path; the resident/paged dispatchers would crash on its
        # None index — degrade to the host engine instead
        raise DeviceUnsupported(
            "over-budget build side outside the hybrid join language")
    if pageable:
        paged = chunk_is_paged(probe.chunk)
        if any_paged and not paged:
            raise DeviceUnsupported("paged build-side leaf (resident "
                                    "uploads of a disk table are barred)")
        try:
            page_rows = int(ctx.get_sysvar("tidb_device_stream_rows"))
        except Exception:
            page_rows = 0
        stream_off = page_rows < 0  # -1: resident inputs never auto-page
        if page_rows <= 0:
            page_rows = DEFAULT_PAGE_ROWS
        if paged or (not stream_off and probe.chunk.num_rows
                     > max(_PAGED_MIN_ROWS, page_rows * 4)):
            try:
                return _paged_join_agg(root, leaves, joins, probe, agg_plan,
                                       agg_conds, ctx, page_rows)
            except DeviceUnsupported:
                if paged:
                    # whole-table upload of a disk-resident fact is not a
                    # fallback — let the host path stream it instead
                    raise
    # canonical row buckets per leaf: uploads pad to the bucket, the
    # program masks each leaf at its traced live count — a delta append
    # that stays inside the bucket reuses the compiled fragment
    per_double = dev.shape_buckets(ctx)
    buckets = {}
    for leaf in leaves:
        leaf.bucket = buckets[leaf.leaf_id] = dev.bucket_rows(
            leaf.chunk.num_rows, per_double)
    dcols = _global_dcols(leaves, buckets=buckets)
    agg_meta_full = _plan_agg(agg_plan, dcols)
    key_fns, key_meta, key_pack, val_plan, agg_ops, slots = agg_meta_full
    agg_meta = (key_fns, val_plan, agg_ops, slots)

    # env: every base column once, device-resident (bucket-padded)
    env = {}
    for leaf in leaves:
        for i, dc in _leaf_env(leaf, buckets[leaf.leaf_id]).items():
            env[leaf.offset + i] = (dc.data, dc.nulls)
    n_lives = tuple(np.int64(leaf.chunk.num_rows) for leaf in leaves)

    sig = fragment_sig(leaves, joins, agg_conds, agg_plan)
    dict_refs = tuple(dc.dictionary for dc in dcols.values()
                      if dc.dictionary is not None)
    jidx = tuple(jn.strategy[2].device_arrays() if jn.strategy is not None
                 else () for jn in joins)

    n_frag = _fill_caps(root, sig)
    learned_ng = _CAP_STORE.get((sig, "agg"))
    if learned_ng is not None:
        capacity = dev.next_pow2(max(learned_ng, 16))
    else:
        est = _estimate_groups(agg_plan, n_frag, ctx)
        capacity = dev.next_pow2(min(n_frag, max(est, 16)))
    # post-join compaction (CPU backend only — scatter-cheap there): learn
    # the kept-row count and re-shape the aggregate input to it
    # post-join compaction backend gate: 'auto' = CPU only (scatter-cheap
    # there; TPU scatters serialize), 'on'/'off' override — flippable at
    # runtime so a TPU window can A/B it without code edits
    try:
        _cmode = ctx.get_sysvar("tidb_device_compact")
    except Exception:
        _cmode = "auto"
    compact_enabled = (_cmode == "on" or (_cmode != "off"
                                 and jax.default_backend() == "cpu"))
    compact_cap = None
    if compact_enabled and n_frag > 65536:
        learned_kept = _CAP_STORE.get((sig, "compact"))
        if learned_kept is not None and dev.next_pow2(
                max(learned_kept, 8)) * 2 <= n_frag:
            compact_cap = dev.next_pow2(max(learned_kept, 8))

    import os as _os
    import sys as _sys
    import time as _time
    _dbg = _os.environ.get("TIDB_TPU_DEBUG_JOIN")
    for _attempt in range(12):
        caps = [jn.cap for jn in joins]
        key = (sig, tuple(caps), capacity, key_pack, tuple(agg_ops),
               compact_cap)
        t0 = _time.perf_counter()

        def build(caps=tuple(caps), cap=capacity, ccap=compact_cap):
            # the leaves/joins/plan objects are OWNED by this execution;
            # when the compile service defers this builder to a worker the
            # query has already degraded to host, so nothing mutates them
            return compile_fragment(root, leaves, joins, agg_plan,
                                    agg_conds, list(caps), cap, key_pack,
                                    agg_meta, compact_cap=ccap)
        fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                              args=(env, jidx, n_lives), shape="join",
                              sig=sig)
        agg_out, ovf_d, sovf_d, kept_d = fn(env, jidx, n_lives)
        from .device_exec import AggFetch, resolve_topn
        f = AggFetch(agg_out, extras=(ovf_d, sovf_d, kept_d),
                     topn=resolve_topn(agg_plan, slots))
        overflows, span_ovfs, kept = f.extras
        kept = int(kept)
        ng = f.ng
        if _dbg:
            print(f"[device_join] attempt {_attempt}: caps={caps} "
                  f"agg_cap={capacity} compact={compact_cap} kept={kept} "
                  f"totals={[int(o) for o in overflows]} "
                  f"{_time.perf_counter() - t0:.2f}s",
                  file=_sys.stderr, flush=True)
        if any(bool(s) for s in span_ovfs):
            raise DeviceUnsupported(
                "multi-key join value ranges exceed int64 packing")
        retry = False
        for jn, total in zip(joins, overflows):
            if jn.kind in ("semi", "anti") or (
                    jn.strategy is not None and jn.strategy[0] == "uniq"):
                continue  # probe-shaped: total ≤ probe cap by construction
            total = int(total)
            tight = dev.next_pow2(max(total, 8))
            if total > jn.cap:
                # jump straight to the required size (totals downstream of
                # an overflowed join are lower bounds — the next pass
                # corrects them, so convergence is O(join depth), not
                # O(log(need)) recompiles)
                jn.exp_cap = tight
                retry = True
            elif jn.cap > 4 * tight and jn.cap > 8192:
                # shrink-to-fit: a fat discovery capacity pads every
                # downstream operator on every future execution; one more
                # compile now buys tight steady-state shapes forever
                jn.exp_cap = tight
                retry = True
            _cap_store_put((sig, jn.pos), total)
        # profitability gates below compare against the CURRENT root cap
        # (node caps move under shrink-to-fit; the pre-loop n_frag is stale
        # after the first retry)
        root_cap = root.cap if isinstance(root, _JoinNode) else n_frag
        compact_ovf = compact_cap is not None and kept > compact_cap
        if compact_ovf:
            # truncated aggregate input: results (and ng) are invalid —
            # recompile with the real kept count before anything else
            compact_cap = dev.next_pow2(max(kept, 8))
            if compact_cap * 2 > root_cap:
                compact_cap = None  # not worth compacting
            _cap_store_put((sig, "compact"), kept)
            _fill_caps(root, sig)
            continue
        _cap_store_put((sig, "compact"), kept)
        if (compact_enabled and compact_cap is None
                and dev.next_pow2(max(kept, 8)) * 2 <= root_cap
                and root_cap > 65536):
            # compaction newly profitable: one recompile buys an agg that
            # works on kept rows instead of the fact length, forever
            compact_cap = dev.next_pow2(max(kept, 8))
            retry = True
        tight_ng = dev.next_pow2(max(ng, 16))
        if ng > capacity:
            capacity = tight_ng
            retry = True
        elif capacity > 4 * tight_ng and capacity > 8192:
            capacity = tight_ng
            retry = True
        _cap_store_put((sig, "agg"), ng)
        if retry:
            _fill_caps(root, sig)
            continue
        break
    else:
        raise DeviceUnsupported("join fragment capacities did not converge")
    if ng == 0 and not agg_plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    body = f.body()
    return _assemble_agg(agg_plan, key_meta, slots, dcols, body, f.out_rows)


#: resident probe tables larger than this stream through pages even
#: without a disk-backed store (bounds HBM at big scale factors)
_PAGED_MIN_ROWS = 1 << 24


def _probe_spine(root):
    node = root
    while isinstance(node, _JoinNode):
        node = node.left
    return node


def _col_row_bytes(c) -> int:
    """Resident bytes per row of one column: dict columns place their
    int32 codes (4B), everything else its dtype width, +1B null mask.
    THE estimate every budget gate shares (hybrid trigger, paged-build
    refusal, mesh paged gate) — one formula, or the gates disagree about
    the same leaf's footprint."""
    return (4 if c.is_object() else c.data.dtype.itemsize) + 1


def _leaf_used_bytes(leaf, used) -> int:
    """Estimated resident bytes of a leaf's fragment-used columns."""
    per_row = sum(_col_row_bytes(leaf.chunk.columns[i])
                  for i in range(leaf.ncols) if leaf.offset + i in used)
    return per_row * leaf.chunk.num_rows


def _over_budget_builds(leaves, joins, agg_plan, agg_conds,
                        exclude_id=None) -> set:
    """Leaf ids whose fragment-used resident estimate exceeds the
    effective budget — candidates for the hybrid join's partitioned
    build.  `exclude_id` names the probe (never a build): the REAL probe
    leaf when the chain shape is known, else the largest-leaf guess.
    ONE implementation for both the deferred-reorder trigger and the
    execution-time trigger, or the two would drift."""
    budget = _dim_resident_budget()
    if budget <= 0 or len(leaves) < 2:
        return set()
    used = _fragment_used_cols(leaves, joins, agg_plan, agg_conds)
    if exclude_id is None:
        exclude_id = max(leaves, key=lambda lf: lf.chunk.num_rows).leaf_id
    return {leaf.leaf_id for leaf in leaves
            if leaf.leaf_id != exclude_id
            and _leaf_used_bytes(leaf, used) > budget}


def _maybe_hybrid(root, leaves, joins, probe, agg_plan, agg_conds, ctx,
                  deferred=None):
    """Route an over-budget build side to the hybrid hash join
    (executor/hybrid_join.py).  Returns the result Chunk, or None when
    the fragment has no over-budget build (the resident/paged paths
    proceed) or the hybrid language rejects it (fallthrough — unless the
    strategy was DEFERRED, where only the hybrid path can run it and the
    caller must degrade)."""
    big_id = deferred
    if big_id is None:
        over = _over_budget_builds(leaves, joins, agg_plan, agg_conds,
                                   exclude_id=probe.leaf_id)
        if len(over) != 1:
            return None  # nothing over budget (or >1: out of language)
        big_id = next(iter(over))
    from .hybrid_join import hybrid_join_agg
    try:
        return hybrid_join_agg(root, leaves, joins, probe, big_id,
                               agg_plan, agg_conds, ctx)
    except DeviceUnsupported:
        if deferred is not None:
            raise
        return None


#: a paged BUILD-side table may be deliberately materialized into HBM up
#: to this many bytes (needed columns only): SF100 orders as a Q3 build
#: side is ~5GB of used columns — resident is the right call on a 16GB
#: chip, but an unbounded upload would defeat the paged memory bound.
#: This constant is only the fallback when NO budget is configured —
#: see _dim_resident_budget().
_DIM_RESIDENT_BUDGET_DEFAULT = 6 << 30


def _dim_resident_budget() -> int:
    """The effective resident-build threshold in bytes: the residency
    ledger's live per-tenant share (so the paged-build refusal — and the
    hybrid-join trigger — track ``tidb_device_mem_budget`` instead of a
    hard-coded constant), falling back to the historical 6GB default
    when no budget is configured (CPU backend with auto budget)."""
    from ..ops import residency
    share = residency.group_share() or residency.effective_budget()
    return share if share > 0 else _DIM_RESIDENT_BUDGET_DEFAULT


def _fragment_used_cols(leaves, joins, agg_plan, agg_conds):
    """Global column indices the fragment actually reads — per-page probe
    transfers and dim uploads carry only these (a 16-wide fact scanned
    for 4 columns must not pay 4x the tunnel bytes)."""
    used = set()
    for leaf in leaves:
        for c in leaf.conds:
            s = set()
            c.columns_used(s)
            used.update(leaf.offset + i for i in s)
    for jn in joins:
        off_l = 0 if jn.global_keys else jn.left.offset
        off_r = 0 if jn.global_keys else jn.right.offset
        off_o = 0 if jn.global_keys else jn.offset
        for k in jn.left_keys:
            s = set()
            k.columns_used(s)
            used.update(off_l + i for i in s)
        for k in jn.right_keys:
            s = set()
            k.columns_used(s)
            used.update(off_r + i for i in s)
        for c in jn.other_conds:
            s = set()
            c.columns_used(s)
            used.update(off_o + i for i in s)
    for e in agg_plan.group_exprs:
        s = set()
        e.columns_used(s)
        used.update(s)
    for d in agg_plan.aggs:
        for a in d.args:
            s = set()
            a.columns_used(s)
            used.update(s)
    for c in agg_conds:
        s = set()
        c.columns_used(s)
        used.update(s)
    return used


class _PagedStats(threading.local):
    """Stage timing of the thread's most recent paged fragment run —
    EXPLAIN ANALYZE surfaces it on the HashAgg line (reference: executor
    runtime stats, util/execdetails), so "where do the seconds go" is
    answerable without a profiler: slice_s = host page slicing + transfer
    enqueue, sync_s = device compute drained at merge barriers, merge_s =
    partial-state folds, fetch_s = final TopN-candidate fetch + host
    assembly. Thread-local: concurrent sessions each annotate their own
    run, never a neighbor's."""

    def __init__(self):
        self.stats = {}

    def clear(self):
        self.stats.clear()

    def update(self, kv):
        self.stats.update(kv)

    def __bool__(self):
        return bool(self.stats)

    def items(self):
        return self.stats.items()


LAST_PAGED_STATS = _PagedStats()


def _paged_join_agg(root, leaves, joins, probe, agg_plan, agg_conds, ctx,
                    page_rows):
    """Streamed-probe execution of an all-unique-build join chain: the
    fact leaf is cut into `page_rows` pages; each page runs the SAME
    compiled scan→gather-joins→partial-agg program (dimension tables and
    their join indexes stay HBM-resident across pages); per-page partial
    states buffer on device and fold into one running merged state via
    the mergeable-agg kernel. Device memory is bounded by
    page + buffered partials + merge state — never the fact table. This
    is the engine's cop-paging analog (reference kv/kv.go:349-350: the
    coprocessor streams a large scan in pages; here each page carries the
    whole join+agg fragment with it)."""
    if any(jn.strategy is None or jn.strategy[0] != "uniq" for jn in joins):
        raise DeviceUnsupported("paged probe requires all-unique builds")
    # planning view is metadata-only for EVERY leaf: the only uploads are
    # the pruned env_dim ones below, AFTER the resident-budget check
    dcols = _global_dcols(leaves, meta_leaf_ids=frozenset(
        leaf.leaf_id for leaf in leaves))
    agg_meta_full = _plan_agg(agg_plan, dcols)
    key_fns, key_meta, key_pack, val_plan, agg_ops, slots = agg_meta_full
    from .device_exec import (
        _MERGE_BUDGET_ROWS, _MERGE_OPS, AggFetch, resolve_topn)
    if any(op not in _MERGE_OPS for op in agg_ops):
        raise DeviceUnsupported("non-mergeable agg in paged fragment")
    merge_ops = tuple(_MERGE_OPS[op] for op in agg_ops)
    agg_meta = (key_fns, val_plan, agg_ops, slots)

    used = _fragment_used_cols(leaves, joins, agg_plan, agg_conds)
    # leaf_rel reads each leaf's row count off its first env entry — keep
    # at least one column per leaf alive
    for leaf in leaves:
        if not any(leaf.offset + i in used for i in range(leaf.ncols)):
            used.add(leaf.offset)
    from ..storage.paged import chunk_is_paged
    per_double = dev.shape_buckets(ctx)
    env_dim = {}
    for leaf in leaves:
        if leaf.leaf_id == probe.leaf_id:
            continue
        lused = [i for i in range(leaf.ncols) if leaf.offset + i in used]
        if chunk_is_paged(leaf.chunk):
            est = 8 * leaf.chunk.num_rows * len(lused)
            if est > _dim_resident_budget():
                raise DeviceUnsupported(
                    "paged build-side leaf exceeds resident budget")
        dim_bucket = dev.bucket_rows(leaf.chunk.num_rows, per_double)
        for i in lused:
            dc = dev.to_device_col(leaf.chunk.columns[i],
                                   bucket=dim_bucket)
            env_dim[leaf.offset + i] = (dc.data, dc.nulls)
    probe_arrays = {
        probe.offset + i: dev.meta_device_col(c)[1]
        for i, c in enumerate(probe.chunk.columns)
        if probe.offset + i in used}
    jidx = tuple(jn.strategy[2].device_arrays() for jn in joins)
    sig = fragment_sig(leaves, joins, agg_conds, agg_plan) + f"|pg{page_rows}"
    dict_refs = tuple(dc.dictionary for dc in dcols.values()
                      if dc.dictionary is not None)

    n = probe.chunk.num_rows
    n_keys = max(len(key_fns), 1)
    nvals = len(val_plan)
    learned = _CAP_STORE.get((sig, "agg"))
    if learned is not None:
        capacity = dev.next_pow2(max(learned, 16))
    else:
        est = _estimate_groups(agg_plan, min(n, page_rows), ctx)
        capacity = dev.next_pow2(min(page_rows, max(est, 16)))
    learned_total = _CAP_STORE.get((sig, "groups"))
    merge_cap = dev.next_pow2(max(learned_total or capacity, 16))

    def pad_page(arr, lo, hi, null_pad=False):
        return jnp.asarray(dev.pad_host(arr[lo:hi], page_rows, null_pad))

    base_lives = [np.int64(leaf.chunk.num_rows) for leaf in leaves]

    def page_lives(hi, lo):
        lives = list(base_lives)
        lives[probe.leaf_id] = np.int64(hi - lo)
        return tuple(lives)

    from .device_exec import merge_partial_states

    def merge_flush(state, buffered, merge_cap):
        return merge_partial_states(state, buffered, merge_cap, n_keys,
                                    nvals, merge_ops, key_pack)

    for jn in joins:
        jn.cap = page_rows  # every join is a probe-shaped gather
    from .device_exec import _want_host_tail
    if _want_host_tail(key_pack, page_rows):
        # raw-tail path: XLA keeps the fused scan->gather-join->expression
        # work; the per-page group-by runs in numpy, which is
        # row-proportional where the XLA-CPU aggregate pays in the packed
        # key SPAN. No capacity discovery, no restarts.
        return _paged_join_agg_host_tail(
            root, leaves, joins, probe, agg_plan, agg_conds, ctx,
            page_rows, dcols, agg_meta_full, merge_ops, sig, dict_refs,
            env_dim, probe_arrays, jidx, n)
    for _attempt in range(4):
        caps = [page_rows] * len(joins)
        key = (sig, tuple(caps), capacity, key_pack, tuple(agg_ops), None,
               "paged")

        def build(caps=tuple(caps), cap=capacity):
            return compile_fragment(root, leaves, joins, agg_plan,
                                    agg_conds, list(caps), cap, key_pack,
                                    agg_meta)
        # per-page env is assembled inside the loop below, so there is no
        # whole-call arg spec to record: the paged fragment compiles sync
        # (still breaker-guarded + persisted through the compile service)
        fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                              shape="join", sig=sig)
        k_flush = max(1, _MERGE_BUDGET_ROWS // capacity)
        state = None
        buffered = []
        max_ng = 0
        overflow = False
        import time as _time
        stats = {"pages": 0, "slice_s": 0.0, "dispatch_s": 0.0,
                 "sync_s": 0.0, "merge_s": 0.0, "capacity": capacity}
        for lo in range(0, n, page_rows):
            hi = min(lo + page_rows, n)
            env = dict(env_dim)
            t0 = _time.perf_counter()
            for gidx, (d, nl) in probe_arrays.items():
                env[gidx] = (pad_page(d, lo, hi), pad_page(nl, lo, hi, True))
            t1 = _time.perf_counter()
            agg_out, _ovf, _sovf, _kept = fn(env, jidx, page_lives(hi, lo))
            t2 = _time.perf_counter()
            stats["pages"] += 1
            stats["slice_s"] += t1 - t0
            stats["dispatch_s"] += t2 - t1
            buffered.append(agg_out)
            if len(buffered) >= k_flush:
                t3 = _time.perf_counter()
                ngs = [int(g) for g in
                       jax.device_get([p[4] for p in buffered])]
                stats["sync_s"] += _time.perf_counter() - t3
                max_ng = max(max_ng, *ngs)
                if max_ng > capacity:
                    overflow = True
                    break
                t4 = _time.perf_counter()
                state, merge_cap = merge_flush(state, buffered, merge_cap)
                stats["merge_s"] += _time.perf_counter() - t4
                buffered = []
        if not overflow and buffered:
            t3 = _time.perf_counter()
            ngs = [int(g) for g in jax.device_get([p[4] for p in buffered])]
            stats["sync_s"] += _time.perf_counter() - t3
            max_ng = max(max_ng, *ngs)
            if max_ng <= capacity:
                t4 = _time.perf_counter()
                state, merge_cap = merge_flush(state, buffered, merge_cap)
                stats["merge_s"] += _time.perf_counter() - t4
                buffered = []
        if overflow or max_ng > capacity:
            # a page's group count exceeded the partial capacity: restart
            # the pass at the observed size (remembered, so the discovery
            # restart happens once per fragment ever)
            capacity = dev.next_pow2(max_ng)
            _cap_store_put((sig, "agg"), max_ng)
            continue
        _cap_store_put((sig, "agg"), max(max_ng, 1))
        break
    else:
        raise DeviceUnsupported("paged fragment capacity did not converge")
    if state is None:
        raise DeviceUnsupported("empty paged fragment input")
    t5 = _time.perf_counter()
    f = AggFetch(state, topn=resolve_topn(agg_plan, slots))
    ng = f.ng
    _cap_store_put((sig, "groups"), ng)
    if ng == 0 and not agg_plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    body = f.body()
    out = _assemble_agg(agg_plan, key_meta, slots, dcols, body, f.out_rows)
    stats["fetch_s"] = _time.perf_counter() - t5
    stats["groups"] = ng
    LAST_PAGED_STATS.clear()
    LAST_PAGED_STATS.update(
        {k: (round(v, 2) if isinstance(v, float) else v)
         for k, v in stats.items()})
    return out


def _paged_join_agg_host_tail(root, leaves, joins, probe, agg_plan,
                              agg_conds, ctx, page_rows, dcols,
                              agg_meta_full, merge_ops, sig, dict_refs,
                              env_dim, probe_arrays, jidx, n):
    """CPU-backend paged fragment: raw-tail program per page + numpy
    partial aggregation + one numpy fold at the end (see
    compile_fragment raw_tail / device_exec._merge_states_host)."""
    import time as _time
    from .device_exec import (AggFetch, _merge_states_host,
                              page_singleton_state, resolve_topn)
    key_fns, key_meta, key_pack, val_plan, agg_ops, slots = agg_meta_full
    agg_meta = (key_fns, val_plan, agg_ops, slots)
    n_keys = max(len(key_fns), 1)
    nvals = len(val_plan)
    key = (sig, key_pack, tuple(agg_ops), "rawtail")

    def build():
        return compile_fragment(root, leaves, joins, agg_plan, agg_conds,
                                [page_rows] * len(joins), 1, key_pack,
                                agg_meta, raw_tail=True)
    fn = acquire_pipeline(key, build, dict_refs, ctx=ctx, shape="join",
                          sig=sig)

    def pad_page(arr, lo, hi, null_pad=False):
        return jnp.asarray(dev.pad_host(arr[lo:hi], page_rows, null_pad))

    base_lives = [np.int64(leaf.chunk.num_rows) for leaf in leaves]
    stats = {"pages": 0, "slice_s": 0.0, "dispatch_s": 0.0, "sync_s": 0.0,
             "merge_s": 0.0}
    states = []
    for lo in range(0, n, page_rows):
        hi = min(lo + page_rows, n)
        env = dict(env_dim)
        t0 = _time.perf_counter()
        for gidx, (d, nl) in probe_arrays.items():
            env[gidx] = (pad_page(d, lo, hi), pad_page(nl, lo, hi, True))
        t1 = _time.perf_counter()
        lives = list(base_lives)
        lives[probe.leaf_id] = np.int64(hi - lo)
        raw, _ovf, _sovf, _kept = fn(env, jidx, tuple(lives))
        t2 = _time.perf_counter()
        # per-page compaction keeps at most one compact state per page in
        # RAM (zero-copy views of the page's buffers drop right after)
        page = page_singleton_state(raw[0], raw[1], raw[2], raw[3],
                                    raw[4], agg_ops)
        state, _cap = _merge_states_host([page], 16, n_keys, nvals,
                                         merge_ops, key_pack)
        states.append(state)
        t3 = _time.perf_counter()
        stats["pages"] += 1
        stats["slice_s"] += t1 - t0
        stats["dispatch_s"] += t2 - t1
        stats["sync_s"] += t3 - t2
    if not states:
        raise DeviceUnsupported("empty paged fragment input")
    t4 = _time.perf_counter()
    state, _cap = (_merge_states_host(states, 16, n_keys, nvals,
                                      merge_ops, key_pack)
                   if len(states) > 1 else (states[0], 0))
    stats["merge_s"] = _time.perf_counter() - t4
    t5 = _time.perf_counter()
    f = AggFetch(state, topn=resolve_topn(agg_plan, slots))
    ng = f.ng
    if ng == 0 and not agg_plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    body = f.body()
    out = _assemble_agg(agg_plan, key_meta, slots, dcols, body, f.out_rows)
    stats["fetch_s"] = _time.perf_counter() - t5
    stats["groups"] = ng
    LAST_PAGED_STATS.clear()
    LAST_PAGED_STATS.update(
        {k: (round(v, 2) if isinstance(v, float) else v)
         for k, v in stats.items()})
    return out


def fragment_sig(leaves, joins, agg_conds, agg_plan):
    parts = []
    for leaf in leaves:
        parts.append(f"L{leaf.leaf_id}@{leaf.offset}x{leaf.ncols}:"
                     + ";".join(_expr_sig(c) for c in leaf.conds))
        for c in leaf.chunk.columns:
            if c.is_object():
                # CONTENT signature, not id(): a delta append re-encodes
                # the same value set into a new dictionary object, and the
                # compiled fragment (whose LUTs bake the content) must
                # still hit
                parts.append(c.dict_sig())
    for jn in joins:
        keys = ",".join(f"{_expr_sig(lk)}={_expr_sig(rk)}"
                        for lk, rk in zip(jn.left_keys, jn.right_keys))
        parts.append(f"J{jn.offset}/{jn.kind}:{keys}|"
                     + ";".join(_expr_sig(c) for c in jn.other_conds))
        parts.append(_strategy_sig(jn))
    parts.append("|c|" + ";".join(_expr_sig(c) for c in agg_conds))
    parts.append("|g|" + ";".join(_expr_sig(e) for e in agg_plan.group_exprs))
    parts.append("|a|" + ";".join(
        f"{d.name}:{_expr_sig(d.args[0]) if d.args else ''}"
        for d in agg_plan.aggs))
    return "\n".join(parts)
