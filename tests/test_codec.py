"""Codec / tablecodec / chunk foundations (reference test model:
util/codec/codec_test.go, tablecodec/tablecodec_test.go)."""

import random

import numpy as np
import pytest

from tidb_tpu import tablecodec
from tidb_tpu.sqltypes import (
    decimal_to_str, new_decimal_type, new_int_type, new_string_type,
    parse_date_str, parse_datetime_str, str_to_decimal,
)
from tidb_tpu.utils import codec
from tidb_tpu.utils.chunk import Chunk, Column, concat_chunks


def test_int_roundtrip_and_order():
    vals = [-(2**63), -12345, -1, 0, 1, 7, 2**63 - 1]
    encs = [codec.encode_key([v]) for v in vals]
    for v, e in zip(vals, encs):
        assert codec.decode_key(e) == [v]
    assert encs == sorted(encs)


def test_bytes_roundtrip_and_order():
    vals = [b"", b"a", b"abc", b"abc\x00", b"abcdefgh", b"abcdefgh\x00", b"b"]
    encs = [codec.encode_key([v]) for v in vals]
    for v, e in zip(vals, encs):
        assert codec.decode_key(e) == [v]
    assert encs == sorted(encs)


def test_float_order():
    vals = [-1e300, -2.5, -0.0, 0.0, 1e-10, 3.14, 1e300]
    encs = [codec.encode_key([v]) for v in vals]
    assert encs == sorted(encs)
    for v, e in zip(vals, encs):
        assert codec.decode_key(e) == [v]


def test_mixed_key_roundtrip():
    key = codec.encode_key([None, 42, b"hello", 2.5])
    assert codec.decode_key(key) == [None, 42, b"hello", 2.5]


def test_random_int_order():
    rng = random.Random(7)
    vals = sorted(rng.randrange(-2**62, 2**62) for _ in range(500))
    encs = [codec.encode_key([v]) for v in vals]
    assert encs == sorted(encs)


def test_record_key():
    k = tablecodec.record_key(45, 99)
    assert tablecodec.decode_record_key(k) == (45, 99)
    start, end = tablecodec.table_range(45)
    assert start <= k < end
    # keys from other tables fall outside
    assert not (start <= tablecodec.record_key(46, 0) < end)


def test_record_key_handle_order():
    ks = [tablecodec.record_key(1, h) for h in [-5, -1, 0, 1, 100, 10**12]]
    assert ks == sorted(ks)


def test_index_key_roundtrip():
    k = tablecodec.index_key(45, 2, [b"abc", 7], handle=5)
    vals = tablecodec.decode_index_values(k)
    assert vals == [b"abc", 7, 5]


def test_row_codec_roundtrip():
    row = {1: 42, 2: None, 3: b"hello", 4: 2.75, 5: -1}
    data = tablecodec.encode_row(list(row), list(row.values()))
    assert tablecodec.decode_row(data) == row


def test_varint():
    buf = bytearray()
    for v in [0, 1, -1, 300, -300, 2**40, -(2**40)]:
        codec.write_varint(buf, v)
    pos = 0
    for v in [0, 1, -1, 300, -300, 2**40, -(2**40)]:
        got, pos = codec.read_varint(bytes(buf), pos)
        assert got == v


def test_decimal_parse_render():
    assert str_to_decimal("123.45", 2) == 12345
    assert str_to_decimal("-0.05", 2) == -5
    assert str_to_decimal("1.005", 2) == 101  # half-up
    assert str_to_decimal("-1.005", 2) == -101
    assert str_to_decimal("1e2", 2) == 10000
    assert str_to_decimal("1.5e-1", 2) == 15
    assert decimal_to_str(12345, 2) == "123.45"
    assert decimal_to_str(-5, 2) == "-0.05"
    assert decimal_to_str(42, 0) == "42"


def test_date_parse():
    assert parse_date_str("1970-01-01") == 0
    assert parse_date_str("1970-01-02") == 1
    assert parse_date_str("1995-03-15") == 9204
    assert parse_datetime_str("1970-01-01 00:00:01") == 1_000_000


def test_chunk_basics():
    ft_i = new_int_type()
    ft_s = new_string_type()
    ft_d = new_decimal_type(10, 2)
    ch = Chunk.from_rows([ft_i, ft_s, ft_d],
                         [(1, b"a", 150), (None, b"bb", -5), (3, None, None)])
    assert ch.num_rows == 3
    assert ch.row(0) == (1, b"a", 150)
    assert ch.row(1) == (None, b"bb", -5)
    assert ch.row(2) == (3, None, None)
    disp = ch.to_display_rows()
    assert disp[0] == ("1", "a", "1.50")
    assert disp[1] == (None, "bb", "-0.05")

    filtered = ch.filter(np.array([True, False, True]))
    assert filtered.num_rows == 2
    assert filtered.row(1) == (3, None, None)

    cc = concat_chunks([ch, filtered])
    assert cc.num_rows == 5


def test_column_dict_encode():
    ft = new_string_type()
    col = Column.from_values(ft, [b"x", b"y", b"x", b"z"])
    codes, uniq = col.dict_encode()
    assert [uniq[c] for c in codes] == [b"x", b"y", b"x", b"z"]


def test_column_prefix64_order():
    ft = new_string_type()
    vals = [b"", b"a", b"ab", b"b", b"zzzzzzzzz"]
    col = Column.from_values(ft, vals)
    p = col.prefix64()
    assert list(p) == sorted(p)
