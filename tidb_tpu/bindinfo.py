"""SQL plan management — plan bindings (reference: bindinfo/handle.go
BindHandle + planner/optimize.go:147-207 binding match).

A binding pairs a normalized statement with a hinted variant of the same
statement.  At plan time the optimizer looks up the current statement's
normalized text; on a hit it transplants the binding's index hints onto the
statement before optimization, so USE/FORCE/IGNORE INDEX choices apply
without editing application SQL.  GLOBAL bindings persist in the catalog
(the mysql.bind_info role); SESSION bindings live on the session.
"""

from __future__ import annotations

import threading
import time

from .errors import TiDBError
from .meta import Meta
from .parser import ast, normalize, parse
from .priv_check import _collect_tables


def normalized_sql(stmt) -> str:
    """Normalized text of a statement AST (literals → '?', lowercase)."""
    return normalize(stmt.restore())


def extract_hints(stmt) -> list:
    """[(table_name_lower, [(verb, [index names])])] for every TableName in
    AST traversal order — positional, so a self-join can carry different
    hints per occurrence (reference: bindinfo matches hints by offset)."""
    tabs = []
    _collect_tables(stmt, tabs)
    return [(tn.name.lower(), list(tn.index_hints)) for tn in tabs]


def apply_hints(stmt, hints: list, sql_hints=None):
    """Overwrite index hints positionally on the statement's TableNames
    from a binding's hint list (reference: BindHint in
    planner/optimize.go). Both statements normalize identically, so their
    traversal orders agree; names are still checked defensively. Returns
    an undo list [(TableName, original hints)] — callers must restore
    after planning, or a cached prepared AST keeps the transplant
    forever.

    sql_hints: the binding's /*+ ... */ optimizer hints (join algorithm,
    agg mode, engine pin) transplanted onto the statement head."""
    tabs = []
    _collect_tables(stmt, tabs)
    undo = []
    for tn, (name, h) in zip(tabs, hints):
        if tn.name.lower() != name:
            continue  # structure drifted: skip rather than mis-hint
        undo.append((tn, tn.index_hints))
        tn.index_hints = [(verb, list(names)) for verb, names in h]
    if sql_hints and hasattr(stmt, "hints"):
        undo.append(("sql_hints", stmt, stmt.hints))
        stmt.hints = [(n, list(a)) for n, a in sql_hints]
    return undo


def undo_hints(undo):
    for entry in undo:
        if entry[0] == "sql_hints":
            _tag, stmt, hints = entry
            stmt.hints = hints
        else:
            tn, hints = entry
            tn.index_hints = hints


def binding_key(db: str, norm_sql: str) -> str:
    """Bindings are scoped to the creating session's database — the same
    normalized text against another db's same-named table must not match
    (reference: bind_info's default_db column)."""
    return f"{(db or '').lower()}\x00{norm_sql}"


class BindHandle:
    """Domain-level cache of GLOBAL bindings (reference: bindinfo
    BindHandle with lease refresh; single process → explicit reload)."""

    def __init__(self, domain):
        self.domain = domain
        self._lock = threading.Lock()
        self.cache: dict[str, dict] = {}
        self.version = 0   # bumped per change; invalidates cached plans
        self.load()

    def load(self):
        txn = self.domain.store.begin()
        try:
            binds = Meta(txn).list_bindings()
        finally:
            txn.rollback()
        with self._lock:
            self.cache = binds
            self.version += 1

    def match(self, norm_sql: str):
        with self._lock:
            return self.cache.get(norm_sql)

    def create(self, norm_sql: str, rec: dict):
        txn = self.domain.store.begin()
        try:
            Meta(txn).set_binding(norm_sql, rec)
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        with self._lock:
            self.cache[norm_sql] = rec
            self.version += 1

    def drop(self, norm_sql: str) -> bool:
        txn = self.domain.store.begin()
        try:
            Meta(txn).del_binding(norm_sql)
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        with self._lock:
            self.version += 1
            return self.cache.pop(norm_sql, None) is not None

    def list(self):
        with self._lock:
            return dict(self.cache)


def make_binding(original_stmt, bind_stmt, db: str = "") -> tuple[str, dict]:
    """Validate a CREATE BINDING pair and build the stored record."""
    norm_o = normalized_sql(original_stmt)
    hints = extract_hints(bind_stmt)
    sql_hints = list(getattr(bind_stmt, "hints", []) or [])
    if not any(h for _t, h in hints) and not sql_hints:
        raise TiDBError("the bound statement carries no hints")
    # the hinted statement must be the same query modulo hints (reference:
    # bindinfo checks original/bind digest equality after hint stripping)
    undo = apply_hints(bind_stmt, [(t, []) for t, _h in hints])
    try:
        norm_b_stripped = normalized_sql(bind_stmt)
    finally:
        undo_hints(undo)
    if norm_b_stripped != norm_o:
        raise TiDBError("the original SQL and the bind SQL are different")
    rec = {"original": original_stmt.restore(),
           "bind": bind_stmt.restore(),
           "db": (db or "").lower(),
           "hints": [[t, [[v, list(n)] for v, n in hs]] for t, hs in hints],
           "sql_hints": [[n, list(a)] for n, a in sql_hints],
           "created": time.strftime("%Y-%m-%d %H:%M:%S"),
           "status": "enabled"}
    return binding_key(db, norm_o), rec


def hints_from_record(rec: dict) -> list:
    h = rec.get("hints")
    if isinstance(h, dict):  # legacy by-name record
        return [(t, [(v, list(n)) for v, n in hs]) for t, hs in h.items()]
    return [(t, [(v, list(n)) for v, n in hs]) for t, hs in h]


def sql_hints_from_record(rec: dict) -> list:
    return [(n, list(a)) for n, a in rec.get("sql_hints", [])]


def plan_hints(plan) -> list:
    """Synthesize the /*+ ... */ hint set that would reproduce `plan`'s
    physical choices — the capture payload (reference: bindinfo capture
    stores the executed plan's hint set, handle.go:749). One hint per
    join keyed by a build-side table name, plus the agg mode when
    pinned."""
    from .planner.logical import Aggregation, DataSource, Join
    hints = []

    def first_table(p):
        if isinstance(p, DataSource):
            return (p.alias or p.table_info.name).lower()
        for c in p.children:
            t = first_table(c)
            if t:
                return t
        return None

    def walk(p):
        if isinstance(p, Join) and p.left_keys:
            t = first_table(p.right)
            if t:
                hints.append(({"hash": "hash_join", "merge": "merge_join",
                               "index": "inl_join"}[p.join_algo], [t]))
        if isinstance(p, Aggregation) and p.agg_hint:
            hints.append(("stream_agg" if p.agg_hint == "stream"
                          else "hash_agg", []))
        for c in p.children:
            walk(c)
    walk(plan)
    return hints


