"""information_schema virtual tables (reference: infoschema/tables.go — 75+
memtables; the core set here, growing with the engine)."""

from __future__ import annotations

from ..errors import SchemaError
from ..sqltypes import TYPE_LONGLONG, TYPE_VARCHAR, FieldType

from ..sqltypes import TYPE_DOUBLE

_S = FieldType(tp=TYPE_VARCHAR)
_I = FieldType(tp=TYPE_LONGLONG)
_F = FieldType(tp=TYPE_DOUBLE)


def mem_table(session, db: str, name: str):
    """-> ([(col_name, ftype)], rows_fn)."""
    fn = _TABLES.get((db, name))
    if fn is None:
        raise SchemaError(f"Table '{db}.{name}' doesn't exist")
    return fn(session)


def _schemata(session):
    cols = [("catalog_name", _S), ("schema_name", _S),
            ("default_character_set_name", _S),
            ("default_collation_name", _S)]

    def rows():
        out = [(b"def", b"information_schema", b"utf8mb4", b"utf8mb4_bin")]
        for n in session.infoschema().schema_names():
            out.append((b"def", n.encode(), b"utf8mb4", b"utf8mb4_bin"))
        return out
    return cols, rows


def _tables(session):
    cols = [("table_catalog", _S), ("table_schema", _S), ("table_name", _S),
            ("table_type", _S), ("engine", _S), ("table_rows", _I),
            ("auto_increment", _I), ("tidb_table_id", _I)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                ttype = (b"VIEW" if t.is_view
                         else b"SEQUENCE" if t.is_sequence
                         else b"BASE TABLE")
                nrows = (session.expr_ctx().table_rows(t.id)
                         if ttype == b"BASE TABLE" else 0)
                out.append((b"def", dbn.encode(), t.name.encode(),
                            ttype, b"tpu-htap", nrows,
                            t.auto_increment, t.id))
        return out
    return cols, rows


def _views(session):
    cols = [("table_catalog", _S), ("table_schema", _S), ("table_name", _S),
            ("view_definition", _S), ("definer", _S), ("security_type", _S)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                if t.is_view:
                    out.append((b"def", dbn.encode(), t.name.encode(),
                                t.view["select"].encode(),
                                t.view.get("definer", "").encode(),
                                b"DEFINER"))
        return out
    return cols, rows


def _partitions(session):
    cols = [("table_catalog", _S), ("table_schema", _S), ("table_name", _S),
            ("partition_name", _S), ("partition_ordinal_position", _I),
            ("partition_method", _S), ("partition_expression", _S),
            ("partition_description", _S), ("tidb_partition_id", _I)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                p = t.partition
                if p is None:
                    continue
                for pos, d in enumerate(p.defs, 1):
                    if p.type == "range":
                        desc = str(d.less_than)
                    elif p.type == "list":
                        desc = ",".join(str(v) for v in d.in_values)
                    else:
                        desc = ""
                    out.append((b"def", dbn.encode(), t.name.encode(),
                                d.name.encode(), pos,
                                p.type.upper().encode(), p.expr.encode(),
                                desc.encode(), d.id))
        return out
    return cols, rows


def _sequences(session):
    cols = [("table_catalog", _S), ("sequence_schema", _S),
            ("sequence_name", _S), ("start", _I), ("increment", _I),
            ("min_value", _I), ("max_value", _I), ("cache", _I),
            ("cycle", _I)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                if t.is_sequence:
                    s = t.sequence
                    out.append((b"def", dbn.encode(), t.name.encode(),
                                s["start"], s["increment"], s["min"],
                                s["max"], s["cache"], s["cycle"]))
        return out
    return cols, rows


def _table_constraints(session):
    cols = [("constraint_catalog", _S), ("constraint_schema", _S),
            ("constraint_name", _S), ("table_schema", _S),
            ("table_name", _S), ("constraint_type", _S)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                if t.is_view or t.is_sequence:
                    continue
                db_b = dbn.encode()
                if t.pk_is_handle:
                    out.append((b"def", db_b, b"PRIMARY", db_b,
                                t.name.encode(), b"PRIMARY KEY"))
                for idx in t.indexes:
                    if idx.primary:
                        kind = b"PRIMARY KEY"
                    elif idx.unique:
                        kind = b"UNIQUE"
                    else:
                        continue
                    out.append((b"def", db_b, idx.name.encode(), db_b,
                                t.name.encode(), kind))
                for fk in t.foreign_keys:
                    out.append((b"def", db_b, fk["name"].encode(), db_b,
                                t.name.encode(), b"FOREIGN KEY"))
        return out
    return cols, rows


def _gc_status(session):
    """GC worker state as rows (the mysql.tidb tikv_gc_* variables role,
    reference: gc_worker.go saveValueToSysTable)."""
    cols = [("variable_name", _S), ("variable_value", _S)]

    def rows():
        import time as _t
        st = session.domain.gc_worker.status()
        lr = st["last_run"]
        return [
            (b"tikv_gc_safe_point", str(st["safe_point"]).encode()),
            (b"tikv_gc_last_run_time",
             (_t.strftime("%Y-%m-%d %H:%M:%S", _t.localtime(lr)).encode()
              if lr else b"")),
            (b"tikv_gc_run_interval",
             f"{int(st['run_interval_s'])}s".encode()),
            (b"tikv_gc_life_time", f"{int(st['life_time_s'])}s".encode()),
            (b"tikv_gc_runs", str(st["runs"]).encode()),
            (b"tikv_gc_locks_resolved",
             str(st["locks_resolved"]).encode()),
        ]
    return cols, rows


def _referential_constraints(session):
    cols = [("constraint_catalog", _S), ("constraint_schema", _S),
            ("constraint_name", _S), ("table_name", _S),
            ("referenced_table_name", _S), ("update_rule", _S),
            ("delete_rule", _S)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                for fk in getattr(t, "foreign_keys", []):
                    out.append((b"def", dbn.encode(), fk["name"].encode(),
                                t.name.encode(),
                                fk["ref_table"].encode(),
                                (fk.get("on_update") or
                                 "restrict").upper().encode(),
                                (fk.get("on_delete") or
                                 "restrict").upper().encode()))
        return out
    return cols, rows


def _columns(session):
    cols = [("table_catalog", _S), ("table_schema", _S), ("table_name", _S),
            ("column_name", _S), ("ordinal_position", _I),
            ("is_nullable", _S), ("data_type", _S), ("column_type", _S),
            ("column_key", _S)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                for i, c in enumerate(t.public_columns(), 1):
                    out.append((b"def", dbn.encode(), t.name.encode(),
                                c.name.encode(), i,
                                b"NO" if c.ftype.not_null else b"YES",
                                c.ftype.type_name().encode(),
                                c.ftype.sql_string().encode(), b""))
        return out
    return cols, rows


def _statistics(session):
    cols = [("table_schema", _S), ("table_name", _S), ("non_unique", _I),
            ("index_name", _S), ("seq_in_index", _I), ("column_name", _S)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                if t.pk_is_handle:
                    pk = next((c.name for c in t.columns
                               if c.id == t.pk_col_id), "")
                    out.append((dbn.encode(), t.name.encode(), 0,
                                b"PRIMARY", 1, pk.encode()))
                for idx in t.indexes:
                    for seq, ic in enumerate(idx.columns, 1):
                        out.append((dbn.encode(), t.name.encode(),
                                    0 if idx.unique else 1,
                                    idx.name.encode(), seq, ic.name.encode()))
        return out
    return cols, rows


def _engines(session):
    cols = [("engine", _S), ("support", _S), ("comment", _S)]

    def rows():
        return [(b"tpu-htap", b"DEFAULT", b"TPU-native HTAP engine")]
    return cols, rows


def processlist_rows(session, max_info=0):
    """One row per live session of the domain — the single source for
    SHOW [FULL] PROCESSLIST and information_schema.processlist
    (reference: executor/show.go fetchShowProcessList)."""
    import time as _t
    out = []
    for s in sorted(session.domain.sessions.values(),
                    key=lambda s: s.conn_id):
        running = s.current_sql is not None
        info = s.current_sql or ""
        if max_info:
            info = info[:max_info]
        out.append((
            s.conn_id, s.user.encode(), b"localhost",
            s.current_db().encode(),
            b"Query" if running else b"Sleep",
            int(_t.time() - s.stmt_start) if running else 0,
            b"autocommit" if s.txn is None else b"in transaction",
            info.encode()))
    return out


def _placement_policies(session):
    """reference: infoschema placement_policies (ddl/placement_policy.go)."""
    cols = [("policy_name", _S), ("primary_region", _S), ("regions", _S),
            ("followers", _I), ("learners", _I), ("schedule", _S),
            ("constraints", _S)]

    def rows():
        from ..meta import Meta
        txn = session.domain.store.begin()
        try:
            pols = Meta(txn).placement_policies()
        finally:
            txn.rollback()
        out = []
        for key, rec in sorted(pols.items()):
            o = rec.get("options", rec)  # tolerate pre-display records
            out.append((rec.get("display", key).encode(),
                        str(o.get("primary_region", "")).encode(),
                        str(o.get("regions", "")).encode(),
                        int(o.get("followers", 0) or 0),
                        int(o.get("learners", 0) or 0),
                        str(o.get("schedule", "")).encode(),
                        str(o.get("constraints", "")).encode()))
        return out
    return cols, rows


def _processlist(session):
    cols = [("id", _I), ("user", _S), ("host", _S), ("db", _S),
            ("command", _S), ("time", _I), ("state", _S), ("info", _S)]

    def rows():
        out = processlist_rows(session)
        return out
    return cols, rows


def _slow_query(session):
    """reference: executor/slow_query.go reading the slow log back as SQL."""
    cols = [("time", _S), ("user", _S), ("db", _S), ("query_time", _F),
            ("digest", _S), ("query", _S), ("result_rows", _I),
            ("succ", _I), ("plan", _S), ("trace", _S)]

    def rows():
        import datetime as _dt
        out = []
        for it in list(session.domain.observe.slow_queries):
            ts = _dt.datetime.fromtimestamp(it.ts).strftime(
                "%Y-%m-%d %H:%M:%S.%f")
            out.append((ts.encode(), it.user.encode(), it.db.encode(),
                        it.duration_s, it.digest.encode(), it.sql.encode(),
                        it.rows, 1 if it.succ else 0, it.plan.encode(),
                        getattr(it, "trace", "").encode()))
        return out
    return cols, rows


def _trace_records(session):
    """Recent query-lifecycle traces (session/tracing.py ring): one row
    per finished trace with its rendered span tree — the reference's
    trace memtable shape over the bounded process-wide ring."""
    from . import tracing
    cols = [("trace_id", _I), ("parent_id", _I), ("origin", _S),
            ("name", _S), ("start_ts", _S), ("duration_s", _F),
            ("spans", _I), ("dropped", _I), ("conn_id", _I), ("succ", _I),
            ("tree", _S)]

    def rows():
        import datetime as _dt
        out = []
        for tr in tracing.recent_traces():
            ts = _dt.datetime.fromtimestamp(tr.started_at).strftime(
                "%Y-%m-%d %H:%M:%S.%f")
            out.append((tr.trace_id, tr.parent_id or 0,
                        tr.origin.encode(), tr.name.encode(), ts.encode(),
                        tr.dur_s if tr.dur_s is not None else 0.0,
                        len(tr.spans), tr.dropped, tr.conn_id or 0,
                        1 if tr.succ else 0,
                        tracing.render_tree(tr).encode()))
        return out
    return cols, rows


def _statements_summary(session):
    """reference: util/stmtsummary/statement_summary.go."""
    cols = [("digest", _S), ("exec_count", _I), ("sum_latency", _F),
            ("max_latency", _F), ("min_latency", _F), ("avg_latency", _F),
            ("sum_result_rows", _I), ("err_count", _I),
            ("schema_name", _S), ("digest_text", _S)]

    def rows():
        out = []
        for st in list(session.domain.observe.stmt_summary.values()):
            avg = st.sum_latency / st.exec_count if st.exec_count else 0.0
            out.append((st.digest.encode(), st.exec_count, st.sum_latency,
                        st.max_latency,
                        0.0 if st.min_latency == float("inf")
                        else st.min_latency,
                        avg, st.sum_rows, st.err_count,
                        st.db.encode(), st.sample_sql.encode()))
        return out
    return cols, rows


def _metrics(session):
    """Flat counter registry snapshot (reference: metrics/metrics.go)."""
    cols = [("name", _S), ("value", _I)]

    def rows():
        return [(k.encode(), v) for k, v in
                sorted(session.domain.observe.counters.items())]
    return cols, rows


def _tidb_indexes(session):
    cols = [("table_schema", _S), ("table_name", _S), ("key_name", _S),
            ("column_name", _S), ("index_id", _I)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                for idx in t.indexes:
                    for ic in idx.columns:
                        out.append((dbn.encode(), t.name.encode(),
                                    idx.name.encode(), ic.name.encode(),
                                    idx.id))
        return out
    return cols, rows


def _character_sets(session):
    cols = [("character_set_name", _S), ("default_collate_name", _S),
            ("description", _S), ("maxlen", _I)]

    def rows():
        from ..utils.collate import CHARSETS
        return [(name, dflt, desc, maxlen)
                for name, desc, dflt, maxlen in CHARSETS]
    return cols, rows


def _collations(session):
    cols = [("collation_name", _S), ("character_set_name", _S), ("id", _I),
            ("is_default", _S), ("is_compiled", _S), ("sortlen", _I)]

    def rows():
        from ..utils.collate import COLLATIONS
        return list(COLLATIONS)
    return cols, rows


def _key_column_usage(session):
    cols = [("constraint_name", _S), ("table_schema", _S), ("table_name", _S),
            ("column_name", _S), ("ordinal_position", _I)]

    def rows():
        out = []
        infos = session.infoschema()
        for dbn in infos.schema_names():
            for t in infos.tables_in_schema(dbn):
                for idx in t.indexes:
                    if not idx.unique:
                        continue
                    cname = b"PRIMARY" if idx.primary else idx.name.encode()
                    for seq, ic in enumerate(idx.columns, 1):
                        out.append((cname, dbn.encode(), t.name.encode(),
                                    ic.name.encode(), seq))
        return out
    return cols, rows


def _tidb_top_sql(session):
    """TopSQL per-digest CPU attribution (reference: util/topsql — the
    pubsub report surface becomes this memtable)."""
    cols = [("sql_digest", _S), ("sample_sql", _S), ("cpu_time_ms", _F),
            ("samples", _I), ("last_seen", _F)]

    def rows():
        return [(e.digest.encode(), e.sample_sql.encode(),
                 round(e.cpu_ms, 3), e.samples, e.last_seen)
                for e in session.domain.topsql.top()]
    return cols, rows


def _perf_stmt_summary(session):
    """performance_schema.events_statements_summary_by_digest (reference:
    perfschema/tables.go) — MySQL perf-schema shape over the engine's
    statement summary; latencies in picoseconds like MySQL."""
    cols = [("schema_name", _S), ("digest", _S), ("digest_text", _S),
            ("count_star", _I), ("sum_timer_wait", _I),
            ("min_timer_wait", _I), ("max_timer_wait", _I),
            ("sum_rows_sent", _I), ("sum_errors", _I),
            ("first_seen", _F), ("last_seen", _F)]
    ps = 1_000_000_000_000  # seconds → picoseconds

    def rows():
        obs = session.domain.observe
        out = []
        with obs._lock:
            items = list(obs.stmt_summary.values())
        for st in items:
            out.append((st.db.encode(), st.digest.encode(),
                        st.sample_sql.encode(), st.exec_count,
                        int(st.sum_latency * ps),
                        int((0 if st.min_latency == float("inf")
                             else st.min_latency) * ps),
                        int(st.max_latency * ps), st.sum_rows,
                        st.err_count, st.first_seen, st.last_seen))
        return out
    return cols, rows


def _metrics_summary(session):
    """metrics_schema.metrics_summary (reference:
    infoschema/metrics_schema.go — PromQL-backed there; backed by the
    engine's counter registry here)."""
    cols = [("metrics_name", _S), ("sum_value", _F), ("comment", _S)]

    def rows():
        obs = session.domain.observe
        with obs._lock:
            items = sorted(obs.counters.items())
        return [(k.encode(), float(v), b"engine counter") for k, v in items]
    return cols, rows


def _metrics_tables(session):
    """information_schema.metrics_tables: the defined-metrics registry
    (reference: infoschema/tables.go tableMetricTables)."""
    cols = [("table_name", _S), ("promql", _S), ("labels", _S),
            ("comment", _S)]

    def rows():
        obs = session.domain.observe
        with obs._lock:
            names = sorted(obs.counters)
        return [(k.encode(), f"sum({k})".encode(), b"", b"engine counter")
                for k in names]
    return cols, rows


def _zero(ft):
    if ft is _I:
        return 0
    if ft is _F:
        return 0.0
    return b""


def _coerce(v, ft):
    try:
        if ft is _I:
            return int(v or 0)
        if ft is _F:
            return float(v or 0.0)
        return ("" if v is None else str(v)).encode()
    except (TypeError, ValueError):
        return _zero(ft)


def _cluster(base_builder, kind: str):
    """A cluster_* memtable: the base table's rows from EVERY live
    worker, fetched as ``DIAG <kind>`` over each peer's direct port
    (session/diag.py cluster_fanout).  Two columns prefix the base
    schema: ``instance`` (which worker answered) and ``error`` — a dead
    peer contributes exactly one row with error="peer-lost: ..." and
    zero-valued base columns, after a bounded per-peer timeout.  Outside
    a fleet the local process answers alone as instance "local"."""
    def build(session):
        base_cols, _ = base_builder(session)
        cols = [("instance", _S), ("error", _S)] + base_cols

        def rows():
            from .diag import cluster_fanout
            out = []
            for inst, payload, err in cluster_fanout(session, kind):
                if err:
                    out.append((inst.encode(), err.encode()) + tuple(
                        _zero(ft) for _n, ft in base_cols))
                    continue
                for r in payload.get("rows", ()):
                    out.append((inst.encode(), b"") + tuple(
                        _coerce(v, ft)
                        for v, (_n, ft) in zip(r, base_cols)))
            return out
        return cols, rows
    return build


def _fragment_perf(session):
    """information_schema.tidb_fragment_perf: the shared fragment
    performance store (fabric/coord.py PERF section via fabric/perf.py)
    — fleet-aggregated count/sum/max and sketch percentiles per
    (fragment sig, row bucket, backend, duration kind), with this
    worker's own sample count alongside so "fleet > any single worker"
    is visible in one row.  Observe-only: nothing reads this to make a
    routing decision (ROADMAP item 4 is the PR that will)."""
    from ..fabric import perf
    cols = [("sig_hash", _S), ("bucket", _I), ("backend", _S),
            ("kind", _S), ("count", _I), ("sum_s", _F), ("max_s", _F),
            ("p50_s", _F), ("p99_s", _F), ("local_count", _I)]

    def rows():
        perf.flush()
        local = {(r["sig_hash"], r["bucket"], r["backend"], r["kind"]):
                 r["count"] for r in perf.local_rows()}
        out = []
        for r in perf.fleet_rows():
            key = (r["sig_hash"], r["bucket"], r["backend"], r["kind"])
            out.append((
                f"{r['sig_hash']:016x}".encode(), r["bucket"],
                perf.BACKENDS[r["backend"]].encode(),
                perf.KINDS[r["kind"]].encode(),
                r["count"], r["sum_s"], r["max_s"],
                perf.percentile(r["sketch"], r["count"], 0.50) or 0.0,
                perf.percentile(r["sketch"], r["count"], 0.99) or 0.0,
                local.get(key, 0)))
        return out
    return cols, rows


_TABLES = {
    ("information_schema", "tidb_top_sql"): _tidb_top_sql,
    ("information_schema", "metrics_tables"): _metrics_tables,
    ("performance_schema", "events_statements_summary_by_digest"):
        _perf_stmt_summary,
    ("metrics_schema", "metrics_summary"): _metrics_summary,
    ("information_schema", "schemata"): _schemata,
    ("information_schema", "tables"): _tables,
    ("information_schema", "columns"): _columns,
    ("information_schema", "statistics"): _statistics,
    ("information_schema", "engines"): _engines,
    ("information_schema", "processlist"): _processlist,
    ("information_schema", "tidb_indexes"): _tidb_indexes,
    ("information_schema", "character_sets"): _character_sets,
    ("information_schema", "collations"): _collations,
    ("information_schema", "placement_policies"): _placement_policies,
    ("information_schema", "key_column_usage"): _key_column_usage,
    ("information_schema", "slow_query"): _slow_query,
    ("information_schema", "trace_records"): _trace_records,
    ("information_schema", "statements_summary"): _statements_summary,
    ("information_schema", "cluster_slow_query"):
        _cluster(_slow_query, "slow"),
    ("information_schema", "cluster_trace_records"):
        _cluster(_trace_records, "traces"),
    ("information_schema", "cluster_statements_summary"):
        _cluster(_statements_summary, "statements"),
    ("information_schema", "cluster_processlist"):
        _cluster(_processlist, "processlist"),
    ("information_schema", "tidb_fragment_perf"): _fragment_perf,
    ("information_schema", "metrics"): _metrics,
    ("information_schema", "views"): _views,
    ("information_schema", "partitions"): _partitions,
    ("information_schema", "sequences"): _sequences,
    ("information_schema", "table_constraints"): _table_constraints,
    ("information_schema", "referential_constraints"):
        _referential_constraints,
    ("information_schema", "gc_status"): _gc_status,
}
