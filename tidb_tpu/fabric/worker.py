"""One fleet worker process: Domain + MySQL wire listener behind the
fleet's advertised port, coordinated through the shared segment.

Spawned by fabric/fleet.py as ``python -m tidb_tpu.fabric.worker`` with
env config (the fleet's spawn contract — env, not argv, so a respawn is
a bit-identical re-exec):

    TIDB_TPU_FABRIC_COORD       coordinator-file path (required unless
                                COORD_ADDR is set)
    TIDB_TPU_FABRIC_COORD_ADDR  host:port of a CoordServer — the worker
                                coordinates over TCP (fabric/coord_net)
                                instead of attaching the segment; every
                                coordinator op becomes a traced
                                cross-process hop
    TIDB_TPU_FABRIC_SLOT        this worker's slot (required)
    TIDB_TPU_FABRIC_PORT        the advertised SO_REUSEPORT port
    TIDB_TPU_FABRIC_INIT        "module:callable" data-seeding hook(domain)
    TIDB_TPU_FABRIC_GLOBALS     "name=value;..." GLOBAL sysvars at boot
    TIDB_TPU_FABRIC_FAILPOINTS  "name=action;..." chaos failpoints
    TIDB_TPU_FABRIC_HOST        simulated host id (multi-host fleets;
                                presence means "my process group IS my
                                host" — the fabric-kill-host contract)
    TIDB_TPU_COMPILE_SERVER     the separated compile server's socket

Boot order matters: the conn-id base installs BEFORE the Domain
bootstraps (internal sessions must already mint fleet-unique ids), the
coordination hooks install before the listeners open (the first admitted
fragment must already see fleet caps).  Besides the shared listener,
each worker opens a DIRECT port (ephemeral) — the operator/bench door to
one specific process: health checks, per-worker SET GLOBAL, and pinning
load in the cross-process WFQ regression.

Shutdown: SIGTERM → drain (stop accepting, wait for in-flight
connections up to the grace window, emit the worker-summary JSON line,
release the lease) → exit 0.  SIGKILL (crash, or the
``fabric-kill-worker`` chaos action) skips all of it — that is the
point: the parent respawns, the lease expires, and the segment reclaim
must make the fleet whole without this process's cooperation.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

#: lease heartbeat period; the fleet treats a lease older than
#: HEARTBEAT_S * 8 as dead (fleet.py LEASE_TIMEOUT_S)
HEARTBEAT_S = 0.25
#: drain grace for in-flight wire connections on SIGTERM
DRAIN_GRACE_S = 5.0


def _parse_kv(raw: str) -> list:
    out = []
    for part in (raw or "").split(";"):
        part = part.strip()
        if part and "=" in part:
            k, _, v = part.partition("=")
            out.append((k.strip(), v.strip()))
    return out


def main() -> int:
    coord_path = os.environ.get("TIDB_TPU_FABRIC_COORD", "")
    coord_addr = os.environ.get("TIDB_TPU_FABRIC_COORD_ADDR", "")
    slot = int(os.environ.get("TIDB_TPU_FABRIC_SLOT", "0"))
    port = int(os.environ.get("TIDB_TPU_FABRIC_PORT", "0"))
    init_spec = os.environ.get("TIDB_TPU_FABRIC_INIT", "")
    if not coord_path and not coord_addr:
        print("worker: TIDB_TPU_FABRIC_COORD not set", file=sys.stderr)
        return 2

    import tidb_tpu  # noqa: F401 — x64 + fingerprint-scoped AOT cache
    from . import conn_id_base, state
    from .coord import Coordinator
    from ..session.session import Session

    # fleet-unique conn ids BEFORE any session exists (bootstrap runs
    # internal sessions; their ids must be fleet-unique too)
    Session.set_conn_id_base(conn_id_base(slot))

    if coord_addr:
        # TCP coordination: same op surface, every call a traced hop
        # into the CoordServer process (the bench trace phase runs the
        # fleet this way to prove cross-process stitching)
        from .coord_net import NetCoordinator
        coordinator = NetCoordinator(coord_addr)
    else:
        coordinator = Coordinator.attach(coord_path)
    coordinator.claim_slot(slot)
    state.activate(coordinator, slot,
                   os.environ.get("TIDB_TPU_COMPILE_SERVER") or None)

    from ..kv import new_store
    from ..session import bootstrap_domain
    from ..server.server import MySQLServer

    wal_dir = os.environ.get("TIDB_TPU_WAL_DIR", "")

    def _boot():
        """[open store → recover → bootstrap → seed], one worker at a
        time under the durable store's init lock: the FIRST worker in
        pays the genesis writes (bootstrap + the init hook's seed
        data), later workers replay them from the shared log and skip —
        the paper's one-storage-layer bootstrap, not N independent
        Domains that merely agree by seeding discipline."""
        d = bootstrap_domain(new_store())
        for name, val in _parse_kv(
                os.environ.get("TIDB_TPU_FABRIC_GLOBALS", "")):
            d.global_vars[name] = val
        if init_spec:
            mod_name, _, fn_name = init_spec.partition(":")
            import importlib
            import inspect
            hook = getattr(importlib.import_module(mod_name), fn_name)
            seeded_key = b"m:fabric_seeded"
            seeded = bool(
                wal_dir
                and d.store.get_snapshot().get(seeded_key) is not None)
            # the hook ALWAYS runs: KV-backed seed data replicates via
            # the shared log (the hook must skip it when `seeded`), but
            # process-LOCAL state — bulk-installed columnar caches —
            # must be rebuilt in every worker
            if "seeded" in inspect.signature(hook).parameters:
                hook(d, seeded=seeded)
            else:
                hook(d)
            if wal_dir and not seeded:
                d.store.mvcc.raw_put(seeded_key, b"1")
        return d

    if wal_dir:
        from ..kv.shared_store import store_init_lock
        with store_init_lock(wal_dir):
            domain = _boot()
    else:
        domain = _boot()

    # chaos failpoints arm AFTER bootstrap/seed: a kill-at-2PC-stage
    # schedule targets SERVED traffic, not the genesis writes (and the
    # fabric-kill-worker hook only ever fires inside _run_query anyway)
    from ..utils import failpoint
    for name, action in _parse_kv(
            os.environ.get("TIDB_TPU_FABRIC_FAILPOINTS", "")):
        failpoint.enable(name, action)

    class FabricMySQLServer(MySQLServer):
        def _run_query(self, io, session, sql):
            # the process-kill chaos hook: `fabric-kill-worker` with a
            # truthy return payload SIGKILLs this worker MID-QUERY — the
            # client must see a clean connection error, the parent must
            # respawn us, and the segment reclaim must free every count
            # this process held (bench_serve fleet chaos + test_fabric)
            if failpoint.inject("fabric-kill-worker"):
                os.kill(os.getpid(), signal.SIGKILL)
            # `fabric-kill-host` takes out the whole simulated HOST: the
            # worker's process group holds every sibling on this host
            # (fleet.py spawns multi-host fleets that way), so one
            # killpg is a machine losing power mid-commit — every
            # region lease the host held expires and must fail over.
            # Outside a multi-host fleet (no TIDB_TPU_FABRIC_HOST) the
            # group may be the test runner's own, so only this process
            # dies — same failpoint, blast radius scoped to what the
            # topology actually isolates.
            if failpoint.inject("fabric-kill-host"):
                if os.environ.get("TIDB_TPU_FABRIC_HOST") is not None:
                    os.killpg(os.getpgid(0), signal.SIGKILL)
                os.kill(os.getpid(), signal.SIGKILL)
            return super()._run_query(io, session, sql)

    shared = FabricMySQLServer(domain, port=port, users={},
                               reuse_port=True).start()
    direct = FabricMySQLServer(domain, port=0, users={}).start()
    try:
        # publish the direct port for peer discovery: cluster memtables
        # (session/diag.py cluster_fanout) reach this worker's DIAG op
        # through the segment's port column; release/reclaim zero it
        coordinator.set_direct_port(slot, direct.port)
    except Exception as e:  # noqa: BLE001 — observe-only surface
        print(f"worker: direct-port publish failed: {e}", file=sys.stderr)

    stop = threading.Event()

    import logging
    hb_log = logging.getLogger("tidb_tpu.fabric.worker")

    def _min_read_ts() -> int:
        """This worker's oldest live snapshot (0 = none): the fleet GC
        floor column (kv/gcworker._fleet_min_read_ts reads the min)."""
        starts = [
            s.txn.start_ts for s in list(domain.sessions.values())
            if getattr(s, "txn", None) is not None and s.txn.valid]
        return min(starts) if starts else 0

    from . import perf as fabric_perf

    def heartbeat():
        n = 0
        while not stop.is_set():
            try:
                coordinator.heartbeat(slot)
                coordinator.set_min_read_ts(slot, _min_read_ts())
                # republish the durable commit frontier each beat: a
                # publish swallowed by a coordinator down-window is
                # repaired here, so peers' freshness waits never gate
                # on a stale column longer than one beat
                pf = getattr(domain.store.mvcc, "publish_frontier", None)
                if pf is not None:
                    pf()
                # drain buffered fragment-perf deltas into the shared
                # store (one locked merge; a no-op when nothing queued)
                fabric_perf.flush()
                n += 1
                if n % 8 == 0:
                    # peer-reclaim sweep: a crashed sibling's lease is
                    # reclaimed by whoever notices first (the parent
                    # usually wins; this covers a dead parent too)
                    coordinator.reclaim_expired(HEARTBEAT_S * 8)
                rs = state.region_store()
                if rs is not None:
                    # region leases ride the same beat: renew ours
                    # (losing one closes that store before a stale
                    # write can race the new owner), and every 8th
                    # beat sweep for a dead host's expired regions —
                    # the survivor side of host-loss failover
                    rs.heartbeat()
                    if n % 8 == 0:
                        rs.failover_expired()
            except Exception as e:  # noqa: BLE001 — a missed beat is
                #   recoverable; a dead segment means the fleet is gone
                hb_log.warning("lease heartbeat failed: %s", e)
            stop.wait(HEARTBEAT_S)

    threading.Thread(target=heartbeat, daemon=True,
                     name="fabric-heartbeat").start()

    def on_term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    print(json.dumps({"metric": "fabric_worker_ready", "slot": slot,
                      "pid": os.getpid(), "port": shared.port,
                      "direct_port": direct.port}), flush=True)
    stop.wait()

    # -- drain ---------------------------------------------------------------
    shared.shutdown()
    direct.shutdown()
    deadline = time.monotonic() + DRAIN_GRACE_S
    while ((shared.connections or direct.connections)
           and time.monotonic() < deadline):
        time.sleep(0.02)
    from ..executor import compile_service, scheduler
    summary = {
        "metric": "fabric_worker_summary", "slot": slot,
        "pid": os.getpid(),
        "drained_conns": not (shared.connections or direct.connections),
        "sched": {k: v for k, v in scheduler.snapshot().items()
                  if k in ("admitted", "queued", "fast_grants",
                           "sched_batched_fragments", "rejected_full",
                           "rejected_timeout",
                           "sched_admission_waits_ms")},
        "compile": compile_service.report_gauges(),
        "fabric": {k: v for k, v in state.snapshot().items()
                   if isinstance(v, (int, float))},
    }
    from ..kv import wal as wal_mod
    summary["wal"] = {k: v for k, v in wal_mod.snapshot().items() if v}
    print(json.dumps(summary), flush=True)
    # last perf drain while the coordinator is still attached — the
    # samples this worker buffered since the final heartbeat
    try:
        fabric_perf.flush()
    except Exception as e:  # noqa: BLE001 — observe-only, never blocks
        #   the drain
        logging.getLogger("tidb_tpu.fabric.worker").debug(
            "final perf drain failed: %s", e)
    # hooks OFF before the segment closes: session teardown + interpreter
    # exit still run residency GC callbacks, and a charge against a
    # closed coordinator would only log noise
    state.deactivate()
    # flush + close the durable store BEFORE releasing the lease: the
    # lease drop is the "my applied column no longer gates truncation"
    # signal, so the log handle must already be quiesced
    domain.store.close()
    coordinator.release_slot(slot)
    if hasattr(coordinator, "close"):  # NetCoordinator has no segment
        coordinator.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
