"""DDL execution (reference: ddl/ — doDDLJob enqueues a model.Job, the owner
worker drives the F1 schema-state machine).

Round-1 shape: every statement becomes a Job that is enqueued and then run
*synchronously* by the in-process worker — same artifact trail as the
reference (job queue + history + schema-version bumps) with single-node
semantics. The multi-step online states + backfill live in ``ddl_worker``
paths added with ADD INDEX backfill.
"""

from __future__ import annotations

import contextlib
import logging

from .errors import SchemaError, TiDBError, ErrCode
from .meta import KEY_SEQ_PREFIX, Meta
from .model import (
    ColumnInfo, DBInfo, IndexColumn, IndexInfo, Job, JobState, SchemaState,
    TableInfo,
)
from .parser import ast
from .sqltypes import FLAG_PRI_KEY, FLAG_UNSIGNED, TYPE_LONGLONG
from . import tablecodec
from .table import cast_value, convert_internal
from .utils import failpoint

log = logging.getLogger("tidb_tpu.ddl")

#: wall-clock budget for waiting out a foreign DDL owner's lease
DDL_CLAIM_BUDGET_MS = 10_000.0


@contextlib.contextmanager
def ddl_owner_lease():
    """Fleet DDL ownership: claim the coordination segment's
    epoch-fenced DDL owner cell (fabric/coord.ddl_claim) for the scope
    of one job/drain, replacing serialize-by-write-conflict as the
    cross-worker DDL serialization point.  Yields the claimed epoch
    (0 = solo / no fleet: the in-process domain ddl_lock is the only
    serialization needed).

    A live foreign lease is waited out under the bounded
    ``ddlOwnerWait`` budget; a dead owner's cell is reclaimable
    immediately after its lease times out (same liveness rule as
    region owners).  An unreachable coordinator degrades — loudly —
    to the old conflict-serialized behavior: the meta job-queue key
    is still rewritten by every DDL txn, so racing writers abort on
    conflict rather than corrupt the queue."""
    from .fabric import state as fabric_state
    from .utils.backoff import Backoffer
    from .errors import BackoffExhaustedError
    coord = fabric_state.coordinator()
    slot = fabric_state.slot() if coord is not None else -1
    if coord is None or slot < 0:
        yield 0
        return
    epoch = 0
    try:
        epoch = coord.ddl_claim(slot)
        if not epoch:
            bo = Backoffer(budget_ms=DDL_CLAIM_BUDGET_MS,
                           wall_clock=True)
            while not epoch:
                bo.backoff("ddlOwnerWait")
                epoch = coord.ddl_claim(slot)
    except BackoffExhaustedError:
        raise
    except Exception as e:  # noqa: BLE001 — segment unlinked /
        #   coordinator down-window: fall back to conflict serialization
        log.warning("ddl owner claim degraded (%s): "
                    "conflict-serialized only", e)
        yield 0
        return
    try:
        yield epoch
    finally:
        with contextlib.suppress(Exception):
            coord.ddl_release(slot)


def ddl_fence_check(epoch: int):
    """The commit-point fence of a leased DDL job: called immediately
    before the job txn commits.  If our lease was reclaimed while the
    job ran (we stalled past the lease timeout and another worker
    claimed a newer epoch), the commit must NOT land — two owners
    interleaving one schema state machine is exactly what the lease
    exists to prevent.  Unprovable (coordinator unreachable) counts as
    lost: abort rather than guess."""
    if not epoch:
        return
    from .fabric import state as fabric_state
    from .utils.backoff import LeaseExpiredError
    coord = fabric_state.coordinator()
    ok = False
    if coord is not None:
        with contextlib.suppress(Exception):
            ok = bool(coord.ddl_check(epoch))
    if not ok:
        raise LeaseExpiredError(
            f"ddl owner lease lost (epoch {epoch} reclaimed); "
            "job aborted before commit")


def ddl_lease_heartbeat(epoch: int) -> bool:
    """Renew leased DDL ownership mid-drain (long job queues,
    backfills).  Returns False when the lease is lost — the caller
    must stop driving jobs and yield to the new owner."""
    if not epoch:
        return True
    from .fabric import state as fabric_state
    coord = fabric_state.coordinator()
    slot = fabric_state.slot() if coord is not None else -1
    if coord is None or slot < 0:
        return True
    try:
        return bool(coord.ddl_heartbeat(slot, epoch))
    except Exception as e:  # noqa: BLE001 — unprovable = lost: the
        #   drain aborts loudly rather than run unfenced
        log.warning("ddl lease heartbeat unprovable: %s", e)
        return False


class DDLExecutor:
    """reference: ddl.DDL interface (ddl/ddl.go:95)."""

    def __init__(self, session):
        self.session = session

    # -- helpers ------------------------------------------------------------

    def _run_job(self, fn, job_type, schema_id=0, table_id=0, args=None):
        """Enqueue + synchronously execute a DDL job in its own txn
        (reference: ddl/ddl.go:551 doDDLJob + ddl_worker.go
        handleDDLJobQueue). Serialized against the async online-DDL worker
        via the domain DDL lock — both rewrite the meta job-queue key, and
        interleaving (e.g. DROP INDEX racing an in-flight ADD INDEX state
        machine) must not happen.  Across workers the job runs under the
        segment-leased DDL owner cell: the epoch fence immediately before
        commit guarantees a stalled owner whose lease was reclaimed can
        never land its txn on top of the new owner's."""
        store = self.session.store
        with self.session.domain.ddl_lock, ddl_owner_lease() as epoch:
            txn = store.begin()
            m = Meta(txn)
            job = Job(id=m.gen_job_id(), type=job_type, schema_id=schema_id,
                      table_id=table_id, args=args or {},
                      start_ts=txn.start_ts)
            m.enqueue_job(job)
            try:
                # chaos door: stall the owner mid-job (past the DDL
                # lease timeout another worker claims; our fence trips)
                failpoint.inject("ddl-mid-job")
                fn(m, job)
                job.state = JobState.SYNCED
                job.schema_state = SchemaState.PUBLIC
                job.schema_version = m.bump_schema_version()
                m.finish_job(job)
                ddl_fence_check(epoch)
                txn.commit()
            except Exception:
                txn.rollback()
                raise
            self.session.domain.reload_schema()
            return job

    # -- statements ---------------------------------------------------------

    def create_database(self, stmt: ast.CreateDatabaseStmt):
        infos = self.session.infoschema()
        if infos.schema_by_name(stmt.name) is not None:
            if stmt.if_not_exists:
                return
            raise SchemaError(f"Can't create database '{stmt.name}'; database exists",
                              code=ErrCode.DBCreateExists)

        def fn(m, job):
            db = DBInfo(id=m.gen_global_id(), name=stmt.name)
            job.schema_id = db.id
            m.create_database(db)
        self._run_job(fn, "create_schema")

    def drop_database(self, stmt: ast.DropDatabaseStmt):
        infos = self.session.infoschema()
        db = infos.schema_by_name(stmt.name)
        if db is None:
            if stmt.if_exists:
                return
            raise SchemaError(f"Can't drop database '{stmt.name}'; database doesn't exist",
                              code=ErrCode.DBDropExists)

        def fn(m, job):
            for t in m.list_tables(db.id):
                m.drop_table(db.id, t.id)
                self._delete_table_data(t)
            m.drop_database(db.id)
        self._run_job(fn, "drop_schema", schema_id=db.id)

    def create_table(self, stmt: ast.CreateTableStmt):
        sess = self.session
        db_name = stmt.table.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        if db is None:
            raise SchemaError(f"Unknown database '{db_name}'", code=ErrCode.BadDB)
        if stmt.temporary:
            return self._create_temporary(stmt, db_name)
        if infos.has_table(db_name, stmt.table.name):
            if stmt.if_not_exists:
                return
            raise SchemaError(f"Table '{stmt.table.name}' already exists",
                              code=ErrCode.TableExists)
        if stmt.like is not None:
            src_db = stmt.like.schema or sess.current_db()
            src = infos.table_by_name(src_db, stmt.like.name)
            tbl_builder = lambda m: _clone_table_info(src, stmt.table.name, m)
        else:
            tbl_builder = lambda m: build_table_info(stmt, m)

        def fn(m, job):
            tbl = tbl_builder(m)
            job.table_id = tbl.id
            m.create_table(db.id, tbl)
        self._run_job(fn, "create_table", schema_id=db.id)
        if stmt.select is not None:
            sess.execute(f"INSERT INTO `{db_name}`.`{stmt.table.name}` "
                         + stmt.select.restore())

    def create_view(self, stmt: ast.CreateViewStmt):
        """CREATE [OR REPLACE] VIEW: plan the defining select once to derive
        the view's column names/types; store the select text in the catalog
        (reference: ddl/ddl_api.go CreateView + planbuilder BuildDataSource
        view expansion)."""
        sess = self.session
        db_name = stmt.view.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        if db is None:
            raise SchemaError(f"Unknown database '{db_name}'",
                              code=ErrCode.BadDB)
        existing = None
        if infos.has_table(db_name, stmt.view.name):
            existing = infos.table_by_name(db_name, stmt.view.name)
            if not existing.is_view:
                raise SchemaError(f"Table '{stmt.view.name}' already exists",
                                  code=ErrCode.TableExists)
            if not stmt.or_replace:
                raise SchemaError(f"Table '{stmt.view.name}' already exists",
                                  code=ErrCode.TableExists)
        plan = sess.plan_query(stmt.select)
        names = [r.name or f"col_{i}" for i, r in enumerate(plan.schema.refs)]
        if stmt.cols:
            if len(stmt.cols) != len(names):
                raise TiDBError(
                    "View's SELECT and view's field list have different "
                    "column counts", code=ErrCode.WrongValueCountOnRow)
            names = list(stmt.cols)
        seen = set()
        for nm in names:
            if nm.lower() in seen:
                raise TiDBError(f"Duplicate column name '{nm}'",
                                code=ErrCode.DupFieldName)
            seen.add(nm.lower())
        fts = [r.ftype for r in plan.schema.refs]

        def fn(m, job):
            if existing is not None:
                m.drop_table(db.id, existing.id)
            tbl = TableInfo(id=m.gen_global_id(), name=stmt.view.name)
            for off, (nm, ft) in enumerate(zip(names, fts)):
                tbl.max_col_id += 1
                tbl.columns.append(ColumnInfo(id=tbl.max_col_id, name=nm,
                                              offset=off, ftype=ft))
            # "db" pins name resolution for the stored text: unqualified
            # tables resolve against the creation-time database, not the
            # reader's current db (reference: ViewInfo + MySQL semantics)
            tbl.view = {"select": stmt.select.restore(), "cols": names,
                        "definer": stmt.definer or sess.user,
                        "db": sess.current_db() or db_name}
            job.table_id = tbl.id
            m.create_table(db.id, tbl)
        self._run_job(fn, "create_view", schema_id=db.id)

    def _create_temporary(self, stmt: ast.CreateTableStmt, db_name: str):
        """CREATE TEMPORARY TABLE: catalog entry lives only on the session
        (reference: table/temptable — a local temp table shadows any
        permanent table of the same name and vanishes with the session).
        Rows use a real table id in the shared store, cleaned on drop."""
        sess = self.session
        key = (db_name.lower(), stmt.table.name.lower())
        if key in sess.temp_tables:
            if stmt.if_not_exists:
                return
            raise SchemaError(f"Table '{stmt.table.name}' already exists",
                              code=ErrCode.TableExists)
        store = sess.store
        txn = store.begin()
        try:
            m = Meta(txn)
            if stmt.like is not None:
                src_db = stmt.like.schema or sess.current_db()
                src = sess.infoschema().table_by_name(src_db, stmt.like.name)
                tbl = _clone_table_info(src, stmt.table.name, m)
            else:
                tbl = build_table_info(stmt, m)
            txn.commit()  # persists only the consumed global ids
        except Exception:
            txn.rollback()
            raise
        tbl.temporary = True
        sess.temp_tables[key] = tbl
        sess.temp_tables_version += 1
        if stmt.select is not None:
            sess.execute(f"INSERT INTO `{db_name}`.`{stmt.table.name}` "
                         + stmt.select.restore())

    def create_sequence(self, stmt: ast.CreateSequenceStmt):
        """reference: ddl/sequence.go onCreateSequence — a sequence is a
        row-less TableInfo whose value lives in the meta allocator."""
        sess = self.session
        db_name = stmt.name.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        if db is None:
            raise SchemaError(f"Unknown database '{db_name}'",
                              code=ErrCode.BadDB)
        if infos.has_table(db_name, stmt.name.name):
            if stmt.if_not_exists:
                return
            raise SchemaError(f"Table '{stmt.name.name}' already exists",
                              code=ErrCode.TableExists)
        o = stmt.options
        inc = int(o.get("increment", 1)) or 1
        lo = int(o.get("min", 1 if inc > 0 else -(1 << 62)))
        hi = int(o.get("max", (1 << 62) if inc > 0 else -1))
        # ascending sequences start at MINVALUE, descending at MAXVALUE
        # (reference: ddl/sequence.go default start)
        seq = {"start": int(o.get("start", lo if inc > 0 else hi)),
               "increment": inc, "min": lo, "max": hi,
               "cache": int(o.get("cache", 1000)),
               "cycle": int(o.get("cycle", 0))}
        if seq["min"] > seq["max"] or not (
                seq["min"] <= seq["start"] <= seq["max"]):
            raise TiDBError("Sequence values are conflicting",
                            code=ErrCode.SequenceRunOut)

        def fn(m, job):
            tbl = TableInfo(id=m.gen_global_id(), name=stmt.name.name)
            tbl.sequence = seq
            job.table_id = tbl.id
            m.create_table(db.id, tbl)
        self._run_job(fn, "create_sequence", schema_id=db.id)

    def drop_sequence(self, stmt: ast.DropSequenceStmt):
        sess = self.session
        infos = sess.infoschema()
        for tn in stmt.sequences:
            db_name = tn.schema or sess.current_db()
            if not infos.has_table(db_name, tn.name):
                if stmt.if_exists:
                    continue
                raise SchemaError(f"Unknown SEQUENCE: '{db_name}.{tn.name}'",
                                  code=ErrCode.BadTable)
            db = infos.schema_by_name(db_name)
            tbl = infos.table_by_name(db_name, tn.name)
            if not tbl.is_sequence:
                raise TiDBError(f"'{db_name}.{tn.name}' is not SEQUENCE",
                                code=ErrCode.WrongObjectSequence)

            def fn(m, job, _db=db, _tbl=tbl):
                m.drop_table(_db.id, _tbl.id)
                m.txn.delete(KEY_SEQ_PREFIX + str(_tbl.id).encode())
            self._run_job(fn, "drop_sequence", schema_id=db.id,
                          table_id=tbl.id)

    def drop_table(self, stmt: ast.DropTableStmt):
        sess = self.session
        # DROP VIEW resolves against the catalog only: a session temp table
        # shadowing the name is never a view and must not hide it
        infos = (sess.domain.infoschema() if stmt.is_view
                 else sess.infoschema())
        remaining = []
        for tn in stmt.tables:
            db_name = (tn.schema or sess.current_db()).lower()
            key = (db_name, tn.name.lower())
            if not stmt.is_view and key in sess.temp_tables:
                sess.drop_temp_table(key)
            elif not stmt.temporary:
                remaining.append(tn)
            elif not stmt.if_exists:
                raise SchemaError(f"Unknown table '{tn.name}'",
                                  code=ErrCode.BadTable)
        stmt = ast.DropTableStmt(tables=remaining, if_exists=stmt.if_exists,
                                 is_view=stmt.is_view)
        if not remaining:
            return
        missing = []
        for tn in stmt.tables:
            db_name = tn.schema or sess.current_db()
            if not infos.has_table(db_name, tn.name):
                missing.append(f"{db_name}.{tn.name}")
        if missing and not stmt.if_exists:
            raise SchemaError(
                f"Unknown {'view' if stmt.is_view else 'table'} "
                f"'{', '.join(missing)}'", code=ErrCode.BadTable)
        for tn in stmt.tables:
            db_name = tn.schema or sess.current_db()
            if not infos.has_table(db_name, tn.name):
                continue
            db = infos.schema_by_name(db_name)
            tbl = infos.table_by_name(db_name, tn.name)
            if stmt.is_view and not tbl.is_view:
                raise TiDBError(f"'{db_name}.{tn.name}' is not VIEW",
                                code=ErrCode.WrongObject)
            if not stmt.is_view and tbl.is_view:
                raise TiDBError(
                    f"'{db_name}.{tn.name}' is a view; use DROP VIEW",
                    code=ErrCode.WrongObject)
            if tbl.is_sequence:
                raise TiDBError(
                    f"'{db_name}.{tn.name}' is a sequence; use DROP "
                    "SEQUENCE", code=ErrCode.WrongObjectSequence)

            def fn(m, job, _db=db, _tbl=tbl):
                m.drop_table(_db.id, _tbl.id)
                if not _tbl.is_view:
                    # data deletion is DEFERRED to the GC worker past the
                    # safepoint; until then RECOVER/FLASHBACK TABLE can
                    # resurrect catalog + data (reference:
                    # ddl/delete_range.go + RecoverTable)
                    self._defer_table_data(m, _tbl, job.start_ts)
                    m.set_dropped_table(_db.id, _tbl, job.start_ts)
            self._run_job(fn, "drop_table", schema_id=db.id, table_id=tbl.id)
            # the deferred delete keeps KV data for RECOVER, but the
            # columnar cache's materialized arrays serve no one anymore
            ids = [tbl.id] + ([d.id for d in tbl.partition.defs]
                              if tbl.partition is not None else [])
            for tid in ids:
                sess.domain.columnar_cache.invalidate(tid)

    def _temp_info(self, tn: ast.TableName):
        sess = self.session
        db_name = (tn.schema or sess.current_db()).lower()
        return sess.temp_tables.get((db_name, tn.name.lower()))

    def truncate_table(self, stmt: ast.TruncateTableStmt):
        sess = self.session
        tmp = self._temp_info(stmt.table)
        if tmp is not None:
            # session-local: just clear the rows (no catalog job — a job
            # would leak the temp schema into the shared catalog)
            self._delete_table_data(tmp)
            return
        db_name = stmt.table.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        tbl = infos.table_by_name(db_name, stmt.table.name)
        if tbl.is_sequence:
            raise TiDBError(f"'{db_name}.{stmt.table.name}' is not BASE "
                            "TABLE", code=ErrCode.WrongObject)

        def fn(m, job):
            # new table id, same schema (reference: truncate allocates new id)
            new_tbl = TableInfo.from_json(tbl.to_json())
            new_tbl.id = m.gen_global_id()
            new_tbl.auto_increment = 1
            if new_tbl.partition is not None:
                for d in new_tbl.partition.defs:
                    d.id = m.gen_global_id()
            m.drop_table(db.id, tbl.id)
            self._delete_table_data(tbl)
            m.create_table(db.id, new_tbl)
            m.set_autoid(new_tbl.id, 1)
            job.table_id = new_tbl.id
        self._run_job(fn, "truncate_table", schema_id=db.id, table_id=tbl.id)

    def create_index(self, stmt: ast.CreateIndexStmt):
        """ADD INDEX runs ONLINE: the session enqueues a job and the domain's
        DDL worker walks delete-only → write-only → write-reorg → public with
        checkpointed batched backfill (tidb_tpu/ddl_worker.py; reference:
        ddl/index.go:519-541, ddl/backfilling.go:142)."""
        sess = self.session
        if self._temp_info(stmt.table) is not None:
            raise TiDBError("CREATE INDEX on a TEMPORARY table is not "
                            "supported", code=ErrCode.UnsupportedDDL)
        db_name = stmt.table.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        tbl = infos.table_by_name(db_name, stmt.table.name)
        if tbl.find_index(stmt.index_name) is not None:
            if stmt.if_not_exists:
                return
            raise TiDBError(f"Duplicate key name '{stmt.index_name}'",
                            code=ErrCode.DupKeyName)
        for cname, _len in stmt.columns:
            if tbl.find_column(cname) is None:
                raise TiDBError(f"Key column '{cname}' doesn't exist in table",
                                code=ErrCode.KeyDoesNotExist)
        if stmt.unique and tbl.partition is not None:
            # per-partition dup checks make a unique key that misses the
            # partition column unenforceable (reference: ddl/partition.go
            # checkPartitionKeysConstraint; MySQL error 1503)
            pcol = tbl.partition.col_name.lower()
            if pcol not in {c.lower() for c, _l in stmt.columns}:
                raise TiDBError(
                    "A UNIQUE INDEX must include all columns in the table's "
                    "partitioning function",
                    code=ErrCode.UniqueKeyNeedAllFieldsInPf)
        job = self.enqueue_job(
            "add_index", schema_id=db.id, table_id=tbl.id,
            args={"index_name": stmt.index_name,
                  "unique": bool(stmt.unique),
                  "columns": [[c, l] for c, l in stmt.columns]})
        sess.domain.ddl_worker.run_job(job.id)

    def enqueue_job(self, job_type, schema_id=0, table_id=0, args=None) -> Job:
        """Enqueue a job for the async worker (reference: ddl.go:551
        doDDLJob's enqueue half). Under the domain DDL lock: the queue is
        one meta KV key also rewritten by the worker's transition/batch
        txns — unserialized writers would abort each other on conflict."""
        store = self.session.store
        with self.session.domain.ddl_lock, ddl_owner_lease() as epoch:
            txn = store.begin()
            try:
                m = Meta(txn)
                job = Job(id=m.gen_job_id(), type=job_type,
                          schema_id=schema_id, table_id=table_id,
                          args=args or {}, start_ts=txn.start_ts)
                m.enqueue_job(job)
                ddl_fence_check(epoch)
                txn.commit()
            except Exception:
                txn.rollback()
                raise
            return job

    def drop_index(self, stmt: ast.DropIndexStmt):
        sess = self.session
        db_name = stmt.table.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        tbl = infos.table_by_name(db_name, stmt.table.name)
        if tbl.find_index(stmt.index_name) is None:
            if stmt.if_exists:
                return
            raise TiDBError(f"Can't DROP '{stmt.index_name}'; check that column/key exists",
                            code=ErrCode.CantDropFieldOrKey)

        # ONLINE drop: the worker walks public → write-only → delete-only
        # → none (ddl_worker.step_drop_index; reference ddl/index.go
        # onDropIndex) so concurrent txns always see a maintainable state
        job = self.enqueue_job(
            "drop_index", schema_id=db.id, table_id=tbl.id,
            args={"index_name": stmt.index_name})
        sess.domain.ddl_worker.run_job(job.id)

    def alter_table(self, stmt: ast.AlterTableStmt):
        sess = self.session
        if self._temp_info(stmt.table) is not None:
            raise TiDBError("ALTER TABLE on a TEMPORARY table is not "
                            "supported", code=ErrCode.UnsupportedDDL)
        db_name = stmt.table.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        tbl = infos.table_by_name(db_name, stmt.table.name)
        if tbl.cached and any(s[0] != "cache" for s in stmt.specs):
            # reference: cached tables must ALTER ... NOCACHE before DDL
            raise TiDBError("'ALTER TABLE' is unsupported on cache tables",
                            code=ErrCode.OptOnCacheTable)
        for spec in stmt.specs:
            kind = spec[0]
            if kind == "add_column":
                self._alter_add_column(db, tbl, spec[1], spec[2])
            elif kind == "drop_column":
                self._alter_drop_column(db, tbl, spec[1])
            elif kind == "add_index":
                con = spec[1]
                s = ast.CreateIndexStmt(
                    index_name=con.name or "_".join(c for c, _ in con.columns),
                    table=stmt.table, columns=con.columns,
                    unique=(con.kind == "unique"))
                self.create_index(s)
            elif kind == "drop_index":
                self.drop_index(ast.DropIndexStmt(index_name=spec[1],
                                                  table=stmt.table))
            elif kind == "modify_column":
                self._alter_modify_column(db, tbl, spec[1], None)
            elif kind == "change_column":
                self._alter_modify_column(db, tbl, spec[2], spec[1])
            elif kind == "rename":
                self._alter_rename(db, tbl, spec[1])
            elif kind == "auto_increment":
                def fn(m, job, _v=spec[1]):
                    m.set_autoid(tbl.id, _v)
                self._run_job(fn, "auto_increment", schema_id=db.id,
                              table_id=tbl.id)
            elif kind == "cache":
                def fn(m, job, _on=spec[1]):
                    t = m.get_table(db.id, tbl.id)
                    t.cached = _on
                    m.update_table(db.id, t)
                self._run_job(fn, "alter_cache", schema_id=db.id,
                              table_id=tbl.id)
            elif kind == "add_partition":
                self._alter_add_partition(db, tbl, spec[1])
            elif kind == "drop_partition":
                self._alter_drop_partition(db, tbl, spec[1])
            elif kind == "truncate_partition":
                self._alter_truncate_partition(db, tbl, spec[1])
            elif kind == "exchange_partition":
                self._alter_exchange_partition(db, tbl, spec[1], spec[2],
                                               spec[3] if len(spec) > 3
                                               else True)
            else:
                raise TiDBError(f"unsupported ALTER TABLE action {kind}",
                                code=ErrCode.UnsupportedDDL)
            infos = sess.infoschema()
            tbl = infos.table_by_name(db_name, stmt.table.name) \
                if infos.has_table(db_name, stmt.table.name) else tbl

    def rename_table(self, stmt: ast.RenameTableStmt):
        sess = self.session
        for old, new in stmt.pairs:
            db_name = old.schema or sess.current_db()
            infos = sess.infoschema()
            db = infos.schema_by_name(db_name)
            tbl = infos.table_by_name(db_name, old.name)
            self._alter_rename(db, tbl, new)

    def _alter_rename(self, db, tbl, new_tn):
        new_name = new_tn.name

        def fn(m, job):
            t = m.get_table(db.id, tbl.id)
            t.name = new_name
            m.update_table(db.id, t)
        self._run_job(fn, "rename_table", schema_id=db.id, table_id=tbl.id)

    def _alter_add_column(self, db, tbl, coldef, pos):
        if tbl.find_column(coldef.name) is not None:
            raise TiDBError(f"Duplicate column name '{coldef.name}'",
                            code=ErrCode.WrongFieldSpec)
        default = None
        has_default = False
        if "default" in coldef.options:
            from .expression import ExprBuilder, Schema
            e = ExprBuilder(Schema([])).build(coldef.options["default"])
            default = cast_value(e.eval_scalar(), coldef.ftype)
            has_default = True
        ci = ColumnInfo(id=0, name=coldef.name, offset=0,
                        ftype=coldef.ftype, default_value=default,
                        has_default=has_default)
        if pos and pos[0] == "after" and tbl.find_column(pos[1]) is None:
            raise TiDBError(f"Unknown column '{pos[1]}' in '{tbl.name}'",
                            code=ErrCode.BadField)
        # ONLINE add: none → delete-only → write-only → public
        # (ddl_worker.step_add_column; reference ddl/column.go
        # onAddColumn — no backfill, defaults materialize at read)
        job = self.enqueue_job(
            "add_column", schema_id=db.id, table_id=tbl.id,
            args={"column": ci.to_json(), "pos": list(pos) if pos else None})
        self.session.domain.ddl_worker.run_job(job.id)

    def _alter_modify_column(self, db, tbl, coldef, old_name):
        """MODIFY/CHANGE COLUMN with a synchronous data reorg: every stored
        row's value converts into the new representation, and indexes
        covering the column are rebuilt (reference: ddl/column.go
        onModifyColumn — the write-reorg for lossy changes)."""
        from .expression.core import phys_kind
        sess = self.session
        target = old_name or coldef.name
        col = tbl.find_column(target)
        if col is None:
            raise TiDBError(f"Unknown column '{target}' in '{tbl.name}'",
                            code=ErrCode.BadField)
        new_name = coldef.name
        if (new_name.lower() != col.name.lower()
                and tbl.find_column(new_name) is not None):
            raise TiDBError(f"Duplicate column name '{new_name}'",
                            code=ErrCode.WrongFieldSpec)
        new_ft = coldef.ftype
        if tbl.pk_is_handle and col.id == tbl.pk_col_id and not _is_int(
                ColumnInfo(ftype=new_ft)):
            raise TiDBError(
                "Unsupported modify column: the handle primary key must "
                "stay an integer type", code=ErrCode.UnsupportedDDL)
        if (tbl.partition is not None
                and tbl.partition.col_name.lower() == col.name.lower()):
            raise TiDBError(
                "Unsupported modify column: column is in the partitioning "
                "function", code=ErrCode.UnsupportedDDL)
        old_ft = col.ftype

        def fn(m, job):
            t = m.get_table(db.id, tbl.id)
            c = t.find_column(target)
            affected_idx = [i for i in t.indexes
                            if any(ic.name.lower() == c.name.lower()
                                   for ic in i.columns)]
            txn = m.txn
            phys = ([d.id for d in t.partition.defs]
                    if t.partition is not None else [t.id])
            same_repr = phys_kind(old_ft) == phys_kind(new_ft) and \
                old_ft.scale == new_ft.scale
            for pid in phys:
                start, end = tablecodec.table_range(pid)
                rows = []
                for key, value in txn.scan(start, end):
                    _tid, h = tablecodec.decode_record_key(key)
                    rows.append((h, tablecodec.decode_row(value)))
                for h, row in rows:
                    if c.id not in row:
                        # column added after this row was written: reads
                        # apply the origin default — materialize it so the
                        # reorg converts a real value, not a phantom NULL
                        cur = c.default_value if c.has_default else None
                        if cur is not None:
                            row[c.id] = cur
                    else:
                        cur = row[c.id]
                    if cur is None and new_ft.not_null and not (
                            t.pk_is_handle and c.id == t.pk_col_id):
                        # existing NULLs make a NOT NULL reorg invalid
                        # (reference: MySQL error 1265/1138)
                        raise TiDBError(
                            f"Invalid use of NULL value in column "
                            f"'{new_name}'", code=ErrCode.TruncatedWrongValue)
                    if cur is not None:
                        row[c.id] = convert_internal(cur, old_ft, new_ft)
                    if not same_repr or c.id in row:
                        col_ids = list(row)
                        txn.put(tablecodec.record_key(pid, h),
                                tablecodec.encode_row(
                                    col_ids, [row[i] for i in col_ids]))
                # rebuild covering indexes under the new representation
                for idx in affected_idx:
                    s, e = tablecodec.index_range(pid, idx.id)
                    for key, _v in txn.scan(s, e):
                        txn.delete(key)
                if affected_idx:
                    from .table import Table as _Table
                    from .partition import partition_view
                    view = (partition_view(t, next(
                        d for d in t.partition.defs if d.id == pid))
                        if t.partition is not None else t)
                    # apply the new schema before re-encoding entries
                    vc = view.find_column(target)
                    vc.ftype = new_ft
                    pt = _Table(view, txn)
                    vis = [view.find_index(idx.name) for idx in affected_idx]
                    for h, row in rows:
                        for vi in vis:
                            pt._index_put(vi, row, h, check_dup=True)
            if "default" in coldef.options:
                # a DEFAULT clause in the new definition replaces the old
                # default (reference: column definition fully re-applies)
                from .expression import ExprBuilder, Schema
                e = ExprBuilder(Schema([])).build(coldef.options["default"])
                v = e.eval_scalar()
                c.default_value = (cast_value(v, new_ft)
                                   if v is not None else None)
                c.has_default = True
            elif c.has_default and c.default_value is not None:
                c.default_value = convert_internal(c.default_value, old_ft,
                                                   new_ft)
            old_cname = c.name
            c.name = new_name
            c.ftype = new_ft
            if new_name.lower() != old_cname.lower():
                # a rename must follow the column everywhere it is named
                for idx in t.indexes:
                    for ic in idx.columns:
                        if ic.name.lower() == old_cname.lower():
                            ic.name = new_name
                for fk in t.foreign_keys:
                    fk["cols"] = [new_name if cn.lower() == old_cname.lower()
                                  else cn for cn in fk["cols"]]
                    # self-referencing FK: fix ref_cols on t's OWN object
                    # (the same-db loop below skips t — a fresh copy there
                    # would be clobbered by the final update_table(t))
                    if fk["ref_table"].lower() == t.name.lower():
                        fk["ref_cols"] = [
                            new_name if cn.lower() == old_cname.lower()
                            else cn for cn in fk["ref_cols"]]
                # ...including OTHER tables' FKs in the SAME database that
                # reference it (FK metadata stores no db qualifier;
                # references resolve same-db, so other dbs' same-named
                # tables must stay untouched)
                for ot in m.list_tables(db.id):
                    if ot.id == t.id:
                        continue
                    touched = False
                    for fk in ot.foreign_keys:
                        if fk["ref_table"].lower() != t.name.lower():
                            continue
                        nc = [new_name if cn.lower() == old_cname.lower()
                              else cn for cn in fk["ref_cols"]]
                        if nc != fk["ref_cols"]:
                            fk["ref_cols"] = nc
                            touched = True
                    if touched:
                        m.update_table(db.id, ot)
            m.update_table(db.id, t)
        self._run_job(fn, "modify_column", schema_id=db.id, table_id=tbl.id)
        self.session.store.mvcc.bump_table_version(tbl.id)
        if tbl.partition is not None:
            for d in tbl.partition.defs:
                self.session.store.mvcc.bump_table_version(d.id)

    def _alter_drop_column(self, db, tbl, name):
        col = tbl.find_column(name)
        if col is None:
            raise TiDBError(f"Can't DROP '{name}'; check that column/key exists",
                            code=ErrCode.CantDropFieldOrKey)
        for idx in tbl.indexes:
            if any(ic.name.lower() == name.lower() for ic in idx.columns):
                raise TiDBError(f"column '{name}' is covered by index '{idx.name}'",
                                code=ErrCode.UnsupportedDDL)

        def fn(m, job):
            t = m.get_table(db.id, tbl.id)
            t.columns = [c for c in t.columns if c.name.lower() != name.lower()]
            for off, c in enumerate(t.columns):
                c.offset = off
            m.update_table(db.id, t)
        self._run_job(fn, "drop_column", schema_id=db.id, table_id=tbl.id)
        self.session.store.mvcc.bump_table_version(tbl.id)

    # -- partition management (reference: ddl/partition.go) ------------------

    def _alter_add_partition(self, db, tbl, defs):
        from .partition import append_partition_def
        if tbl.partition is None:
            raise TiDBError("Partition management on a not partitioned table "
                            "is not possible",
                            code=ErrCode.PartitionMgmtOnNonpartitioned)
        if tbl.partition.type == "hash":
            raise TiDBError("ADD PARTITION requires a RANGE or LIST table",
                            code=ErrCode.OnlyOnRangeListPartition)
        col = tbl.find_column(tbl.partition.col_name)

        def fn(m, job):
            t = m.get_table(db.id, tbl.id)
            for name, kind, values in defs:
                append_partition_def(t.partition, col, name, kind, values,
                                     m.gen_global_id)
            m.update_table(db.id, t)
        self._run_job(fn, "add_partition", schema_id=db.id, table_id=tbl.id)

    def _alter_drop_partition(self, db, tbl, names):
        if tbl.partition is None:
            raise TiDBError("Partition management on a not partitioned table "
                            "is not possible",
                            code=ErrCode.PartitionMgmtOnNonpartitioned)
        if tbl.partition.type == "hash":
            raise TiDBError("DROP PARTITION requires a RANGE or LIST table",
                            code=ErrCode.OnlyOnRangeListPartition)
        dropped = []

        def fn(m, job):
            t = m.get_table(db.id, tbl.id)
            tp = t.partition
            for name in names:
                d = tp.find_def(name)
                if d is None:
                    raise TiDBError(f"Error in list of partitions to DROP",
                                    code=ErrCode.DropPartitionNonExistent)
                if len(tp.defs) == 1:
                    raise TiDBError(
                        "Cannot remove all partitions, use DROP TABLE instead",
                        code=ErrCode.DropLastPartition)
                tp.defs.remove(d)
                dropped.append(d)
            m.update_table(db.id, t)
        self._run_job(fn, "drop_partition", schema_id=db.id, table_id=tbl.id)
        for d in dropped:
            self._delete_table_data(d.id)

    def _alter_exchange_partition(self, db, tbl, pname, other_tn,
                                  validate=True):
        """ALTER TABLE t EXCHANGE PARTITION p WITH TABLE t2: swap the
        partition's physical id with the plain table's id — O(1), no data
        movement (reference: ddl/partition.go onExchangeTablePartition).
        By default every incoming row must satisfy the partition's bound
        (WITHOUT VALIDATION skips the scan, matching MySQL)."""
        sess = self.session
        if tbl.partition is None:
            raise TiDBError("Partition management on a not partitioned "
                            "table is not possible",
                            code=ErrCode.PartitionMgmtOnNonpartitioned)
        other_db_name = other_tn.schema or sess.current_db()
        infos = sess.infoschema()
        other_db = infos.schema_by_name(other_db_name)
        other = infos.table_by_name(other_db_name, other_tn.name)
        if other.partition is not None or other.is_view or other.is_sequence:
            raise TiDBError(
                "Table to exchange with partition must be a plain base "
                "table", code=ErrCode.WrongObject)

        def shape(t):
            return ([(c.name.lower(), c.ftype.tp)
                     for c in t.public_columns()],
                    # index IDS must line up too: index keys embed the id,
                    # so differently-numbered indexes would make the swapped
                    # data unreadable through the other table's index set
                    [(i.id, i.name.lower(),
                      tuple(ic.name.lower() for ic in i.columns), i.unique)
                     for i in t.indexes])
        if shape(tbl) != shape(other):
            raise TiDBError(
                "Tables have different definitions",
                code=ErrCode.UnsupportedDDL)

        def fn(m, job):
            from .partition import locate_partition, make_part_fn
            from .table import Table as _Table
            t = m.get_table(db.id, tbl.id)
            o = m.get_table(other_db.id, other.id)
            d = t.partition.find_def(pname)
            if d is None:
                raise TiDBError(f"Unknown partition '{pname}' in table "
                                f"'{t.name}'", code=ErrCode.UnknownPartition)
            if validate:
                # every incoming row must route to THIS partition
                # (reference error: ErrRowDoesNotMatchPartition)
                pf = make_part_fn(t)
                for _h, row in _Table(o, m.txn).iter_rows():
                    try:
                        target = locate_partition(t.partition, pf(row))
                    except TiDBError:
                        target = None
                    if target is None or target.id != d.id:
                        raise TiDBError(
                            "Found a row that does not match the partition",
                            code=ErrCode.RowDoesNotMatchPartition)
            # the swap IS the exchange: record/index keys stay where they
            # are, only ownership flips — autoid counters follow the ids
            a_part, a_other = m.autoid(d.id), m.autoid(o.id)
            m.set_autoid(d.id, a_other)
            m.set_autoid(o.id, a_part)
            d.id, o.id = o.id, d.id
            m.drop_table(other_db.id, other.id)
            m.create_table(other_db.id, o)
            m.update_table(db.id, t)
        self._run_job(fn, "exchange_partition", schema_id=db.id,
                      table_id=tbl.id)
        for tid in (other.id, *(d.id for d in tbl.partition.defs)):
            sess.domain.columnar_cache.invalidate(tid)
            sess.store.mvcc.bump_table_version(tid)

    def _alter_truncate_partition(self, db, tbl, names):
        if tbl.partition is None:
            raise TiDBError("Partition management on a not partitioned table "
                            "is not possible",
                            code=ErrCode.PartitionMgmtOnNonpartitioned)
        old_ids = []

        def fn(m, job):
            t = m.get_table(db.id, tbl.id)
            tp = t.partition
            for name in names:
                d = tp.find_def(name)
                if d is None:
                    raise TiDBError(f"Unknown partition '{name}' in table "
                                    f"'{t.name}'", code=ErrCode.UnknownPartition)
                old_ids.append(d.id)
                d.id = m.gen_global_id()
            m.update_table(db.id, t)
        self._run_job(fn, "truncate_partition", schema_id=db.id,
                      table_id=tbl.id)
        for oid in old_ids:
            self._delete_table_data(oid)

    def recover_table(self, stmt: ast.RecoverTableStmt):
        """RECOVER/FLASHBACK TABLE: undo a DROP whose delete-ranges the GC
        worker has not yet processed (reference: ddl/ddl_api.go
        RecoverTable — same table id, data untouched)."""
        sess = self.session
        db_name = stmt.table.schema or sess.current_db()
        infos = sess.infoschema()
        db = infos.schema_by_name(db_name)
        if db is None:
            raise SchemaError(f"Unknown database '{db_name}'",
                              code=ErrCode.BadDB)
        target_name = stmt.new_name or stmt.table.name
        if infos.has_table(db_name, target_name):
            raise SchemaError(f"Table '{target_name}' already exists",
                              code=ErrCode.TableExists)

        def fn(m, job):
            cands = [(k, dbid, t, ts) for k, dbid, t, ts in
                     m.dropped_tables()
                     if dbid == db.id
                     and t.name.lower() == stmt.table.name.lower()]
            if not cands:
                raise TiDBError(
                    f"Can't find dropped/truncated table '{stmt.table.name}'"
                    " in GC safe point", code=ErrCode.BadTable)
            _k, _dbid, tbl, _ts = max(cands, key=lambda c: c[3])
            tbl.name = target_name
            m.create_table(db.id, tbl)
            for key, rec in m.delete_ranges():
                if rec["owner"] == tbl.id:
                    m.remove_delete_range(key)
            m.remove_dropped_table(tbl.id)
            job.table_id = tbl.id
        self._run_job(fn, "recover_table", schema_id=db.id)

    # -- internals ----------------------------------------------------------

    def _defer_table_data(self, m: Meta, tbl: TableInfo, ts: int):
        """Queue every physical range of the table for GC-time deletion."""
        ids = [tbl.id]
        if tbl.partition is not None:
            ids += [d.id for d in tbl.partition.defs]
        for tid in ids:
            start, end = tablecodec.table_range(tid)
            m.enqueue_delete_range(tbl.id, start, end, ts)
            pfx = tablecodec.TABLE_PREFIX + tablecodec._enc_i64(tid)
            m.enqueue_delete_range(
                tbl.id, pfx + tablecodec.INDEX_SEP,
                pfx + tablecodec.INDEX_SEP + b"\xff" * 17, ts)

    def _delete_table_data(self, table_or_id):
        """reference: ddl/delete_range.go — here immediate range delete.
        Accepts a TableInfo (partitions cleaned too) or a bare physical id."""
        ids = [table_or_id]
        if isinstance(table_or_id, TableInfo):
            ids = [table_or_id.id]
            if table_or_id.partition is not None:
                ids += [d.id for d in table_or_id.partition.defs]
        for table_id in ids:
            start, end = tablecodec.table_range(table_id)
            self.session.store.mvcc.raw_delete_range(start, end)
            pfx = tablecodec.TABLE_PREFIX + tablecodec._enc_i64(table_id)
            self.session.store.mvcc.raw_delete_range(pfx + tablecodec.INDEX_SEP,
                                                     pfx + tablecodec.INDEX_SEP + b"\xff" * 17)
            self.session.domain.columnar_cache.invalidate(table_id)



def build_table_info(stmt: ast.CreateTableStmt, m: Meta) -> TableInfo:
    """AST → TableInfo (reference: ddl/ddl_api.go buildTableInfo)."""
    from .expression import ExprBuilder, Schema as ESchema
    tbl = TableInfo(id=m.gen_global_id(), name=stmt.table.name)
    pk_count = 0
    auto_random_req = None
    #: table-level DEFAULT CHARSET → the charset's default collation for
    #: string columns without their own COLLATE (reference:
    #: parser/charset/charset.go GetDefaultCollation)
    _CHARSET_DEFAULT_COLLATE = {
        "utf8mb4": "utf8mb4_bin", "utf8": "utf8mb4_bin",
        "gbk": "gbk_chinese_ci", "binary": "binary",
        "latin1": "latin1_bin", "ascii": "ascii_bin",
    }
    tbl_collate = None
    opt_cs = (stmt.options.get("charset") or "").lower()
    if opt_cs:
        tbl_collate = _CHARSET_DEFAULT_COLLATE.get(opt_cs)
    if stmt.options.get("collate"):
        tbl_collate = stmt.options["collate"]
    for off, cd in enumerate(stmt.columns):
        tbl.max_col_id += 1
        default = None
        has_default = False
        if "default" in cd.options:
            e = ExprBuilder(ESchema([])).build(cd.options["default"])
            v = e.eval_scalar()
            default = cast_value(v, cd.ftype) if v is not None else None
            has_default = True
        if "collate" in cd.options:
            cd.ftype.collate = cd.options["collate"]
        elif tbl_collate is not None:
            from .expression import phys_kind as _pk, K_STR as _KS
            if _pk(cd.ftype) == _KS:
                cd.ftype.collate = tbl_collate
        ci = ColumnInfo(id=tbl.max_col_id, name=cd.name, offset=off,
                        ftype=cd.ftype, default_value=default,
                        has_default=has_default,
                        comment=cd.options.get("comment", ""))
        tbl.columns.append(ci)
        if cd.options.get("primary"):
            pk_count += 1
            _set_pk(tbl, ci)
        if cd.options.get("auto_increment"):
            if not _is_int(ci):
                raise TiDBError("Incorrect column specifier for AUTO_INCREMENT",
                                code=ErrCode.WrongAutoKey)
        if "auto_random" in cd.options:
            auto_random_req = (ci, int(cd.options["auto_random"]))
        if cd.options.get("unique"):
            tbl.max_idx_id += 1
            tbl.indexes.append(IndexInfo(
                id=tbl.max_idx_id, name=cd.name, unique=True,
                columns=[IndexColumn(cd.name, off, -1)]))
    for con in stmt.constraints:
        if con.kind == "primary":
            pk_count += 1
            if pk_count > 1:
                raise TiDBError("Multiple primary key defined",
                                code=ErrCode.MultiplePriKey)
            if len(con.columns) == 1:
                ci = tbl.find_column(con.columns[0][0])
                if ci is None:
                    raise TiDBError(f"Key column '{con.columns[0][0]}' doesn't exist",
                                    code=ErrCode.KeyDoesNotExist)
                _set_pk(tbl, ci)
            if not tbl.pk_is_handle:
                # composite or non-int pk: unique index named PRIMARY
                tbl.max_idx_id += 1
                cols = []
                for cname, length in con.columns:
                    ci = tbl.find_column(cname)
                    if ci is None:
                        raise TiDBError(f"Key column '{cname}' doesn't exist",
                                        code=ErrCode.KeyDoesNotExist)
                    cols.append(IndexColumn(ci.name, ci.offset, length or -1))
                tbl.indexes.append(IndexInfo(id=tbl.max_idx_id, name="PRIMARY",
                                             unique=True, primary=True,
                                             columns=cols))
        elif con.kind in ("unique", "index"):
            idx = _build_index_info(tbl, con.name or _auto_index_name(tbl, con),
                                    con.columns, con.kind == "unique", None)
            tbl.indexes.append(idx)
        elif con.kind == "foreign":
            # stored + rendered, not enforced — the v5.x reference default
            # (ddl/foreign_key.go stores FKInfo; checks landed later)
            ref = con.ref or {}
            rt = ref.get("table")
            tbl.foreign_keys.append({
                "name": con.name or f"fk_{len(tbl.foreign_keys) + 1}",
                "cols": [c for c, _l in con.columns],
                "ref_table": rt.name if rt is not None else "",
                "ref_cols": list(ref.get("columns", [])),
                "on_delete": ref.get("on_delete", ""),
                "on_update": ref.get("on_update", ""),
            })
    if auto_random_req is not None:
        # validated AFTER constraints so a table-level PRIMARY KEY (id)
        # counts (reference: ddl_api.go autoRandomBits checks)
        ci, bits = auto_random_req
        if not (tbl.pk_is_handle and tbl.pk_col_id == ci.id):
            raise TiDBError(
                "Invalid auto random: auto_random is only for the "
                "integer primary key column", code=ErrCode.WrongAutoKey)
        if not 1 <= bits <= 15:
            raise TiDBError("Invalid auto random: shard bits must be "
                            "in [1, 15]", code=ErrCode.WrongAutoKey)
        tbl.auto_random_bits = bits
    if "auto_increment" in stmt.options:
        try:
            tbl.auto_increment = int(stmt.options["auto_increment"])
        except (TypeError, ValueError):
            pass
    if stmt.partition is not None:
        from .partition import build_partition_info, check_partition_keys
        tbl.partition = build_partition_info(stmt.partition, tbl,
                                             m.gen_global_id)
        check_partition_keys(tbl)
    return tbl


def _auto_index_name(tbl, con):
    base = con.columns[0][0]
    names = {i.name.lower() for i in tbl.indexes}
    name = base
    n = 2
    while name.lower() in names:
        name = f"{base}_{n}"
        n += 1
    return name


def _build_index_info(tbl: TableInfo, name, columns, unique, m) -> IndexInfo:
    tbl.max_idx_id += 1
    cols = []
    for cname, length in columns:
        ci = tbl.find_column(cname)
        if ci is None:
            raise TiDBError(f"Key column '{cname}' doesn't exist in table",
                            code=ErrCode.KeyDoesNotExist)
        cols.append(IndexColumn(ci.name, ci.offset, length or -1))
    return IndexInfo(id=tbl.max_idx_id, name=name, unique=unique, columns=cols)


def _set_pk(tbl: TableInfo, ci: ColumnInfo):
    if _is_int(ci):
        tbl.pk_is_handle = True
        tbl.pk_col_id = ci.id
        ci.ftype.flag |= FLAG_PRI_KEY
        from .sqltypes import FLAG_NOT_NULL
        ci.ftype.flag |= FLAG_NOT_NULL
    else:
        tbl.max_idx_id += 1
        tbl.indexes.append(IndexInfo(
            id=tbl.max_idx_id, name="PRIMARY", unique=True, primary=True,
            columns=[IndexColumn(ci.name, ci.offset, -1)]))


def _is_int(ci: ColumnInfo) -> bool:
    from .sqltypes import INT_TYPES
    return ci.ftype.tp in INT_TYPES


def _clone_table_info(src: TableInfo, new_name: str, m: Meta) -> TableInfo:
    t = TableInfo.from_json(src.to_json())
    t.id = m.gen_global_id()
    t.name = new_name
    t.auto_increment = 1
    if t.partition is not None:
        for d in t.partition.defs:
            d.id = m.gen_global_id()
    return t
