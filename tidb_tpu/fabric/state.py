"""This process's fabric identity + the ``fabric_*`` gauges.

A worker process calls :func:`activate` once at boot (fabric/worker.py)
with its slot id and an attached :class:`~tidb_tpu.fabric.coord.Coordinator`;
that installs the cross-process hooks into the in-process layers:

* the admission scheduler's fleet hook (fleet-wide per-tenant running
  caps + the shared WFQ virtual clocks),
* the residency ledger's fleet hook (per-tenant HBM charges published to
  the segment; tenant shares read fleet-wide),
* the fragment-dedup handle consulted by device_exec.run_device,
* the span tracer's process label (trace post-mortems name the worker
  that served the statement — the "tracing context across process hops"
  anchor: dedup waits and remote compiles tag the owning slot next to
  this label).

Everything is a no-op in the ordinary single-process deployment:
``active()`` is False, every hook stays None, and ``report_gauges()``
returns ``{}`` so EXPLAIN ANALYZE annotations carry no fabric noise.

Gauges — ``fabric_workers`` (live leases), ``fabric_respawns`` (parent
restart counter), ``fabric_dedup_hits`` (fleet-wide follower reuses),
``fabric_compile_rtt_ms`` (last compile-server round trip) — surface in
EXPLAIN ANALYZE annotations (exec_select splats ``report_gauges()``),
``/status`` (``device_fabric`` payload) and ``/metrics``.
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger("tidb_tpu.fabric.state")

_LOCK = threading.Lock()

#: the active fabric context: [coordinator, slot, compile_server_addr]
_CTX = [None, -1, None]
_DEDUP = [None]
#: the process's region router (fabric/region.RegionStore) when the
#: keyspace is region-sharded; worker heartbeats drive its lease
#: renewal + expired-lease failover sweep through this handle
_REGIONS = [None]

#: process-local fabric counters (the segment holds the fleet-global
#: ones; these attribute THIS worker's share for its /status payload)
STATS = {
    "fabric_dedup_hits": 0,        # followers served from a peer's page
    "fabric_dedup_leads": 0,       # dedup slots this worker led
    "fabric_dedup_waits": 0,       # dispatches that waited on a peer
    "fabric_dedup_timeouts": 0,    # waits that fell back to local compute
    "fabric_remote_compiles": 0,   # compiles served by the compile server
    "fabric_artifact_hits": 0,     # pipelines deserialized from artifacts
    "fabric_remote_errors": 0,     # compile-server transport failures
    "fabric_compile_rtt_ms": 0.0,  # last compile-server round trip
    "cache_hits": 0,               # versioned result-cache serves
    "cache_invalidations": 0,      # hits refused on a version advance
    "cache_delta_folds": 0,        # hits served by folding the WAL delta
    "cache_stale_reads": 0,        # page-level vv verify caught staleness
    "freshness_waits": 0,          # reads that blocked on the fleet frontier
    "freshness_timeouts": 0,       # waits that blew the budget (9011 raised)
    "freshness_stale_ok": 0,       # reads explicitly downgraded to stale_ok
}


def activate(coordinator, slot: int, compile_server: "str | None" = None,
             lease_hbm: bool = True):
    """Install the fleet hooks for this process (fabric/worker.py boot;
    tests activate in-process)."""
    from . import dedup as dedup_mod
    with _LOCK:
        _CTX[0] = coordinator
        _CTX[1] = int(slot)
        _CTX[2] = compile_server
        coordinator.set_claim_owner(int(slot))
        _DEDUP[0] = dedup_mod.Dedup(coordinator, int(slot))
    from ..executor import scheduler
    scheduler.set_fleet(_SchedFleet(coordinator, int(slot)))
    if lease_hbm:
        from ..ops import residency
        residency.set_fleet(_ResidencyFleet(coordinator, int(slot)))
    from ..session import tracing
    tracing.set_process_label(f"slot{int(slot)}")


def deactivate():
    with _LOCK:
        _CTX[0] = None
        _CTX[1] = -1
        _CTX[2] = None
        _DEDUP[0] = None
        _REGIONS[0] = None
    from ..executor import scheduler
    scheduler.set_fleet(None)
    from ..ops import residency
    residency.set_fleet(None)
    from ..session import tracing
    tracing.set_process_label("")


def active() -> bool:
    return _CTX[0] is not None


def coordinator():
    return _CTX[0]


def slot() -> int:
    return _CTX[1]


def compile_server_addr() -> "str | None":
    """The fleet compile server's socket address, or None.  Worker env
    (TIDB_TPU_COMPILE_SERVER) wins so a standalone process — no fleet —
    can still point at a host-shared compile server."""
    import os
    return os.environ.get("TIDB_TPU_COMPILE_SERVER") or _CTX[2]


def dedup_handle():
    """The fragment-dedup handle (device_exec.run_device consults this
    for batch_key'd dispatches), or None outside a fleet."""
    return _DEDUP[0]


def set_region_store(rs):
    """Register (or clear, with None) this process's region router —
    the worker heartbeat thread then renews its leases and sweeps
    expired siblings' regions for failover."""
    with _LOCK:
        _REGIONS[0] = rs


def region_store():
    return _REGIONS[0]


def host() -> "int | None":
    """The simulated host id this worker runs on (fleet.py spawns
    multi-host fleets with TIDB_TPU_FABRIC_HOST), or None."""
    import os
    h = os.environ.get("TIDB_TPU_FABRIC_HOST")
    return int(h) if h is not None else None


def bump(key: str, n=1):
    with _LOCK:
        STATS[key] += n


def note_rtt(ms: float):
    with _LOCK:
        STATS["fabric_compile_rtt_ms"] = round(ms, 2)


# -- the cross-process hooks --------------------------------------------------

class _SchedFleet:
    """executor/scheduler.py's view of the segment: fleet-wide per-tenant
    running caps (atomic check+charge) and the shared WFQ clocks."""

    def __init__(self, coordinator, slot: int):
        self._c = coordinator
        self._slot = slot

    def try_acquire(self, group: str, cap: int) -> bool:
        return self._c.try_acquire_running(self._slot, group, cap)

    def release(self, group: str):
        self._c.release_running(self._slot, group)

    def vtimes(self, groups) -> dict:
        return self._c.vtimes(groups)

    def advance(self, group: str, delta: float, floor: float):
        self._c.vtime_advance(group, delta, floor)


class _ResidencyFleet:
    """ops/residency.py's view: per-tenant HBM charges published to the
    segment; a tenant's share consumption is read fleet-wide."""

    def __init__(self, coordinator, slot: int):
        self._c = coordinator
        self._slot = slot

    def charge(self, group: str, delta: int):
        self._c.charge_hbm(self._slot, group, delta)

    def remote_bytes(self, group: str) -> int:
        return self._c.hbm_remote_bytes(group, self._slot)


# -- gauges -------------------------------------------------------------------

def snapshot() -> dict:
    """The ``device_fabric`` /status payload: this worker's counters plus
    the fleet-global segment view when attached."""
    with _LOCK:
        out = dict(STATS)
        c, s = _CTX[0], _CTX[1]
    out["slot"] = s
    out["active"] = c is not None
    if c is not None:
        try:
            fleet = c.counters()
            out["fabric_workers"] = len(c.live_slots())
            out["fabric_respawns"] = fleet["fabric_respawns"]
            out["fleet_dedup_hits"] = fleet["fabric_dedup_hits"]
            out["fabric_lease_reclaims"] = fleet["fabric_lease_reclaims"]
            out["fabric_prewarm_dedup"] = fleet["fabric_prewarm_dedup"]
            out["fleet_cache_hits"] = fleet.get("fabric_cache_hits", 0)
            out["fleet_cache_invalidations"] = fleet.get(
                "fabric_cache_invalidations", 0)
            out["fleet_cache_delta_folds"] = fleet.get(
                "fabric_cache_delta_folds", 0)
            out["fleet_cache_stale_reads"] = fleet.get(
                "fabric_cache_stale_reads", 0)
        except Exception as e:  # noqa: BLE001 — segment may be unlinked
            log.debug("fleet counters unreadable: %s", e)
            out["fabric_workers"] = 0
        try:
            seg = c.snapshot()
            out["fabric_perf_rows"] = seg.get("perf_rows_used", 0)
            out["fabric_perf_samples"] = seg.get("perf_samples", 0)
            out["fabric_perf_dropped"] = seg.get("fabric_perf_dropped", 0)
        except Exception as e:  # noqa: BLE001 — same degrade as above
            log.debug("perf-store counters unreadable: %s", e)
    # this process's share of the shared fragment-perf store
    from . import perf as _perf
    out["perf_store"] = _perf.stats()
    return out


def report_gauges() -> dict:
    """EXPLAIN ANALYZE / bench surfacing (same fired-only policy as the
    scheduler/compile-service reports).  Empty outside a fleet, so
    single-process plans carry zero fabric noise."""
    if not active():
        return {}
    s = snapshot()
    out = {"fabric_workers": s.get("fabric_workers", 0)}
    for k in ("fabric_dedup_hits", "fabric_dedup_waits",
              "fabric_artifact_hits", "fabric_remote_compiles",
              "fabric_remote_errors", "fabric_respawns",
              "cache_hits", "cache_invalidations",
              "cache_delta_folds", "cache_stale_reads",
              "freshness_waits", "freshness_timeouts",
              "freshness_stale_ok"):
        if s.get(k):
            out[k] = s[k]
    if s.get("fabric_compile_rtt_ms"):
        out["fabric_compile_rtt_ms"] = s["fabric_compile_rtt_ms"]
    return out


def reset_for_tests():
    with _LOCK:
        for k in STATS:
            STATS[k] = 0.0 if k == "fabric_compile_rtt_ms" else 0
