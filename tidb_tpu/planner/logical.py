"""Logical plan operators (reference: planner/core/logical_plans.go)."""

from __future__ import annotations

from ..expression import Schema


class LogicalPlan:
    def __init__(self, children, schema: Schema):
        self.children = children
        self.schema = schema

    @property
    def child(self):
        return self.children[0]

    def explain_name(self):
        return type(self).__name__

    def explain_info(self):
        return ""


class DataSource(LogicalPlan):
    """Columnar table scan (reference: planner/core DataSource →
    PhysicalTableReader; the cop-pushdown boundary becomes host↔TPU)."""

    def __init__(self, db_name, table_info, col_infos, schema, alias=""):
        super().__init__([], schema)
        self.db_name = db_name
        self.table_info = table_info
        self.col_infos = col_infos      # ColumnInfo list parallel to schema
        self.alias = alias
        self.pushed_conds = []          # filters evaluated at scan
        self.access = None              # planner/access.py descriptor
        self.access_est = None          # estimated rows via the access path
        self.partitions = None          # [PartitionDef] to scan (None: not partitioned)
        self.index_hints = []           # [(use|force|ignore, [index names])]

    def explain_name(self):
        if self.access is not None:
            kind = self.access[0]
            if kind in ("point_pk", "point_index"):
                return "PointGet"
            if kind in ("batch_pk", "batch_index"):
                return "BatchPointGet"
            if kind == "index_merge":
                return "IndexMerge"
            return "IndexLookUp"
        return "TableScan"

    def explain_info(self):
        s = f"table:{self.alias or self.table_info.name}"
        if self.table_info.partition is not None:
            all_defs = self.table_info.partition.defs
            sel = self.partitions if self.partitions is not None else all_defs
            if len(sel) == len(all_defs):
                s += ", partition:all"
            else:
                s += ", partition:" + ",".join(d.name for d in sel)
        if self.access is not None:
            kind = self.access[0]
            if kind == "point_pk":
                s += f", handle:{self.access[1]}"
            elif kind == "point_index":
                s += f", index:{self.access[1].name}"
            elif kind == "batch_pk":
                s += f", handles:{len(self.access[1])}"
            elif kind == "batch_index":
                s += f", index:{self.access[1].name}, keys:{len(self.access[2])}"
            elif kind == "index_merge":
                parts = ",".join(
                    ("handle" if sub[0] == "point_pk" else sub[1].name)
                    for sub in self.access[1])
                s += f", union:[{parts}], est_rows:{self.access_est}"
            else:
                _k, idx, lo, hi = self.access
                s += (f", index:{idx.name}, range:[{lo},{hi}]"
                      f", est_rows:{self.access_est}")
        if self.pushed_conds:
            s += ", filter:" + " AND ".join(repr(c) for c in self.pushed_conds)
        return s


class MemSource(LogicalPlan):
    """information_schema / memtable source (reference: infoschema/tables.go)."""

    def __init__(self, db_name, table_name, schema, rows_fn):
        super().__init__([], schema)
        self.db_name = db_name
        self.table_name = table_name
        self.rows_fn = rows_fn  # () -> list of row tuples (internal values)

    def explain_name(self):
        return "MemTableScan"

    def explain_info(self):
        return f"table:{self.table_name}"


class Dual(LogicalPlan):
    """One-row, zero-column source (SELECT without FROM)."""

    def __init__(self):
        super().__init__([], Schema([]))

    def explain_name(self):
        return "TableDual"


class Selection(LogicalPlan):
    def __init__(self, child, conds):
        super().__init__([child], child.schema)
        self.conds = conds

    def explain_info(self):
        return " AND ".join(repr(c) for c in self.conds)


class Projection(LogicalPlan):
    def __init__(self, child, exprs, schema):
        super().__init__([child], schema)
        self.exprs = exprs

    def explain_info(self):
        return ", ".join(repr(e) for e in self.exprs)


class Join(LogicalPlan):
    """kinds: inner | left | right | semi | anti | leftouter_semi."""

    def __init__(self, left, right, kind, schema):
        super().__init__([left, right], schema)
        self.kind = kind
        self.left_keys = []    # exprs over left schema
        self.right_keys = []   # exprs over right schema
        self.other_conds = []  # exprs over concat schema, applied post-match
        self.join_algo = "hash"   # hash | merge | index (planner/physical.py)
        self.index_join = None    # ("pk",) | ("index", IndexInfo) descriptor
        self.join_cost = None         # chosen variant's estimated cost
        self.cost_candidates = None   # {algo: cost} the chooser compared

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def explain_name(self):
        if not self.left_keys:
            return "NestedLoopJoin"
        return {"merge": "MergeJoin", "index": "IndexJoin"}.get(
            self.join_algo, "HashJoin")

    def explain_info(self):
        s = self.kind
        if self.left_keys:
            pairs = ", ".join(f"{l!r}=={r!r}" for l, r in
                              zip(self.left_keys, self.right_keys))
            s += f", equal:[{pairs}]"
        if self.index_join is not None:
            s += (", inner:handle" if self.index_join[0] == "pk"
                  else f", inner:index:{self.index_join[1].name}")
        if self.other_conds:
            s += ", other:" + " AND ".join(repr(c) for c in self.other_conds)
        return s


class Aggregation(LogicalPlan):
    def __init__(self, child, group_exprs, aggs, schema):
        super().__init__([child], schema)
        self.group_exprs = group_exprs
        self.aggs = aggs  # [AggFuncDesc]
        # set by push_topn_into_agg: ([(output idx, desc)], fetch bound) —
        # a TopN above only needs this many candidate groups, so the
        # device fragment fetches just those instead of every group
        self.topn_fetch = None
        self.agg_hint = None  # 'hash' | 'stream' from /*+ HASH_AGG/STREAM_AGG */

    def explain_name(self):
        return "StreamAgg" if self.agg_hint == "stream" else "HashAgg"

    def explain_info(self):
        return (f"group by:[{', '.join(map(repr, self.group_exprs))}], "
                f"funcs:[{', '.join(map(repr, self.aggs))}]")


class Sort(LogicalPlan):
    def __init__(self, child, by):  # by: [(expr, desc)]
        super().__init__([child], child.schema)
        self.by = by

    def explain_info(self):
        return ", ".join(f"{e!r}{' desc' if d else ''}" for e, d in self.by)


class TopN(LogicalPlan):
    def __init__(self, child, by, offset, count):
        super().__init__([child], child.schema)
        self.by = by
        self.offset = offset
        self.count = count

    def explain_info(self):
        return (", ".join(f"{e!r}{' desc' if d else ''}" for e, d in self.by)
                + f", offset:{self.offset}, count:{self.count}")


class Limit(LogicalPlan):
    def __init__(self, child, offset, count):
        super().__init__([child], child.schema)
        self.offset = offset
        self.count = count

    def explain_info(self):
        return f"offset:{self.offset}, count:{self.count}"


class SetOp(LogicalPlan):
    """kinds: union | union_all | intersect | except."""

    def __init__(self, children, kind, schema):
        super().__init__(children, schema)
        self.kind = kind

    def explain_name(self):
        return {"union": "Union", "union_all": "UnionAll",
                "intersect": "Intersect", "except": "Except"}[self.kind]


class WinFuncDesc:
    """One window function over the node's (partition, order) spec.
    frame: None (default frame) or ("rows", (kind, n), (kind, n))."""

    __slots__ = ("name", "args", "ftype", "frame")

    def __init__(self, name, args, ftype, frame=None):
        self.name = name
        self.args = args          # built exprs over the child schema
        self.ftype = ftype
        self.frame = frame

    def __repr__(self):
        s = f"{self.name}({', '.join(map(repr, self.args))})"
        if self.frame is not None:
            s += f" {self.frame[0]}[{self.frame[1]}..{self.frame[2]}]"
        return s


class Window(LogicalPlan):
    """One OVER() spec; stacked Window nodes handle differing specs in one
    query (reference: planner/core/logical_plans.go LogicalWindow)."""

    def __init__(self, child, funcs, partition_exprs, order_by, schema):
        super().__init__([child], schema)
        self.funcs = funcs              # [WinFuncDesc]
        self.partition_exprs = partition_exprs
        self.order_by = order_by        # [(expr, desc)]

    def explain_name(self):
        return "Window"

    def explain_info(self):
        s = ", ".join(map(repr, self.funcs))
        if self.partition_exprs:
            s += " partition by:[" + ", ".join(
                map(repr, self.partition_exprs)) + "]"
        if self.order_by:
            s += " order by:[" + ", ".join(
                f"{e!r}{' desc' if d else ''}" for e, d in self.order_by) + "]"
        return s


def explain_nodes(plan: LogicalPlan, depth=0, out=None):
    """Flatten the plan as (rendered id, info, node) rows."""
    if out is None:
        out = []
    prefix = ("  " * depth + "└─") if depth else ""
    out.append((prefix + plan.explain_name(), plan.explain_info(), plan))
    for c in plan.children:
        explain_nodes(c, depth + 1, out)
    return out


def explain_tree(plan: LogicalPlan, depth=0, out=None):
    """Render the plan as EXPLAIN rows (id, info)."""
    return [(name, info) for name, info, _ in explain_nodes(plan, depth)]
