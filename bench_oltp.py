"""Fleet OLTP chaos bench (ISSUE 19): a TPC-C-shaped NewOrder/Payment
mix across N worker processes over the serving fabric, group-commit WAL
(``tidb_wal_fsync = 'interval'``), with per-round consistency invariants
and kill/stall chaos.

What one run asserts, every round:

* **money conservation** — Payment moves ``amt`` into ``w_ytd`` AND
  ``d_ytd`` AND out of ``c_balance`` atomically, so in any single
  snapshot ``sum(w_ytd) == sum(d_ytd) == -sum(c_balance)``;
* **order/sequence atomicity** — NewOrder's district-counter increment
  and its order insert commit together:
  ``sum(d_next_o_id) - n_districts == count(orders)``;
* **acked rows survive** — every client-acked NewOrder key is re-read
  after each chaos event (including from the respawned worker, which
  recovered the shared log from scratch);
* **read your peers' writes** — a marker committed under fsync
  ``commit`` on worker A is visible to a SINGLE immediate read on every
  other worker: the reader's ts acquisition waits on the fleet committed
  frontier (kv/shared_store.fresh_read_ts).  A value older than the
  marker is a SILENT STALE READ and fails the run unless the worker
  loudly annotated the downgrade (freshness_stale_ok); a classified
  9011 refusal is loud and therefore clean.

Chaos rounds: SIGKILL one worker mid-mix (measures respawn + recovery
wall clock), then SIGSTOP-stall one worker under load (survivors must
keep serving; the resumed worker must catch up and pass the peer-read
probe).  Freshness-wait latency (p50/p99) is aggregated from every
worker's ``freshness_wait_seconds`` histogram over DIAG metrics.

CLI: ``python bench_oltp.py --procs 3 --smoke`` is the fixed-seed CI
preset (tier-1 via tests/test_serve.py); it emits one ``serve_oltp``
JSON summary line and appends it to bench_history.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import sys
import threading
import time

import tidb_tpu  # noqa: F401  (x64 on)

#: TPC-C-shaped corpus dimensions (tiny on purpose: the CONTENTION is
#: the workload — a handful of district rows shared by every client is
#: what produces cross-worker write conflicts)
N_WH = 2
N_DIST = 4          # districts per warehouse
N_CUST = 10         # customers per district
N_DISTRICTS = N_WH * N_DIST

#: conflict-class error codes: the clean retryable outcomes of two
#: workers racing one district row (WriteConflict / TxnRetryable /
#: resolved-lock dup insert)
CONFLICT_CODES = (9007, 8002, 1062)
#: the loud classified stale-read refusal (errors.FreshnessWaitError)
FRESHNESS_CODE = 9011

RESPAWN_BUDGET_S = 30.0
#: SIGSTOP stall length: long enough to stall mid-2PC writes, short of
#: the 2s fleet lease timeout (a reclaimed slot would turn the stall
#: round into a second kill round)
STALL_S = 1.0
#: bound for the eventual-visibility probe under fsync 'interval'
#: (frontier publish trails a client ack by <= one flush period; 2s is
#: ~100 flush periods of slack)
CONVERGE_S = 2.0

_EMIT_LOCK = threading.Lock()


def _emit(obj) -> None:
    with _EMIT_LOCK:
        print(json.dumps(obj), flush=True)


def _pctl(sorted_vals, q: float):
    if not sorted_vals:
        return None
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return round(sorted_vals[i], 2)


def _dk(w: int, d: int) -> int:
    return w * 100 + d


def _ck(w: int, d: int, c: int) -> int:
    return _dk(w, d) * 1000 + c


def _ok(dk: int, o_id: int) -> int:
    return dk * 100000 + o_id


def _oltp_seed(domain, seeded: bool = False):
    """Worker-side data init (TIDB_TPU_FABRIC_INIT hook).  Pure KV:
    under the durable shared store only the FIRST worker writes; the
    rest replay the schema and rows from the shared log."""
    from tidb_tpu.testkit import TestKit
    if seeded:
        return
    tk = TestKit(domain)
    tk.must_exec("use test")
    tk.must_exec("create table warehouse (w_id int primary key, "
                 "w_ytd int)")
    tk.must_exec("create table district (d_key int primary key, "
                 "w_id int, d_next_o_id int, d_ytd int)")
    tk.must_exec("create table customer (c_key int primary key, "
                 "c_balance int)")
    tk.must_exec("create table orders (o_key int primary key, "
                 "d_key int, o_entry int)")
    tk.must_exec("create table marker (id int primary key, v int)")
    tk.must_exec("insert into warehouse values " + ",".join(
        f"({w}, 0)" for w in range(1, N_WH + 1)))
    tk.must_exec("insert into district values " + ",".join(
        f"({_dk(w, d)}, {w}, 1, 0)"
        for w in range(1, N_WH + 1) for d in range(1, N_DIST + 1)))
    tk.must_exec("insert into customer values " + ",".join(
        f"({_ck(w, d, c)}, 0)"
        for w in range(1, N_WH + 1) for d in range(1, N_DIST + 1)
        for c in range(1, N_CUST + 1)))
    tk.must_exec("insert into marker values (1, 0)")


def _conn(port):
    from tidb_tpu.fabric.client import FleetClient
    c = FleetClient(port)
    c.must_exec("use test")
    return c


def _diag(port, kind: str) -> dict:
    """One worker's DIAG payload (empty on an unreachable peer — the
    stats feed must never fail a run)."""
    try:
        from tidb_tpu.fabric.client import FleetClient
        c = FleetClient(port, timeout=5.0)
        try:
            c.must_exec("use test")
            _cols, rows = c.must_query(f"DIAG {kind}")
            return json.loads(rows[0][0])
        finally:
            c.close()
    except Exception:  # noqa: BLE001 — diagnostics-only feed
        return {}


def _hist_pctls(merged_bounds, merged_counts, qs):
    """Percentiles from a cumulative-free bucket histogram: the value of
    a quantile is its bucket's UPPER bound (the /metrics convention);
    the overflow bucket reports the top bound."""
    total = sum(merged_counts)
    out = []
    for q in qs:
        if total == 0:
            out.append(0.0)
            continue
        rank = q * total
        acc = 0
        val = merged_bounds[-1]
        for i, n in enumerate(merged_counts):
            acc += n
            if acc >= rank:
                val = merged_bounds[min(i, len(merged_bounds) - 1)]
                break
        out.append(val)
    return out


class _Stats:
    """Shared mutable run state (one lock, bumped from client threads)."""

    def __init__(self):
        self.mu = threading.Lock()
        self.counts = {"new_order_ok": 0, "payment_ok": 0,
                       "conflicts": 0, "clean_errors": 0,
                       "freshness_refusals": 0, "wire_drops": 0,
                       "write_attempts": 0}
        self.read_ms: list = []
        self.txn_ms: list = []
        self.acked_orders: list = []   # committed o_key values
        self.violations: list = []

    def bump(self, key, n=1):
        with self.mu:
            self.counts[key] += n

    def violate(self, what):
        with self.mu:
            self.violations.append(what)


def _classified(c, st: _Stats, sql_steps) -> bool:
    """Run a txn's statements; True on commit-acked.  An 'err' outcome
    is classified: conflict codes count toward the conflict rate, 9011
    is the loud freshness refusal, anything else a clean error.  The
    txn is rolled back on any error (best-effort; the server also
    rolls back on connection teardown)."""
    for sql in sql_steps:
        kind, payload = c.query(sql)
        if kind == "err":
            code = payload[0]
            if code in CONFLICT_CODES:
                st.bump("conflicts")
            elif code == FRESHNESS_CODE:
                st.bump("freshness_refusals")
            else:
                st.bump("clean_errors")
            c.query("rollback")
            return False
        if kind == "rows" and not payload[1]:
            # read step found no row (e.g. district mid-conflict):
            # treat as a clean abort, not a crash
            st.bump("clean_errors")
            c.query("rollback")
            return False
    return True


def _new_order(c, st: _Stats, rng) -> None:
    w = rng.randrange(1, N_WH + 1)
    dk = _dk(w, rng.randrange(1, N_DIST + 1))
    st.bump("write_attempts")
    t0 = time.monotonic()
    kind, payload = c.query("begin")
    if kind == "err":
        st.bump("clean_errors")
        return
    kind, payload = c.query(
        f"select d_next_o_id from district where d_key = {dk}")
    if kind != "rows" or not payload[1]:
        st.bump("clean_errors")
        c.query("rollback")
        return
    o_id = int(payload[1][0][0])
    ok = _classified(c, st, (
        f"update district set d_next_o_id = {o_id + 1} "
        f"where d_key = {dk}",
        f"insert into orders values ({_ok(dk, o_id)}, {dk}, "
        f"{int(time.time())})",
        "commit",
    ))
    if ok:
        with st.mu:
            st.counts["new_order_ok"] += 1
            st.acked_orders.append(_ok(dk, o_id))
            st.txn_ms.append((time.monotonic() - t0) * 1000.0)


def _payment(c, st: _Stats, rng) -> None:
    w = rng.randrange(1, N_WH + 1)
    d = rng.randrange(1, N_DIST + 1)
    ck = _ck(w, d, rng.randrange(1, N_CUST + 1))
    amt = rng.randrange(1, 50)
    st.bump("write_attempts")
    t0 = time.monotonic()
    kind, _ = c.query("begin")
    if kind == "err":
        st.bump("clean_errors")
        return
    ok = _classified(c, st, (
        f"update warehouse set w_ytd = w_ytd + {amt} where w_id = {w}",
        f"update district set d_ytd = d_ytd + {amt} "
        f"where d_key = {_dk(w, d)}",
        f"update customer set c_balance = c_balance - {amt} "
        f"where c_key = {ck}",
        "commit",
    ))
    if ok:
        with st.mu:
            st.counts["payment_ok"] += 1
            st.txn_ms.append((time.monotonic() - t0) * 1000.0)


def _point_read(c, st: _Stats, rng) -> None:
    w = rng.randrange(1, N_WH + 1)
    d = rng.randrange(1, N_DIST + 1)
    t0 = time.monotonic()
    kind, payload = c.query(
        f"select d_next_o_id, d_ytd from district "
        f"where d_key = {_dk(w, d)}")
    if kind == "err":
        if payload[0] == FRESHNESS_CODE:
            st.bump("freshness_refusals")
        else:
            st.bump("clean_errors")
        return
    with st.mu:
        st.read_ms.append((time.monotonic() - t0) * 1000.0)


def _mix_round(fleet, st: _Stats, *, n_threads, n_ops, seed, round_no,
               live_slots, chaos: bool):
    """One round of the NewOrder/Payment/read mix, client threads spread
    over the live workers' direct ports."""
    from tidb_tpu.fabric.client import WireError

    def worker(tid):
        rng = random.Random((seed << 16) ^ (round_no << 8) ^ tid)
        port = fleet.direct_port(live_slots[tid % len(live_slots)])
        try:
            c = _conn(port)
        except WireError:
            st.bump("wire_drops")
            if not chaos:
                st.violate(f"round {round_no}: wire failure on connect "
                           "without chaos")
            return
        try:
            for _ in range(n_ops):
                r = rng.random()
                try:
                    if r < 0.40:
                        _new_order(c, st, rng)
                    elif r < 0.75:
                        _payment(c, st, rng)
                    else:
                        _point_read(c, st, rng)
                except WireError:
                    st.bump("wire_drops")
                    if not chaos:
                        st.violate(f"round {round_no}: wire drop "
                                   "without chaos")
                    return
        finally:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300.0)
    assert not any(t.is_alive() for t in threads), "STUCK oltp clients"


def _check_invariants(fleet, st: _Stats, slot: int, label: str):
    """The round-end consistency audit from ONE worker, all sums read in
    a single snapshot txn."""
    c = _conn(fleet.direct_port(slot))
    try:
        c.must_exec("begin")
        sw = int(c.must_query("select sum(w_ytd) from warehouse")[1][0][0])
        sd = int(c.must_query("select sum(d_ytd) from district")[1][0][0])
        sc = int(c.must_query(
            "select sum(c_balance) from customer")[1][0][0])
        n_orders = int(c.must_query(
            "select count(*) from orders")[1][0][0])
        sum_next = int(c.must_query(
            "select sum(d_next_o_id) from district")[1][0][0])
        c.must_exec("commit")
    finally:
        c.close()
    if not (sw == sd == -sc):
        st.violate(f"{label}: MONEY LEAK on slot {slot}: sum(w_ytd)={sw} "
                   f"sum(d_ytd)={sd} -sum(c_balance)={-sc}")
    if sum_next - N_DISTRICTS != n_orders:
        st.violate(f"{label}: ORDER/SEQUENCE SPLIT on slot {slot}: "
                   f"sum(d_next_o_id)-{N_DISTRICTS}={sum_next - N_DISTRICTS}"
                   f" but count(orders)={n_orders}")
    return {"orders": n_orders, "ytd": sw}


def _check_acked_survive(fleet, st: _Stats, slot: int, label: str,
                         rng, sample_n: int = 20):
    """Spot-check that client-acked NewOrder keys exist on `slot` (the
    full count is covered by the sequence invariant; the sample pins
    concrete acked keys, including after a kill/recover)."""
    with st.mu:
        acked = list(st.acked_orders)
    if not acked:
        return
    sample = rng.sample(acked, min(sample_n, len(acked)))
    c = _conn(fleet.direct_port(slot))
    try:
        for key in sample:
            rows = c.must_query(
                f"select o_key from orders where o_key = {key}")[1]
            if not rows:
                st.violate(f"{label}: ACKED ROW LOST on slot {slot}: "
                           f"committed order {key} missing")
    finally:
        c.close()


def _stale_counters(fleet, slots) -> dict:
    """slot -> freshness_stale_ok (loud-downgrade counter) via DIAG."""
    out = {}
    for s in slots:
        fab = _diag(fleet.direct_port(s), "status").get("fabric", {})
        out[s] = int(fab.get("freshness_stale_ok", 0) or 0)
    return out


def _peer_read_probe(fleet, st: _Stats, writer: int, readers, label: str,
                     marker_seq: list, *, strict: bool):
    """Commit a marker bump on `writer`, read it back from every slot in
    `readers`.  strict=True flips the GLOBAL fsync policy to 'commit' on
    the writer for the bump, so the frontier publish PRECEDES the ack
    and a single immediate read per peer must see it.  strict=False (the
    'interval' mix policy) allows the frontier to trail one flush
    period, so the probe retries within CONVERGE_S.  Either way a read
    that returns an older value without a loud stale_ok downgrade (or a
    classified 9011 refusal) is a silent-stale violation."""
    from tidb_tpu.fabric.client import WireError

    pre_stale = _stale_counters(fleet, readers)
    marker_seq[0] += 1
    n = marker_seq[0]
    w = _conn(fleet.direct_port(writer))
    try:
        if strict:
            w.must_exec("set global tidb_wal_fsync = 'commit'")
        w.must_exec("begin")
        w.must_exec(f"update marker set v = {n} where id = 1")
        w.must_exec("commit")
    finally:
        if strict:
            try:
                w.must_exec("set global tidb_wal_fsync = 'interval'")
            except WireError:
                pass
        w.close()

    for s in readers:
        deadline = time.monotonic() + (0 if strict else CONVERGE_S)
        while True:
            c = _conn(fleet.direct_port(s))
            try:
                kind, payload = c.query(
                    "select v from marker where id = 1")
            finally:
                c.close()
            if kind == "err":
                if payload[0] == FRESHNESS_CODE:
                    # the LOUD classified refusal: clean by contract
                    st.bump("freshness_refusals")
                    break
                st.violate(f"{label}: peer-read probe error on slot {s}:"
                           f" {payload}")
                break
            v = int(payload[1][0][0])
            if v >= n:
                break
            if time.monotonic() < deadline:
                time.sleep(0.02)
                continue
            post = _stale_counters(fleet, [s])
            if post.get(s, 0) > pre_stale.get(s, 0):
                # the worker ANNOUNCED the downgrade — loud, clean
                st.bump("freshness_refusals")
                break
            st.violate(
                f"{label}: SILENT STALE READ on slot {s}: marker v={v} "
                f"< committed {n} with no stale_ok downgrade and no "
                "9011 refusal")
            break


def run_oltp(procs: int = 3, n_threads: int = 6, n_ops: int = 8,
             seed: int = 0, chaos: bool = True, emit=_emit) -> dict:
    """Drive the OLTP chaos bench; returns the ``serve_oltp`` summary
    dict (also emitted).  Raises AssertionError on any consistency
    violation — tests call this in-process, the CLI exits 1."""
    from tidb_tpu.fabric.fleet import Fleet

    assert procs >= 2, "the cross-worker contract needs >= 2 workers"
    assert not chaos or procs >= 3, (
        "chaos rounds need >= 3 workers: two DISTINCT survivors must "
        "keep serving while one is down")
    rng = random.Random(seed)
    st = _Stats()
    marker_seq = [0]
    fleet = Fleet(procs, init="bench_oltp:_oltp_seed",
                  # the throughput mix runs under GROUP COMMIT: acks
                  # ride the interval flusher, frontier publish trails
                  # by <= one flush period (the strict peer-read probe
                  # flips to 'commit' per round to pin immediacy)
                  sysvars={"tidb_wal_fsync": "interval"})
    t_boot = time.monotonic()
    fleet.start(timeout_s=300.0)
    emit({"metric": "oltp_fleet_up", "procs": procs, "port": fleet.port,
          "boot_s": round(time.monotonic() - t_boot, 2), "seed": seed,
          "chaos": chaos})
    kill_recover_s = None
    stall_round = False
    t_run = time.monotonic()
    try:
        all_slots = list(range(procs))
        round_no = 0

        # -- round 0: fault-free baseline --------------------------------
        t0 = time.monotonic()
        _mix_round(fleet, st, n_threads=n_threads, n_ops=n_ops,
                   seed=seed, round_no=round_no, live_slots=all_slots,
                   chaos=False)
        _check_invariants(fleet, st, all_slots[0], "round0")
        _peer_read_probe(fleet, st, writer=all_slots[0],
                         readers=all_slots[1:], label="round0",
                         marker_seq=marker_seq, strict=True)
        _peer_read_probe(fleet, st, writer=all_slots[-1],
                         readers=all_slots[:-1], label="round0-rev",
                         marker_seq=marker_seq, strict=False)
        emit({"metric": "oltp_round", "round": 0, "kind": "baseline",
              "wall_s": round(time.monotonic() - t0, 2),
              **dict(st.counts)})

        if chaos:
            # -- round 1: SIGKILL one worker mid-mix ---------------------
            round_no += 1
            victim = rng.choice(all_slots[1:])  # keep slot0 as auditor
            survivors = [s for s in all_slots if s != victim]
            old_pid = fleet.worker_pid(victim)
            t0 = time.monotonic()
            killer = threading.Timer(
                0.3, lambda: fleet.kill_worker(victim, signal.SIGKILL))
            killer.start()
            _mix_round(fleet, st, n_threads=n_threads, n_ops=n_ops,
                       seed=seed, round_no=round_no,
                       live_slots=all_slots, chaos=True)
            killer.join()
            assert fleet.wait_respawn(victim, old_pid,
                                      RESPAWN_BUDGET_S), (
                f"worker {victim} not respawned within "
                f"{RESPAWN_BUDGET_S}s")
            kill_recover_s = round(time.monotonic() - t0, 2)
            _check_invariants(fleet, st, survivors[0], "round1-survivor")
            # the RESPAWNED worker recovered the shared log from zero:
            # acked rows and all sums must be intact THERE too
            _check_invariants(fleet, st, victim, "round1-respawned")
            _check_acked_survive(fleet, st, victim, "round1-respawned",
                                 rng)
            _peer_read_probe(fleet, st, writer=survivors[0],
                             readers=[victim] + survivors[1:],
                             label="round1", marker_seq=marker_seq,
                             strict=True)
            emit({"metric": "oltp_round", "round": 1, "kind": "kill",
                  "victim": victim, "recover_s": kill_recover_s,
                  "wall_s": round(time.monotonic() - t0, 2),
                  **dict(st.counts)})

            # -- round 2: SIGSTOP-stall one worker under load ------------
            round_no += 1
            stall_round = True
            victim = rng.choice(all_slots[1:])
            survivors = [s for s in all_slots if s != victim]
            pid = fleet.worker_pid(victim)
            t0 = time.monotonic()
            os.kill(pid, signal.SIGSTOP)
            try:
                _mix_round(fleet, st, n_threads=n_threads,
                           n_ops=max(2, n_ops // 2), seed=seed,
                           round_no=round_no, live_slots=survivors,
                           chaos=True)
                # survivors serve each other's writes while a member
                # is frozen mid-whatever
                _peer_read_probe(fleet, st, writer=survivors[0],
                                 readers=survivors[1:],
                                 label="round2-stalled",
                                 marker_seq=marker_seq, strict=True)
            finally:
                if time.monotonic() - t0 < STALL_S:
                    time.sleep(STALL_S - (time.monotonic() - t0))
                os.kill(pid, signal.SIGCONT)
            # the resumed worker must catch its tail up and pass the
            # SAME immediate-visibility bar as everyone else
            _peer_read_probe(fleet, st, writer=survivors[0],
                             readers=[victim], label="round2-resumed",
                             marker_seq=marker_seq, strict=True)
            _check_invariants(fleet, st, victim, "round2-resumed")
            emit({"metric": "oltp_round", "round": 2, "kind": "stall",
                  "victim": victim, "stall_s": STALL_S,
                  "wall_s": round(time.monotonic() - t0, 2),
                  **dict(st.counts)})

        # -- final audit from EVERY worker (identical answers) -----------
        finals = {s: _check_invariants(fleet, st, s, "final")
                  for s in all_slots}
        if len({(v["orders"], v["ytd"]) for v in finals.values()}) > 1:
            st.violate(f"final: workers disagree on committed state: "
                       f"{finals}")
        _check_acked_survive(fleet, st, all_slots[0], "final", rng)
        wall_s = time.monotonic() - t_run

        # -- freshness histogram, fleet-merged over DIAG -----------------
        bounds, counts = None, None
        waits = timeouts = stale_ok = 0
        for s in all_slots:
            h = (_diag(fleet.direct_port(s), "metrics")
                 .get("hists", {}).get("freshness_wait_seconds"))
            if h:
                if bounds is None:
                    bounds = h["bounds"]
                    counts = [0] * len(h["counts"])
                counts = [a + b for a, b in zip(counts, h["counts"])]
            fab = _diag(fleet.direct_port(s), "status").get("fabric", {})
            waits += int(fab.get("freshness_waits", 0) or 0)
            timeouts += int(fab.get("freshness_timeouts", 0) or 0)
            stale_ok += int(fab.get("freshness_stale_ok", 0) or 0)
        if bounds:
            p50, p99 = _hist_pctls(bounds, counts, (0.50, 0.99))
        else:
            p50 = p99 = 0.0

        with st.mu:
            c = dict(st.counts)
            read_ms = sorted(st.read_ms)
            txn_ms = sorted(st.txn_ms)
            n_acked = len(st.acked_orders)
            violations = list(st.violations)
        txns_ok = c["new_order_ok"] + c["payment_ok"]
        summary = {
            "metric": "serve_oltp", "procs": procs,
            "threads": n_threads, "ops": n_ops, "seed": seed,
            "chaos": chaos, "wall_s": round(wall_s, 2),
            # tpmC-shaped: committed business txns per minute
            "tpmC": round(txns_ok / wall_s * 60.0, 1),
            "txns_ok": txns_ok, "new_orders": c["new_order_ok"],
            "payments": c["payment_ok"], "acked_orders": n_acked,
            "conflict_rate": round(
                c["conflicts"] / max(c["write_attempts"], 1), 4),
            "conflicts": c["conflicts"],
            "clean_errors": c["clean_errors"],
            "wire_drops": c["wire_drops"],
            "freshness_wait_p50_ms": round(p50 * 1000.0, 3),
            "freshness_wait_p99_ms": round(p99 * 1000.0, 3),
            "freshness_waits": waits,
            "freshness_timeouts": timeouts,
            "freshness_stale_ok": stale_ok,
            "freshness_refusals": c["freshness_refusals"],
            "txn_p50_ms": _pctl(txn_ms, 0.50),
            "txn_p99_ms": _pctl(txn_ms, 0.99),
            "read_p50_ms": _pctl(read_ms, 0.50),
            "read_p99_ms": _pctl(read_ms, 0.99),
            "kill_recover_s": kill_recover_s,
            "stall_round": stall_round,
            "violations": len(violations),
        }
        emit(summary)
        assert not violations, (
            "OLTP CONSISTENCY VIOLATIONS:\n" + "\n".join(violations))
        assert txns_ok > 0, "no transaction ever committed"
        return summary
    finally:
        drained = fleet.shutdown()
        emit({"metric": "oltp_fleet_drained",
              **(drained or {"ok": False})})
        assert drained and drained["ok"], (
            f"FLEET DRAIN LEAK (leases/running/dedup): {drained}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--procs", type=int, default=3)
    ap.add_argument("--threads", type=int, default=6)
    ap.add_argument("--ops", type=int, default=8,
                    help="operations per client thread per round")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-chaos", action="store_true",
                    help="baseline round only (no kill/stall rounds)")
    ap.add_argument("--smoke", action="store_true",
                    help="fixed-seed CI preset (3 workers, chaos on); "
                         "appends the serve_oltp line to "
                         "bench_history.jsonl")
    args = ap.parse_args(argv)
    if args.smoke:
        args.procs, args.threads, args.ops, args.seed = 3, 6, 6, 0
    try:
        summary = run_oltp(procs=args.procs, n_threads=args.threads,
                           n_ops=args.ops, seed=args.seed,
                           chaos=not args.no_chaos)
    except AssertionError as e:
        _emit({"metric": "oltp_violation", "error": str(e)[:2000]})
        return 1
    if args.smoke:
        import subprocess
        rev = ""
        try:
            rev = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
        except Exception:  # noqa: BLE001
            pass
        hist = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_history.jsonl")
        line = {**summary, "rev": rev,
                "at": time.strftime("%Y-%m-%d %H:%M:%S")}
        with open(hist, "a") as f:
            f.write(json.dumps(line) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
