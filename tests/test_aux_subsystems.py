"""Auxiliary subsystems: auto-analyze stats worker, CMSketch estimates,
TRACE statement, failpoint fault injection, sysvar breadth (reference:
domain/domain.go:1270, statistics/cmsketch.go, executor/trace.go,
pingcap/failpoint, sessionctx/variable/sysvar.go)."""

import pytest

from tidb_tpu.errors import TiDBError
from tidb_tpu.session.sysvars import get_registry
from tidb_tpu.utils import failpoint
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("create table t (a int primary key, b int)")
    return tk


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


# -- stats worker -------------------------------------------------------------

def test_modify_counts_recorded(tk):
    info = tk.session.infoschema().table_by_name("test", "t")
    tk.must_exec("insert into t values (1, 1), (2, 2)")
    tk.must_exec("update t set b = 5 where a = 1")
    tk.must_exec("delete from t where a = 2")
    w = tk.session.domain.stats_worker
    assert w.modify_counts.get(info.id, 0) >= 4


def test_auto_analyze_triggers(tk):
    info = tk.session.infoschema().table_by_name("test", "t")
    vals = ",".join(f"({i}, {i % 7})" for i in range(1500))
    tk.must_exec(f"insert into t values {vals}")
    w = tk.session.domain.stats_worker
    done = w.run_once()
    assert info.id in done
    stats = tk.session.domain.stats[info.id]
    assert stats["row_count"] == 1500
    # churn below the ratio: no re-analyze
    tk.must_exec("update t set b = 99 where a < 10")
    assert info.id not in w.run_once()
    # churn above the ratio (>50%): re-analyze
    tk.must_exec("update t set b = b + 1 where a < 1000")
    assert info.id in w.run_once()


def test_auto_analyze_respects_toggle(tk):
    tk.must_exec("set global tidb_enable_auto_analyze = OFF")
    vals = ",".join(f"({i}, 1)" for i in range(1200))
    tk.must_exec(f"insert into t values {vals}")
    assert tk.session.domain.stats_worker.run_once() == []
    tk.must_exec("set global tidb_enable_auto_analyze = ON")


# -- CMSketch -----------------------------------------------------------------

def test_cmsketch_point_estimate(tk):
    # 20 heavy values (TopN captures 8) + long tail → sketch answers the
    # tail with bounded overestimates
    rows = []
    rid = 0
    for v in range(20):
        for _ in range(50 - v):
            rows.append((rid, v))
            rid += 1
    for v in range(100, 400):
        rows.append((rid, v))
        rid += 1
    tk.must_exec("insert into t values " +
                 ",".join(f"({a},{b})" for a, b in rows))
    tk.must_exec("analyze table t")
    info = tk.session.infoschema().table_by_name("test", "t")
    cs = tk.session.domain.stats[info.id]["columns"][str(
        next(c.id for c in info.columns if c.name == "b"))]
    assert "cmsketch" in cs
    from tidb_tpu.statistics.analyze import cm_query
    est = cm_query(cs["cmsketch"], 150)  # tail value: true count 1
    assert 1 <= est <= 10  # CM overestimates but stays near


# -- TRACE --------------------------------------------------------------------

def test_trace_select(tk):
    tk.must_exec("insert into t values (1, 2), (3, 4)")
    r = tk.must_query("trace select sum(b) from t")
    ops = [row[0] for row in r.rows]
    assert "statement" in ops  # the lifecycle trace's root span
    assert any("plan_query" in o for o in ops)
    assert any("executor.run" in o for o in ops)
    assert any("operator." in o for o in ops)


# -- failpoints ---------------------------------------------------------------

def test_failpoint_panic_between_prewrite_and_commit(tk):
    """In-process failure after prewrite: locks are released, nothing is
    committed, and the next writer proceeds cleanly."""
    tk.must_exec("insert into t values (1, 1)")
    failpoint.enable("txn-after-prewrite", "panic")
    with pytest.raises(failpoint.FailpointError):
        tk.must_exec("insert into t values (2, 2)")
    failpoint.disable("txn-after-prewrite")
    assert failpoint.hits("txn-after-prewrite") >= 1
    tk.must_query("select count(*) from t").check([("1",)])
    tk.must_exec("insert into t values (2, 22)")
    tk.must_query("select b from t where a = 2").check([("22",)])
    tk.must_query("select count(*) from t").check([("2",)])


def test_failpoint_sleep_and_return(tk):
    failpoint.enable("txn-before-prewrite", "sleep(0.01)")
    tk.must_exec("insert into t values (5, 5)")  # just slower, still works
    assert failpoint.hits("txn-before-prewrite") >= 1
    failpoint.disable_all()
    assert failpoint.inject("txn-before-prewrite") is None


def test_failpoint_ddl_backfill(tk):
    vals = ",".join(f"({i}, {i})" for i in range(50))
    tk.must_exec(f"insert into t values {vals}")
    failpoint.enable("ddl-backfill-batch", "sleep(0.001)")
    tk.must_exec("create index i_b on t (b)")
    assert failpoint.hits("ddl-backfill-batch") >= 1
    tk.must_exec("admin check index t i_b")


# -- sysvars ------------------------------------------------------------------

def test_sysvar_registry_breadth(tk):
    assert len(get_registry()) >= 140
    # common client handshake reads work
    r = tk.must_query(
        "select @@version_comment, @@auto_increment_increment, "
        "@@character_set_server, @@tidb_row_format_version")
    assert r.rows[0][1] == "1"


def test_show_variables_count(tk):
    rows = tk.must_query("show variables").rows
    assert len(rows) >= 140


def test_trace_checked_for_privileges(tk):
    from tidb_tpu.session import Session
    tk.must_exec("create user 'tr'@'%'")
    s = Session(tk.session.domain)
    s.user = "tr@%"
    with pytest.raises(TiDBError):
        s.execute("trace select * from t")


def test_cmsketch_int_float_keys_collide(tk):
    from tidb_tpu.statistics.analyze import build_cmsketch, cm_query
    import numpy as np
    cm = build_cmsketch(np.array([2.0, 3.5]), np.array([20, 7]))
    assert cm_query(cm, 2) == 20       # int query, float build
    assert cm_query(cm, 2.0) == 20
    assert cm_query(cm, 3.5) == 7


def test_admin_checksum_table(tk):
    """reference: executor/checksum.go + distsql.Checksum — stable,
    order-independent, change-sensitive."""
    tk.must_exec("create table ck (id int primary key, v varchar(8))")
    tk.must_exec("insert into ck values (1,'a'),(2,'b')")
    r1 = tk.must_query("admin checksum table ck").rows
    assert int(r1[0][3]) == 2  # total_kvs
    tk.must_exec("insert into ck values (3,'c')")
    r2 = tk.must_query("admin checksum table ck").rows
    assert r1[0][2] != r2[0][2]
    tk.must_exec("delete from ck where id = 3")
    r3 = tk.must_query("admin checksum table ck").rows
    assert r1[0][2] == r3[0][2]
