"""Resilience layer: unified backoff budgets (utils/backoff.py), the
device→host circuit breaker (executor/circuit.py), failpoint hygiene, and
the new sysvar knobs (reference: store/tikv/backoff.go Backoffer +
pingcap/failpoint)."""

import time

import pytest

from tidb_tpu.errors import (BackoffExhaustedError, ErrCode, LockedError,
                             TiDBError, WriteConflictError)
from tidb_tpu.executor.circuit import CircuitBreaker, get_breaker
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.backoff import (Backoffer, ExchangeError, classify,
                                    CLASS_DEVICE, CLASS_EXCHANGE,
                                    CLASS_FAULT, CLASS_REGION,
                                    CLASS_TRANSPORT)
from tidb_tpu.utils.failpoint import FailpointError


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


# -- error taxonomy -----------------------------------------------------------

class TestClassify:
    def test_region_class(self):
        assert classify(WriteConflictError("w")) == CLASS_REGION
        assert classify(LockedError("l")) == CLASS_REGION

    def test_exchange_and_fault(self):
        assert classify(ExchangeError("x")) == CLASS_EXCHANGE
        assert classify(FailpointError("f")) == CLASS_FAULT

    def test_transport_class(self):
        assert classify(ConnectionRefusedError("refused")) == CLASS_TRANSPORT
        assert classify(RuntimeError("Connection refused")) == CLASS_TRANSPORT

    def test_filesystem_oserrors_are_not_transport(self):
        # FileNotFoundError is a bug to surface, not tunnel weather to
        # retry/degrade on
        from tidb_tpu.utils.backoff import CLASS_OTHER
        assert classify(FileNotFoundError("page.bin")) == CLASS_OTHER
        assert classify(PermissionError("denied")) == CLASS_OTHER

    def test_device_class(self):
        class XlaRuntimeError(Exception):
            pass
        assert classify(XlaRuntimeError("boom")) == CLASS_DEVICE
        assert classify(RuntimeError("RESOURCE_EXHAUSTED: hbm")) \
            == CLASS_DEVICE


# -- Backoffer ---------------------------------------------------------------

class TestBackoffer:
    def test_attempt_cap_raises_classified(self):
        bo = Backoffer(budget_ms=10_000, seed=7, sleep=False)
        err = ExchangeError("send failed")
        with pytest.raises(BackoffExhaustedError) as ei:
            for _ in range(100):
                bo.backoff("exchangeRetry", err)
        e = ei.value
        assert e.code == ErrCode.BackoffExhausted
        assert e.retry_kind == "exchangeRetry"
        assert e.error_class == CLASS_EXCHANGE
        assert "send failed" in str(e)

    def test_sleep_budget_exhausts(self):
        bo = Backoffer(budget_ms=5, seed=1, sleep=False)
        with pytest.raises(BackoffExhaustedError):
            for _ in range(1000):
                bo.backoff("txnLock", LockedError("l"))
        assert bo.slept_ms <= 5

    def test_weight_scales_budget(self):
        assert Backoffer(budget_ms=100, weight=3).budget_ms == 300

    def test_deterministic_with_seed(self):
        def curve(seed):
            bo = Backoffer(budget_ms=10_000, seed=seed, sleep=False)
            out = []
            for _ in range(8):
                bo.backoff("txnRetry")
                out.append(bo.slept_ms)
            return out
        assert curve(42) == curve(42)
        assert curve(42) != curve(43)

    def test_check_killed_interrupts(self):
        def boom():
            raise TiDBError("Query execution was interrupted",
                            code=ErrCode.QueryInterrupted)
        bo = Backoffer(budget_ms=10_000, check_killed=boom)
        with pytest.raises(TiDBError) as ei:
            bo.backoff("txnLock")
        assert ei.value.code == ErrCode.QueryInterrupted

    def test_for_session_clamps_to_max_execution_time(self, tk):
        tk.must_exec("set max_execution_time = 7")
        bo = Backoffer.for_session(tk.session)
        # the cap clamps the WEIGHTED budget: tidb_backoff_weight (2)
        # must not stretch retries past the execution window
        assert bo.budget_ms == pytest.approx(7.0)

    def test_for_session_weight_scales_unclamped(self, tk):
        tk.must_exec("set tidb_backoff_weight = 3")
        bo = Backoffer.for_session(tk.session, budget_ms=100)
        assert bo.budget_ms == pytest.approx(300.0)

    def test_wall_clock_deadline_counts_work_time(self):
        """A wall-clock Backoffer charges slow re-executions against the
        deadline, not only its own sleeps (innodb_lock_wait_timeout is a
        hard elapsed-time bound)."""
        bo = Backoffer(budget_ms=30, wall_clock=True, sleep=False)
        time.sleep(0.05)  # the "statement re-execution" burning the clock
        with pytest.raises(BackoffExhaustedError) as ei:
            bo.backoff("txnLock", LockedError("l"))
        assert "deadline" in str(ei.value)
        assert bo.remaining_ms() == 0.0

    def test_growth_kind_never_sleeps(self):
        bo = Backoffer(budget_ms=1)  # any sleep would blow this budget
        for _ in range(11):
            bo.backoff("exchangeGrow")
        with pytest.raises(BackoffExhaustedError):
            bo.backoff("exchangeGrow")


# -- failpoint hygiene (satellite) -------------------------------------------

class TestFailpointHygiene:
    def test_enabled_context_manager_never_leaks(self):
        with pytest.raises(RuntimeError):
            with failpoint.enabled("some-point", "panic"):
                assert failpoint.list_active() == {"some-point": "panic"}
                raise RuntimeError("body blew up")
        assert failpoint.list_active() == {}

    def test_list_active_snapshot(self):
        failpoint.enable("a", "panic")
        failpoint.enable("b", "return(3)")
        try:
            active = failpoint.list_active()
            assert active == {"a": "panic", "b": "return(3)"}
            active["c"] = "x"  # mutating the snapshot must not leak back
            assert "c" not in failpoint.list_active()
        finally:
            failpoint.disable_all()

    def test_concurrent_disable_race(self):
        """inject() vs disable(): the hit-count/active read is atomic under
        one lock — hammering both never counts a hit for a disabled point
        into a freshly re-enabled one (the torn-read satellite fix)."""
        import threading
        stop = threading.Event()

        def flipper():
            while not stop.is_set():
                failpoint.enable("race-point", "return(1)")
                failpoint.disable("race-point")

        t = threading.Thread(target=flipper)
        t.start()
        try:
            for _ in range(2000):
                failpoint.inject("race-point")  # must never raise
        finally:
            stop.set()
            t.join()
            failpoint.disable_all()

    def test_n_return_action(self):
        with failpoint.enabled("np", "2*return(9)"):
            assert failpoint.inject("np") == 9
            assert failpoint.inject("np") == 9
            assert failpoint.inject("np") is None


# -- circuit breaker ----------------------------------------------------------

class TestCircuitBreakerUnit:
    def test_open_after_threshold_and_recover(self):
        now = [0.0]
        br = CircuitBreaker(threshold=3, cooldown_s=10.0,
                            clock=lambda: now[0])
        for _ in range(2):
            br.record_failure(RuntimeError("x"))
        assert br.state == "closed" and br.allow()
        br.record_failure(RuntimeError("x"))
        assert br.state == "open" and not br.allow()
        now[0] += 10.0
        assert br.state == "half-open"
        assert br.allow()        # the single probe slot
        assert not br.allow()    # everyone else stays host-side
        br.record_success()
        assert br.state == "closed" and br.allow()

    def test_failed_probe_reopens(self):
        now = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=5.0,
                            clock=lambda: now[0])
        br.record_failure(RuntimeError("x"))
        now[0] += 5.0
        assert br.allow()
        br.record_failure(RuntimeError("still dead"))
        assert br.state == "open" and not br.allow()

    def test_threshold_zero_disables(self):
        br = CircuitBreaker(threshold=0)
        for _ in range(100):
            br.record_failure(RuntimeError("x"))
        assert br.allow()

    def test_success_resets_failure_count(self):
        br = CircuitBreaker(threshold=3)
        br.record_failure(RuntimeError("x"))
        br.record_failure(RuntimeError("x"))
        br.record_success()
        br.record_failure(RuntimeError("x"))
        assert br.state == "closed"

    def test_stale_verdicts_leave_live_probe_alone(self):
        """A fragment admitted while CLOSED that reports its verdict after
        the breaker opened and ANOTHER thread won the probe must neither
        close the breaker nor free/kill the live probe slot (the
        half-open race the threaded chaos mode exercises)."""
        import threading as _t
        now = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=5.0,
                            clock=lambda: now[0])
        br.record_failure(RuntimeError("x"))
        now[0] += 5.0
        probed = _t.Event()
        release = _t.Event()

        def prober():
            assert br.allow()  # wins the single probe slot
            probed.set()
            release.wait(5.0)
            br.record_success()

        t = _t.Thread(target=prober)
        t.start()
        assert probed.wait(5.0)
        # stale verdicts from THIS thread while the probe is in flight:
        br.record_success()
        assert br.state == "half-open" and not br.allow(), (
            "stale success must not close the breaker mid-probe")
        br.record_failure(RuntimeError("late straggler"))
        assert br.state == "half-open" and not br.allow(), (
            "stale failure must not reopen/steal the live probe's slot")
        release.set()
        t.join(5.0)
        assert br.state == "closed" and br.allow()

    def test_stale_success_does_not_close_open_breaker(self):
        """A fragment admitted before the breaker tripped that succeeds
        mid-cooldown (no probe in flight) must not close the breaker —
        waiting fragments would re-dispatch to the hung backend and each
        pay a full deadline; recovery goes through the probe."""
        now = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=10.0,
                            clock=lambda: now[0])
        br.record_failure(RuntimeError("hang"))
        assert br.state == "open"
        br.record_success()  # the stale straggler
        assert br.state == "open" and not br.allow()
        now[0] += 10.0       # cooldown elapses → probe recovers normally
        assert br.allow()
        br.record_success()
        assert br.state == "closed"

    def test_stale_success_after_released_probe_keeps_half_open(self):
        """A prober that exits via release_probe (no verdict) leaves the
        slot free in HALF_OPEN; a straggler's stale success must not
        close the breaker — the next PROBE's verdict decides."""
        now = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=5.0,
                            clock=lambda: now[0])
        br.record_failure(RuntimeError("hang"))
        now[0] += 5.0
        assert br.allow()          # probe admitted...
        br.release_probe()         # ...exits with no verdict
        br.record_success()        # straggler from before the open
        assert br.state == "half-open", (
            "stale success must not close a probe-less half-open breaker")
        assert br.allow()          # a real probe still recovers it
        br.record_success()
        assert br.state == "closed"

    def test_vanished_probe_slot_is_reclaimed(self):
        """A probe owner that died without any verdict (no success, no
        failure, no release) must not wedge the breaker host-side
        forever: allow() reclaims the slot after the grace window."""
        now = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=1.0,
                            clock=lambda: now[0])
        br.record_failure(RuntimeError("x"))
        now[0] += 1.0
        assert br.allow()          # probe taken ... and its owner vanishes
        assert not br.allow()      # slot held
        now[0] += 600.0            # past cooldown but INSIDE the reclaim
        assert not br.allow(), (   # floor: a slow live probe keeps its slot
            "a probe within the reclaim floor must not be robbed")
        now[0] += 600.0            # way past max(cooldown, reclaim floor)
        assert br.allow(), "stale probe slot must be reclaimable"
        assert br.snapshot()["probe_reclaims"] == 1
        br.record_success()
        assert br.state == "closed"

    def test_concurrent_allow_single_probe_slot(self):
        """N threads hammering allow()/record_* concurrently: at most ONE
        probe admission per half-open window, every exit path releases,
        and the breaker is never wedged at the end."""
        import threading as _t
        br = CircuitBreaker(threshold=1, cooldown_s=0.01)
        br.record_failure(RuntimeError("x"))
        time.sleep(0.02)  # → half-open
        admitted = []
        mu = _t.Lock()
        start = _t.Barrier(8)

        def hammer(tid):
            start.wait(5.0)
            for i in range(200):
                if br.allow():
                    with mu:
                        admitted.append(tid)
                    # alternate every exit path run_device uses
                    if i % 3 == 0:
                        br.record_failure(RuntimeError("probe failed"))
                        time.sleep(0.011)  # let the cooldown elapse
                    elif i % 3 == 1:
                        br.release_probe()  # no-verdict exit
                    else:
                        br.record_success()

        threads = [_t.Thread(target=hammer, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not any(t.is_alive() for t in threads)
        snap = br.snapshot()
        assert snap["state"] in ("closed", "open", "half-open")
        # not wedged: after the cooldown the breaker must admit a probe
        # and a success must close it
        time.sleep(0.02)
        deadline = time.monotonic() + 2.0
        while not br.allow() and time.monotonic() < deadline:
            time.sleep(0.005)
        br.record_success()
        assert br.state == "closed" and br.allow()


class TestCircuitBreakerEndToEnd:
    def test_device_faults_flip_to_host_and_recover(self, tk):
        """Acceptance: failpoint-forced device failures flip queries to the
        host engine mid-corpus with CORRECT results; the breaker closes
        again after cooldown."""
        tk.must_exec("create table t (a int, b int)")
        tk.must_exec("insert into t values " + ",".join(
            f"({i % 7},{i})" for i in range(128)))
        q = "select a, sum(b) from t group by a order by a"
        golden = tk.must_query(q).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec("set global tidb_device_circuit_threshold = 2")
        tk.must_exec("set global tidb_device_circuit_cooldown = 0.1")
        br = get_breaker(tk.session)
        with failpoint.enabled("device-agg-exec", "panic"):
            for _ in range(4):  # mid-corpus: every query still correct
                assert tk.must_query(q).rows == golden
        assert br.state == "open"
        assert br.snapshot()["degraded"] >= 1
        time.sleep(0.12)
        assert br.state == "half-open"
        assert tk.must_query(q).rows == golden  # successful probe
        assert br.state == "closed"

    def test_breaker_isolated_per_domain(self, tk):
        other = TestKit()  # a second embedded cluster
        get_breaker(tk.session).record_failure(RuntimeError("x"))
        assert get_breaker(other.session).snapshot()["failures"] == 0

    def test_user_errors_are_not_health_signals(self, tk):
        """A TiDBError from the device path (a genuine user error) must
        pass through run_device without tripping the breaker."""
        from tidb_tpu.executor.device_exec import run_device
        br = get_breaker(tk.session)
        before = br.snapshot()["failures"]
        def user_error():
            raise TiDBError("Division by zero", code=ErrCode.DivisionByZero)
        with pytest.raises(TiDBError):
            run_device(tk.session, user_error)
        assert br.snapshot()["failures"] == before

    def test_unclassified_bugs_propagate(self, tk):
        """A programming bug (KeyError) is not a device-health signal:
        it must surface, not silently degrade to host."""
        from tidb_tpu.executor.device_exec import run_device
        br = get_breaker(tk.session)
        before = br.snapshot()["failures"]
        def bug():
            raise KeyError("missing column slot")
        with pytest.raises(KeyError):
            run_device(tk.session, bug)
        assert br.snapshot()["failures"] == before

    def test_probe_slot_released_on_unsupported_fragment(self, tk):
        """A HALF_OPEN probe fragment that raises DeviceUnsupported gives
        no health verdict — the probe slot must free for the next
        fragment instead of wedging the breaker host-side forever."""
        from tidb_tpu.executor.device_exec import (run_device,
                                                   DeviceUnsupported)
        tk.must_exec("set global tidb_device_circuit_threshold = 1")
        tk.must_exec("set global tidb_device_circuit_cooldown = 0.01")
        br = get_breaker(tk.session)
        br.record_failure(RuntimeError("RESOURCE_EXHAUSTED"))
        time.sleep(0.02)
        assert br.state == "half-open"
        def unsupported():
            raise DeviceUnsupported("empty input")
        with pytest.raises(DeviceUnsupported):
            run_device(tk.session, unsupported)
        # slot freed: a healthy fragment can still win the probe and close
        assert run_device(tk.session, lambda: "ok") == "ok"
        assert br.state == "closed"


# -- lock-wait budgets route through the Backoffer ---------------------------

class TestLockWaitBudget:
    def test_lock_wait_timeout_is_classified(self, tk):
        tk.must_exec("create table lw (id int primary key, v int)")
        tk.must_exec("insert into lw values (1, 1)")
        tk.must_exec("set innodb_lock_wait_timeout = 1")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("set innodb_lock_wait_timeout = 1")
        tk.must_exec("begin")
        tk.must_exec("update lw set v = 2 where id = 1")
        t0 = time.monotonic()
        e = tk2.exec_error("update lw set v = 3 where id = 1")
        el = time.monotonic() - t0
        assert e.code == ErrCode.LockWaitTimeout
        assert el < 30, "budget must bound the wait, not loop forever"
        tk.must_exec("commit")
        tk2.must_exec("update lw set v = 4 where id = 1")  # recovers


# -- sysvar knobs (satellite) -------------------------------------------------

class TestResilienceSysvars:
    @pytest.mark.parametrize("name,default", [
        ("tidb_device_circuit_threshold", "5"),
        ("tidb_device_circuit_cooldown", "30"),
        ("tidb_backoff_weight", "2"),
    ])
    def test_defaults_visible(self, tk, name, default):
        tk.must_query(f"show variables like '{name}'").check(
            [(name, default)])

    def test_round_trip(self, tk):
        tk.must_exec("set tidb_device_circuit_threshold = 9")
        tk.must_exec("set tidb_device_circuit_cooldown = 1.5")
        tk.must_exec("set tidb_backoff_weight = 4")
        tk.must_query(
            "show variables like 'tidb_device_circuit%'").check_unordered(
            [("tidb_device_circuit_threshold", "9"),
             ("tidb_device_circuit_cooldown", "1.5")])
        tk.must_query("select @@tidb_backoff_weight").check([("4",)])

    def test_select_session_var(self, tk):
        tk.must_query("select @@tidb_device_circuit_threshold").check(
            [("5",)])

    def test_int_clamps_at_floor(self, tk):
        tk.must_exec("set tidb_device_circuit_threshold = -3")
        tk.must_query("select @@tidb_device_circuit_threshold").check(
            [("0",)])

    def test_float_rejects_garbage(self, tk):
        e = tk.exec_error("set tidb_device_circuit_cooldown = 'soon'")
        assert isinstance(e, TiDBError)

    def test_float_rejects_nan_and_clamps_negative(self, tk):
        # NaN sails past min/max clamps (all comparisons False) and would
        # wedge an opened breaker forever
        e = tk.exec_error("set tidb_device_circuit_cooldown = 'nan'")
        assert isinstance(e, TiDBError)
        tk.must_exec("set tidb_device_circuit_cooldown = '-5'")
        tk.must_query("select @@tidb_device_circuit_cooldown").check(
            [("0",)])


# -- coordinator failpoints (tentpole: failpoint expansion) ------------------

class TestCoordinatorFaults:
    def test_campaign_loss_skips_gc_round(self, tk):
        gw = tk.session.domain.gc_worker
        with failpoint.enabled("coordinator-campaign-loss", "return(1)"):
            out = gw.run_once()
        assert out.get("skipped") is True
        # campaigns succeed again once the fault clears
        assert tk.session.domain.coordinator.campaign("gc", "tidb-0")

    def test_tso_skew_keeps_monotonic(self, tk):
        coord = tk.session.domain.coordinator
        before = coord.tso()
        with failpoint.enabled("coordinator-tso-skew", "return(1048576)"):
            jumped = coord.tso()
        after = coord.tso()
        assert before < jumped < after
        assert jumped - before > 1048576

    def test_heartbeat_lost_then_recovers(self, tk):
        coord = tk.session.domain.coordinator
        with failpoint.enabled("coordinator-heartbeat-lost", "return(1)"):
            assert coord.heartbeat("tidb-0") is False
        assert coord.heartbeat("tidb-0") is True

    def test_lease_expire_lets_new_holder_win(self, tk):
        coord = tk.session.domain.coordinator
        assert coord.campaign("ddl-owner", "node-a", ttl_s=300)
        assert not coord.campaign("ddl-owner", "node-b")
        with failpoint.enabled("coordinator-lease-expire", "return(1)"):
            assert coord.campaign("ddl-owner", "node-b")
        assert coord.leader("ddl-owner") == "node-b"
