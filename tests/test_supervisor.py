"""Device-runtime supervision (executor/supervisor.py): hang detection
under a hard deadline, classified DeviceHangError (errno 9008 next to
BackoffExhausted 9005), breaker integration, backend fencing, the
abandoned-calls gauge across EXPLAIN ANALYZE / observe / HTTP status,
KILL responsiveness while a hang is pending, and the run_device
`shape=` call-site lint."""

import ast
import json
import os
import threading
import time
import urllib.request

import pytest

from tidb_tpu.errors import DeviceHangError, ErrCode, QueryInterruptedError
from tidb_tpu.executor import supervisor
from tidb_tpu.executor.circuit import get_breaker
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.backoff import CLASS_HANG, classify


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t1 (id int primary key, grp int, val int)")
    tk.must_exec("create table t2 (id int primary key, ref int, amt int)")
    tk.must_exec("insert into t1 values " + ",".join(
        f"({i},{i % 5},{i * 3 % 97})" for i in range(200)))
    tk.must_exec("insert into t2 values " + ",".join(
        f"({i},{i % 200},{i * 7 % 89})" for i in range(200)))
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    tk.must_exec("set tidb_device_dispatch_rows = 1")
    yield tk
    # drain any short injected hangs so later tests see a clean gauge
    deadline = time.monotonic() + 5.0
    while supervisor.abandoned_calls() and time.monotonic() < deadline:
        time.sleep(0.01)


AGG_Q = "select grp, sum(val) from t1 group by grp order by grp"
JOIN_Q = ("select t1.grp, sum(t2.amt) from t1 join t2 on t1.id = t2.ref "
          "group by t1.grp order by t1.grp")


def _drain():
    deadline = time.monotonic() + 5.0
    while supervisor.abandoned_calls() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert supervisor.abandoned_calls() == 0


# -- unit behavior -----------------------------------------------------------

class TestSupervisedCall:
    def test_inline_when_no_deadline(self):
        tid = threading.get_ident()
        out = supervisor.supervised_call(
            lambda: threading.get_ident(), deadline_s=0)
        assert out == tid  # no worker thread hop on the unsupervised path

    def test_worker_thread_and_result(self):
        tid = threading.get_ident()
        out = supervisor.supervised_call(
            lambda: threading.get_ident(), deadline_s=5.0)
        assert out != tid

    def test_exceptions_pass_through(self):
        with pytest.raises(KeyError):
            supervisor.supervised_call(
                lambda: (_ for _ in ()).throw(KeyError("x")),
                deadline_s=5.0)

    def test_deadline_raises_hang_and_reclaims(self):
        t0 = time.monotonic()
        with pytest.raises(DeviceHangError) as ei:
            supervisor.supervised_call(time.sleep, 0.5, deadline_s=0.05,
                                       label="unit-hang")
        el = time.monotonic() - t0
        assert el < 0.4, "detection must fire at the deadline, not fn end"
        assert ei.value.code == ErrCode.DeviceHang == 9008
        assert classify(ei.value) == CLASS_HANG
        assert supervisor.abandoned_calls() >= 1
        _drain()  # the sleeping worker completes and rejoins the pool

    def test_tls_stats_bridged_to_caller(self):
        """Compile stats accrued on the worker thread must show in the
        CALLER's thread-local view (EXPLAIN ANALYZE / bench attribution)."""
        from tidb_tpu.executor.device_exec import _bump, pipe_cache_stats
        st0 = pipe_cache_stats(thread_local=True)
        supervisor.supervised_call(_bump, "traces", 3, deadline_s=5.0)
        st1 = pipe_cache_stats(thread_local=True)
        assert st1["traces"] - st0["traces"] == 3

    def test_fence_roundtrip(self):
        supervisor.fence("unit test")
        assert supervisor.quarantined()
        supervisor._maybe_reinit()
        assert not supervisor.quarantined()

    def test_effective_deadline_sysvar_and_met(self, tk):
        assert supervisor.effective_deadline(tk.session) == 0.0
        tk.must_exec("set tidb_device_call_timeout = 2.5")
        assert supervisor.effective_deadline(tk.session) == 2.5
        tk.must_exec("set max_execution_time = 1000")
        d = supervisor.effective_deadline(tk.session)
        assert 0 < d <= 1.0  # the tighter (remaining-met) window wins
        tk.must_exec("set max_execution_time = 0")
        tk.must_exec("set tidb_device_call_timeout = 0")


# -- hang injection in every fragment shape (satellite) ----------------------

class TestFragmentHangs:
    @pytest.mark.parametrize("fp,query,shape", [
        ("device-agg-exec", AGG_Q, "agg"),
        ("device-join-exec", JOIN_Q, "join"),
    ])
    def test_hang_detected_classified_and_counted(self, tk, fp, query,
                                                  shape):
        tk.must_exec("set tidb_device_call_timeout = 0.05")
        br = get_breaker(tk.session, shape=shape)
        before = br.snapshot()["failures"]
        t0 = time.monotonic()
        with failpoint.enabled(fp, "sleep(0.5)"):
            e = tk.exec_error(query)
        el = time.monotonic() - t0
        assert isinstance(e, DeviceHangError), e
        assert e.code == 9008
        assert el < 0.4, f"hang detection took {el:.2f}s past the deadline"
        assert br.snapshot()["failures"] == before + 1
        # the backend is usable by the IMMEDIATELY following query in the
        # same process (fence + reinit ran before its first fragment) —
        # still supervised, but with room for the post-fence recompile
        tk.must_exec("set tidb_device_call_timeout = 30")
        rows = tk.must_query(query).rows
        tk.must_exec("set tidb_executor_engine = 'host'")
        assert rows == tk.must_query(query).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        _drain()

    def test_mpp_fragment_hang(self, tk):
        tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
        tk.must_exec("set tidb_device_call_timeout = 0.05")
        with failpoint.enabled("device-mpp-exec", "sleep(0.5)"):
            e = tk.exec_error(AGG_Q)
        assert isinstance(e, DeviceHangError), e
        # next query (fault cleared) succeeds in the same process —
        # supervised with room for the post-fence recompile
        tk.must_exec("set tidb_device_call_timeout = 30")
        assert tk.must_query(AGG_Q).rows
        _drain()

    def test_repeated_hangs_trip_breaker_to_host(self, tk):
        """Once the breaker opens on hangs, fragments degrade to the host
        engine up front — queries SUCCEED again even with the hang
        failpoint still active (the degrade half of the contract)."""
        tk.must_exec("set global tidb_device_circuit_threshold = 2")
        tk.must_exec("set global tidb_device_circuit_cooldown = 30")
        tk.must_exec("set tidb_device_call_timeout = 0.05")
        br = get_breaker(tk.session, shape="agg")
        try:
            with failpoint.enabled("device-agg-exec", "sleep(0.5)"):
                for _ in range(2):
                    e = tk.exec_error(AGG_Q)
                    assert isinstance(e, DeviceHangError)
                assert br.state == "open"
                rows = tk.must_query(AGG_Q).rows  # degraded, still right
            tk.must_exec("set tidb_executor_engine = 'host'")
            assert rows == tk.must_query(AGG_Q).rows
        finally:
            tk.must_exec("set global tidb_device_circuit_threshold = 5")
            br.record_success()  # close for later tests
        _drain()

    def test_met_expiry_is_user_limit_not_hang(self, tk):
        """When max_execution_time is the binding deadline, expiry is a
        STATEMENT limit: QueryInterrupted (1317), no breaker charge, no
        backend fence — the device earned no hang verdict."""
        tk.must_exec("set max_execution_time = 150")
        br = get_breaker(tk.session, shape="agg")
        before = br.snapshot()["failures"]
        fences0 = supervisor.snapshot()["hangs"]
        with failpoint.enabled("device-agg-exec", "sleep(1.0)"):
            e = tk.exec_error(AGG_Q)
        tk.must_exec("set max_execution_time = 0")
        assert isinstance(e, QueryInterruptedError), e
        assert br.snapshot()["failures"] == before
        assert supervisor.snapshot()["hangs"] == fences0
        _drain()

    def test_kill_interrupts_pending_hang(self, tk):
        """KILL lands while the hung device call is still pending: the
        query returns QueryInterrupted promptly — the supervisor's wait
        is the interruption point the GIL-blocked call can't offer."""
        tk.must_exec("set tidb_device_call_timeout = 10")
        out = {}

        def run():
            t0 = time.monotonic()
            try:
                tk.session.execute(AGG_Q)
                out["exc"] = None
            except Exception as e:  # noqa: BLE001
                out["exc"] = e
            out["el"] = time.monotonic() - t0

        with failpoint.enabled("device-agg-exec", "sleep(1.0)"):
            t = threading.Thread(target=run)
            t.start()
            time.sleep(0.2)
            tk.session.kill()
            t.join(5.0)
        assert not t.is_alive()
        assert isinstance(out["exc"], QueryInterruptedError), out["exc"]
        assert out["el"] < 0.9, (
            f"KILL took {out['el']:.2f}s — must interrupt the wait, not "
            "ride out the hung call")
        _drain()


# -- gauge surfacing ---------------------------------------------------------

class TestAbandonedGauge:
    def test_observe_explain_and_status_api(self, tk):
        tk.must_exec("set tidb_device_call_timeout = 0.05")
        # the sleep must outlive the EXPLAIN ANALYZE below (post-fence
        # cold recompile can take >1s) so the gauge is still live at the
        # /status fetch; _drain's window comfortably covers the rest
        with failpoint.enabled("device-agg-exec", "sleep(3.0)"):
            e = tk.exec_error(AGG_Q)
        assert isinstance(e, DeviceHangError)
        # the call is still blocked on its worker: gauge is live
        assert supervisor.abandoned_calls() >= 1
        obs = tk.domain.observe
        assert obs.gauge_snapshot().get("device_abandoned_calls", 0) >= 1

        # EXPLAIN ANALYZE of a (now unsupervised) device query annotates
        # the outstanding gauge on its fragment line
        tk.must_exec("set tidb_device_call_timeout = 0")
        rows = tk.must_query(f"explain analyze {AGG_Q}").rows
        blob = "\n".join(" ".join(str(c) for c in r) for r in rows)
        assert "device_abandoned_calls" in blob

        # HTTP status API: /status JSON field + /metrics gauge line
        from tidb_tpu.server.http_status import StatusServer
        srv = StatusServer(tk.domain, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status = json.load(urllib.request.urlopen(f"{base}/status"))
            assert status["device_abandoned_calls"] >= 1
            assert status["device_supervisor"]["hangs"] >= 1
            metrics = urllib.request.urlopen(f"{base}/metrics").read()
            assert b"device_abandoned_calls" in metrics
        finally:
            srv.shutdown()
        _drain()
        # drained: the worker completed, the gauge went back to zero
        supervisor._publish()
        assert obs.gauge_snapshot().get("device_abandoned_calls") == 0


# -- lint: every run_device call site names its breaker shape (satellite) ----

class TestRunDeviceShapeLint:
    def test_all_call_sites_pass_explicit_shape(self):
        """Registry rule (tidb_tpu/lint rules/confinement.py): a
        run_device call without shape= silently shares the 'agg' breaker
        — direct calls AND the _with_pipe_stats indirection count."""
        from tidb_tpu.lint import run_rule
        findings = run_rule("run-device-shape")
        assert not findings, [f.to_json() for f in findings]
