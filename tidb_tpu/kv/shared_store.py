"""Durable, fleet-coherent MVCC store: the paper's "one storage layer
under many SQL servers", built from the embedded python engine
(kv/mvcc.py) + a shared write-ahead log (kv/wal.py) + the fabric
coordination segment (fabric/coord.py).

The pieces and who owns what:

* **WAL on the commit path** — every logical mutation (prewrite /
  commit / rollback / raw puts / raw delete-range) appends a framed
  record stamped with the origin slot.  Commit records are the
  durability point: ``commit()`` appends (and group-fsyncs under
  ``tidb_wal_fsync = commit``) BEFORE applying locally, so an acked
  commit survives SIGKILL and an un-acked one is simply absent.
* **Recovery** (:meth:`DurableMVCCStore.recover`) — Percolator
  semantics: load the checkpoint snapshot, replay the log tail in
  order, CRC-truncate the first torn record, then resolve orphaned
  prewrites via their primary's disposition (a commit record for the
  txn's start_ts means commit the leftovers; none means roll back).
  In a live fleet, leftovers owned by a LIVE sibling slot are its
  in-flight 2PC — left alone.
* **Fleet TSO** (:class:`SegmentTSOracle`) — batched leases off the
  segment's ``_tso`` cell make every worker's timestamps
  fleet-monotonic through the same ``next_ts()`` abstraction solo mode
  uses (kv/mvcc.TSOracle), closing the per-process-oracle collision.
* **Shared lock table** — prewrite/pessimistic-lock claims key hashes
  in the segment BEFORE local checks, so cross-worker write-write
  conflicts are detected synchronously (LockedError → the normal
  lock-wait ladder), not after the fact.  A full table degrades to
  local-only detection; a dead slot's claims are freed by lease
  reclaim.
* **Tailing** — each worker replays every OTHER slot's records into its
  local replica (foreign prewrites become visible locks; commits
  convert them and bump table versions so the columnar cache
  invalidates), at snapshot/txn creation (synchronous catch-up: a
  statement begun after a peer's commit returned ALWAYS sees it) and
  from a background tailer thread.
* **Schema propagation** — a commit that writes the meta
  schema-version key publishes the segment's ``_schema_ver`` cell; the
  Domain's schema lease (session/session.py) reloads on a newer cell
  and stale commits fail retriably with ErrInfoSchemaChanged.

Failure semantics: a failed commit-record append (torn injection,
fsync failure) rolls the local txn back, best-effort logs a rollback
record, and re-raises — recovery honors the LAST disposition per
start_ts, so live state and recovered state agree.
"""

from __future__ import annotations

import contextlib
import fcntl
import hashlib
import json
import logging
import os
import signal
import threading
import time

from collections import deque

from ..utils import failpoint
from ..errors import WriteConflictError
from .mvcc import Lock, MVCCStore, OP_LOCK, OP_ROLLBACK, TSOracle
from . import wal as wal_mod

log = logging.getLogger("tidb_tpu.kv.shared_store")

#: timestamps per segment lease (one segment round-trip per BATCH ts)
TSO_BATCH = 64

#: background tailer poll period
TAIL_INTERVAL_S = 0.01

#: wall-clock budget a snapshot may spend blocked on the fleet
#: committed frontier (fresh_read_ts) before REFUSING the read loudly
FRESHNESS_BUDGET_MS = 1000.0

#: how long a lagging origin's freshness breaker stays open after a
#: wait timeout — reads degrade to explicit stale_ok instead of
#: re-paying the budget against a wedged-but-alive worker
FRESHNESS_BREAKER_S = 5.0

#: meta key whose commit publishes the fleet schema-version cell
SCHEMA_VERSION_KEY = b"m:schema_version"

#: committed-delta ring entries kept per table — the fold source for
#: the versioned result cache (executor/agg_cache.py).  Evicting past a
#: cached page's version only downgrades its next hit to a full
#: recompute, never to a wrong answer
DELTA_RING_CAP = 512


def key_hash(key: bytes) -> bytes:
    return hashlib.blake2b(key, digest_size=16).digest()


class SegmentTSOracle:
    """The fleet timestamp oracle: batched leases off the coordination
    segment's monotonic ``_tso`` cell, wall-clock anchored so GC's
    now-based safepoint arithmetic stays meaningful.  Same ``next_ts``
    surface as kv/mvcc.TSOracle — engines cannot tell them apart."""

    def __init__(self, coordinator, batch: int = TSO_BATCH):
        self._c = coordinator
        self._batch = max(int(batch), 1)
        self._lock = threading.Lock()
        self._next = 0
        self._end = 0
        self._local = TSOracle()  # post-unlink teardown fallback

    def next_ts(self) -> int:
        with self._lock:
            if self._next < self._end:
                self._next += 1
                return self._next
            # the lease floors at BOTH wall clock (GC arithmetic) and
            # our own high-water (advance_to may have pushed _next past
            # the old lease — e.g. after tailing a peer's commit)
            floor = max(int(time.time() * 1000) << 18, self._next)
            try:
                base, end = self._c.tso_lease(self._batch, floor)
            except Exception as e:  # noqa: BLE001 — segment unlinked at
                #   teardown: stay monotonic past everything we issued
                log.debug("segment tso lease failed (%s); local fallback",
                          e)
                ts = max(self._local.next_ts(), self._next + 1)
                self._next = ts
                return ts
            self._next = base + 1
            self._end = end
            return self._next

    def advance_to(self, ts: int):
        """Never issue a timestamp <= ``ts``: a replica's clock may not
        lag a commit it has applied (read-your-peers'-committed-writes
        — batched leases otherwise leave this worker's snapshot ts
        BELOW a peer's fresher commit_ts), nor a recovery high-water.
        Local-only: the segment cell is already past any commit_ts it
        ever granted, and the lease floor covers the recovery case."""
        with self._lock:
            self._local.advance_to(ts)
            self._next = max(self._next, int(ts))


def _table_id_of(key: bytes) -> "int | None":
    """Best-effort table id from a record/index key (None for meta)."""
    if len(key) >= 9 and key[:1] == b"t":
        from .. import tablecodec
        try:
            return tablecodec._dec_i64(key[1:9])
        except Exception as e:  # noqa: BLE001 — non-table 't' key: the
            #   caller only loses a cache-invalidation bump
            log.debug("table-id decode failed for %r: %s", key[:16], e)
            return None
    return None


def _maybe_kill(payload):
    if payload == "kill":
        os.kill(os.getpid(), signal.SIGKILL)


def _record_ts(rec: tuple) -> int:
    """The timestamp a log record carries (0 when none) — the replay
    high-water the recovered oracle must resume above."""
    kind = rec[0]
    if kind == "commit":
        return max(rec[2], rec[3])
    if kind in ("prewrite", "rollback", "raw", "rawdel"):
        return rec[2]
    return 0


class DurableMVCCStore(MVCCStore):
    """kv/mvcc.MVCCStore + WAL durability + fleet coherence.

    Solo (no coordinator): WAL append/recovery only — a single durable
    process.  Fleet (coordinator + slot): adds the segment TSO, the
    shared lock table, tailing and schema publication.
    """

    def __init__(self, wal: "wal_mod.WAL", *, coordinator=None,
                 slot: int = -1, oracle=None):
        super().__init__(oracle=oracle)
        self.wal = wal
        self._coord = coordinator
        self._slot = int(slot)
        self._tail_lock = threading.RLock()
        self._applied_lsn = wal.base_lsn
        # view-anchored write-conflict detection: a peer's commit can
        # carry a commit_ts BELOW a later-minted local read ts (the
        # shared oracle hands out the cts first) while its APPLY lands
        # only after that reader's statement already ran — so
        # has_commit_after(for_update_ts) alone can never see the
        # conflict and the write becomes a cross-worker lost update.
        # Every applied foreign commit bumps this sequence and stamps
        # its keys; lock/prewrite conflict any txn whose captured read
        # view (kv/store.Snapshot.view_seq) predates a written key's
        # stamp.
        self._foreign_apply_seq = 0
        self._key_apply_seq: dict = {}
        # durable commit frontier this worker publishes: forward-only
        # maxes fed by the WAL's durable-ack hook; the worker heartbeat
        # republishes (repairs a coordinator down-window) through the
        # same publish_frontier funnel
        self._frontier_mu = threading.Lock()
        self._frontier_ts = 0
        self._frontier_lsn = 0
        # per-origin freshness breaker: slot -> monotonic expiry.  A
        # stalled-but-alive origin that blew the freshness budget stops
        # gating reads until the window closes (reads carry stale_ok)
        self._breaker: "dict[int, float]" = {}
        self._stale_reads = 0
        self._last_stale_reason = ""
        self._last_stale_warn = 0.0
        #: observation hook: wait seconds per fleet ts acquisition
        #: (the Domain wires observe_hist("freshness_wait_seconds"))
        self.on_freshness_wait = None
        if coordinator is not None and self._slot >= 0:
            wal.on_durable = self._on_durable
        #: start_ts values holding >=1 shared lock-table claim
        self._claimed: set[int] = set()
        self._claim_mu = threading.Lock()
        self._lock_degrades = 0  # lock-table-full local-only fallbacks
        # per-table committed-delta ring: tid -> deque[(commit_ts,
        # row keys)], the versioned result cache's fold source.  The
        # floor is the ts BELOW which completeness is unproven; noted
        # holds commit ts the matching bump_table_version consumes (a
        # bump the ring never saw — DDL, BR, unwind — poisons folds)
        self._delta_ring: "dict[int, deque]" = {}
        self._delta_floor: "dict[int, int]" = {}
        self._delta_noted: "dict[int, set]" = {}
        self._delta_min_after = 0  # boot-time poison: checkpoint rows
        self._delta_mu = threading.Lock()
        self._tail_stop = threading.Event()
        self._tail_thread = None
        self._recovered = False
        # replayed txn fates, stashed by recover() for deferred
        # cross-region orphan resolution (fabric/region.py)
        self._recover_lock_owner: dict[int, int] = {}
        self._recover_disposition: dict[int, tuple] = {}

    # -- lifecycle ------------------------------------------------------------

    def recover(self, *, defer_orphans: bool = False) -> dict:
        """Checkpoint + tail replay + torn-tail truncation + orphan
        resolution.  Idempotent; runs under the cross-process WAL lock
        (boot of a fresh replica into a live fleet replays the whole
        log while peers keep appending — the tailer picks up the rest).

        ``defer_orphans=True`` skips the resolution pass and stashes the
        replayed disposition/owner maps on the instance: a region-
        sharded store (fabric/region.py) recovers EVERY region first,
        merges their dispositions, and only then resolves — a
        cross-region txn's commit point may live in another region's
        log (the primary key's region), and resolving from one region's
        log alone would roll back a committed txn's secondaries.
        """
        from ..session import tracing
        t0 = time.monotonic()
        with tracing.span("store.recover"):
            torn = self.wal.truncate_torn_tail()
            start = self.wal.base_lsn
            ck = self.wal.read_checkpoint()
            if ck is not None and ck[0] >= self.wal.base_lsn:
                self.load_state(ck[1])
                start = ck[0]
            end = self.wal.scan_valid_end()
            replayed = 0
            max_ts = 0
            lock_owner: dict[int, int] = {}   # start_ts -> origin slot
            disposition: dict[int, tuple] = {}  # start_ts -> last fate
            for rec, lsn in self.wal.read_records(start, end):
                fp = failpoint.inject("store-recover-replay")
                _maybe_kill(fp)
                self._apply(rec, replay=True, lock_owner=lock_owner,
                            disposition=disposition)
                max_ts = max(max_ts, _record_ts(rec))
                replayed += 1
            self._applied_lsn = end
            if max_ts:
                # the oracle must resume ABOVE every replayed version:
                # a same-millisecond restart could otherwise mint
                # timestamps below them (invisible to new snapshots)
                self.tso.advance_to(max_ts)
            # fold completeness cannot extend below this boot: rows
            # restored from the checkpoint never passed through the
            # delta ring
            self._delta_min_after = max(self._delta_min_after, max_ts)
            self._recover_lock_owner = lock_owner
            self._recover_disposition = disposition
            resolved = 0
            if not defer_orphans:
                resolved = self.resolve_orphans(disposition, lock_owner)
            self._publish_after_recovery()
            self._recovered = True
            wal_mod._bump("wal_recoveries")
            wal_mod._bump("wal_replayed_records", replayed)
            out = {"replayed": replayed, "torn_bytes": torn,
                   "resolved_orphans": resolved,
                   "from_checkpoint": ck is not None,
                   "recover_s": round(time.monotonic() - t0, 4)}
            log.info("store recovered: %s", out)
            return out

    def resolve_orphans(self, disposition: "dict[int, tuple]",
                        lock_owner: "dict[int, int] | None" = None,
                        *, assume_fenced: bool = False) -> int:
        """Resolve orphaned prewrites via their primary: a commit record
        for the start_ts is the primary's committed proof; none means
        the txn died before its commit point.  Locks owned by a LIVE
        sibling slot are in-flight 2PC, not orphans — UNLESS
        ``assume_fenced``: a region-failover owner holds the new epoch,
        so the old owner (even one still heartbeating: a partitioned
        zombie) can never append its commit record past the fence, and
        deferring to it would leave its locks blocking reads forever.

        ``disposition`` may be wider than this store's own log: the
        region router merges every region's replayed dispositions so a
        secondary in region B finds its primary's commit from region
        A's log (Percolator's commit point is per-txn, not per-region).
        """
        lock_owner = lock_owner if lock_owner is not None else {}
        live = set()
        if self._coord is not None and not assume_fenced:
            with contextlib.suppress(Exception):
                live = set(self._coord.live_slots())
        resolved = 0
        with self._lock:
            leftovers = list(self.locks.items())
        for key, lk in leftovers:
            owner = lock_owner.get(lk.start_ts, -2)
            if owner in live and owner != self._slot:
                continue
            fate = disposition.get(lk.start_ts)
            tid = _table_id_of(key)
            if fate is not None and fate[0] == "commit":
                MVCCStore.commit(self, [key], lk.start_ts, fate[1])
                rec = ("commit", self._slot, lk.start_ts, fate[1],
                       [key], [tid] if tid is not None else [])
            else:
                MVCCStore.rollback(self, [key], lk.start_ts)
                rec = ("rollback", self._slot, lk.start_ts, [key])
            # the resolution is logged so every replica (live peers
            # tailing now, future recoveries) converges on one fate
            with contextlib.suppress(Exception):
                self.wal.append(rec)
            resolved += 1
        return resolved

    def _publish_after_recovery(self):
        if self._coord is None:
            return
        with contextlib.suppress(Exception):
            if self._slot >= 0:
                self._coord.set_wal_applied(self._slot, self._applied_lsn)
            v = self._local_schema_version()
            if v:
                self._coord.publish_schema_version(v)

    def _local_schema_version(self) -> int:
        res = self.map.read(SCHEMA_VERSION_KEY, 1 << 62)
        if res is None or res[1] is None:
            return 0
        with contextlib.suppress(Exception):
            return int(json.loads(res[1]))
        return 0

    def fleet_schema_version(self) -> int:
        """The published schema-version cell (0 solo / unreadable)."""
        if self._coord is None:
            return 0
        try:
            return self._coord.schema_version()
        except Exception as e:  # noqa: BLE001 — segment may be unlinked
            log.debug("schema cell unreadable: %s", e)
            return 0

    def start_tailer(self):
        if self._coord is None or self._tail_thread is not None:
            return

        def loop():
            while not self._tail_stop.wait(TAIL_INTERVAL_S):
                try:
                    self.catch_up()
                except Exception as e:  # noqa: BLE001 — a tail hiccup
                    #   retries next tick; persistent failure is visible
                    #   as a stuck wal_applied column
                    log.warning("wal tailer catch-up failed: %s", e)

        self._tail_thread = threading.Thread(
            target=loop, daemon=True, name="wal-tailer")
        self._tail_thread.start()

    def close(self):
        self._tail_stop.set()
        if self._tail_thread is not None:
            self._tail_thread.join(timeout=2.0)
        self.wal.close()

    # -- tailing --------------------------------------------------------------

    def catch_up(self):
        """Apply every committed record past our applied frontier.  No
        coordinator → solo: nothing ever appears we did not write."""
        if self._coord is None:
            return
        with self._tail_lock:
            # chaos door: delay tail application — the freshness wait
            # in fresh_read_ts must cover the gap, never a stale answer
            failpoint.inject("tail-lag")
            self.wal.reopen_if_truncated()
            if self._applied_lsn < self.wal.base_lsn:
                # a peer truncated past us.  Legal only when our applied
                # column said so (we had applied everything below the
                # new base) or we are a slot the fleet reclaimed as dead
                # — a zombie in that state may be missing checkpoint-
                # only records, which is worth a loud log, not silence
                log.warning(
                    "wal truncated past applied frontier (%d < %d): "
                    "records below the new base live only in the "
                    "checkpoint", self._applied_lsn, self.wal.base_lsn)
                self._applied_lsn = self.wal.base_lsn
            end = self.wal.committed_lsn()
            if end <= self._applied_lsn:
                return
            n = 0
            for rec, lsn in self.wal.read_records(self._applied_lsn, end):
                self._apply(rec)
                self._applied_lsn = lsn
                n += 1
            if n and self._slot >= 0:
                with contextlib.suppress(Exception):
                    self._coord.set_wal_applied(self._slot,
                                                self._applied_lsn)

    def _apply(self, rec: tuple, replay: bool = False,
               lock_owner: "dict | None" = None,
               disposition: "dict | None" = None):
        """Apply one log record to the local replica.  Outside replay,
        records from OUR OWN slot are skipped (already applied live)."""
        kind, origin = rec[0], rec[1]
        own = (not replay and self._slot >= 0 and origin == self._slot)
        if kind == "prewrite":
            _k, _o, start_ts, primary, muts = rec
            if lock_owner is not None:
                lock_owner[start_ts] = origin
            if own:
                return
            with self._lock:
                for key, op, value in muts:
                    cur = self.locks.get(key)
                    if cur is not None and cur.start_ts != start_ts:
                        # both sides degraded past the shared lock
                        # table (table-full) and raced: keep ours, the
                        # foreign txn's commit/rollback still applies
                        log.warning(
                            "foreign prewrite overlaps local lock "
                            "(ts %d vs %d) — shared lock table was "
                            "full", start_ts, cur.start_ts)
                        continue
                    self.locks[key] = Lock(start_ts, primary, op, value)
        elif kind == "commit":
            _k, _o, start_ts, commit_ts, keys, tids = rec
            if disposition is not None:
                disposition[start_ts] = ("commit", commit_ts)
            if own:
                return
            # the local clock must pass the applied commit BEFORE it is
            # readable, so the very next local snapshot includes it
            self.tso.advance_to(commit_ts)
            try:
                MVCCStore.commit(self, keys, start_ts, commit_ts)
            except Exception as e:  # noqa: BLE001 — a tailed commit for
                #   a txn this replica resolved differently (degraded
                #   lock-table race) must not wedge the tailer; the
                #   divergence is logged, not swallowed
                log.warning("tailed commit apply failed for ts %d: %s",
                            start_ts, e)
            # stamp the keys AFTER the values landed: any local
            # statement whose read view was captured before this point
            # computed from the pre-commit values and must conflict
            # when it tries to write these keys (see _view_conflict)
            with self._lock:
                self._foreign_apply_seq += 1
                seq = self._foreign_apply_seq
                for key in keys:
                    self._key_apply_seq[key] = seq
            self._note_delta(commit_ts, keys)
            for tid in tids:
                self.bump_table_version(tid, commit_ts)
            if not replay:
                wal_mod._bump("wal_tail_records")
        elif kind == "rollback":
            _k, _o, start_ts, keys = rec
            if disposition is not None:
                disposition[start_ts] = ("rollback",)
            if own:
                return
            # last disposition wins: a commit record followed by a
            # rollback record for the same start_ts (its fsync failed
            # and the owner rolled back) must UNWIND, not coexist
            regressed = self._unwindable(keys, start_ts)
            self.unwind_commit(keys, start_ts)
            MVCCStore.rollback(self, keys, start_ts)
            if regressed:
                # visible rows just regressed WITHOUT a commit record:
                # advance the touched tables' versions under a fresh ts
                # (never noted, so the fold ring poisons itself) so
                # every stamped cache page over them invalidates rather
                # than serving the resurrected state
                ts = 0
                with contextlib.suppress(Exception):
                    ts = self.tso.next_ts()
                for tid in sorted({t for t in (_table_id_of(k)
                                               for k in regressed)
                                   if t is not None}):
                    self.bump_table_version(tid, ts)
            if not replay:
                wal_mod._bump("wal_tail_records")
        elif kind == "raw":
            _k, _o, commit_ts, pairs, tids = rec
            if own:
                return
            self.tso.advance_to(commit_ts)
            MVCCStore.raw_batch_put(self, pairs, commit_ts)
            self._note_delta(commit_ts, [k for k, _v in pairs])
            for tid in tids:
                self.bump_table_version(tid, commit_ts)
        elif kind == "rawdel":
            _k, _o, _ts, start, end = rec
            if own:
                return
            MVCCStore.raw_delete_range(self, start, end)
            self._poison_range(start, end)
        else:
            log.warning("unknown wal record kind %r skipped", kind)

    # -- the fleet committed frontier -----------------------------------------

    def _on_durable(self, commit_ts: int, cover_lsn: int):
        """WAL durable-ack hook: ``commit_ts`` is fsync-acked and the
        sync covers through ``cover_lsn``.  Runs on whatever thread paid
        the fsync, BEFORE that commit's append returns to its caller —
        so by the time any client sees an ack, the frontier the fleet
        gates reads on already includes it (the linearizability edge)."""
        with self._frontier_mu:
            self._frontier_ts = max(self._frontier_ts, int(commit_ts))
            self._frontier_lsn = max(self._frontier_lsn, int(cover_lsn))
        self.publish_frontier()

    def publish_frontier(self):
        """Publish this worker's durable commit frontier to the segment
        (forward-only there too).  Also called each worker heartbeat so
        a publish lost to a coordinator down-window is repaired within
        a beat.  The ``frontier-stall`` failpoint freezes publication —
        the chaos shape for a worker whose fsyncs complete but whose
        frontier column wedges."""
        if self._coord is None or self._slot < 0:
            return
        if failpoint.inject("frontier-stall"):
            return
        with self._frontier_mu:
            ts, lsn = self._frontier_ts, self._frontier_lsn
        if not ts:
            return
        with contextlib.suppress(Exception):
            self._coord.set_commit_frontier(self._slot, ts, lsn)

    def _note_stale(self, reason: str):
        """A read is proceeding WITHOUT fleet-freshness proof.  Loud by
        contract: counted (``freshness_stale_ok``, surfaced in EXPLAIN
        ANALYZE via the fabric gauges and /metrics) and rate-limit
        logged — never silent."""
        self._stale_reads += 1
        self._last_stale_reason = reason
        from ..fabric import state as fabric_state
        with contextlib.suppress(Exception):
            fabric_state.bump("freshness_stale_ok")
        now = time.monotonic()
        if now - self._last_stale_warn >= 1.0:
            self._last_stale_warn = now
            log.warning("stale_ok read downgrade: %s", reason)

    def fresh_read_ts(self) -> int:
        """Fleet-linearizable timestamp acquisition: the paper's
        strong-consistency contract — a query observes every
        transaction acked before it began — enforced ACROSS workers.

        Reads every live origin's published durable frontier
        (commit_ts, covering LSN) at ts-acquisition.  The returned ts
        is fenced above every frontier commit_ts (``advance_to`` +
        ``next_ts``), then we block — targeted catch-up under the
        bounded ``freshnessWait`` budget — until the local replica has
        applied through every gating origin's frontier LSN.

        Degradations are explicit, never silent: a dead/reclaimed slot
        stops gating at lease reclaim (``commit_frontiers`` filters to
        live leases); an unreachable coordinator or a breaker-open
        origin downgrades the read to stale_ok (counted + logged); a
        stalled-but-alive origin that exhausts the budget raises
        :class:`~tidb_tpu.errors.FreshnessWaitError` (9011) and trips
        its per-origin breaker for FRESHNESS_BREAKER_S."""
        if self._coord is None:
            return self.tso.next_ts()
        t0 = time.monotonic()
        waited = False
        try:
            try:
                fronts = self._coord.commit_frontiers()
            except Exception as e:  # noqa: BLE001 — coordinator gone:
                #   freshness is unprovable; degrade LOUDLY, not
                #   silently (a plain next_ts read may miss peers)
                self._note_stale(f"coordinator unreachable ({e})")
                return self.tso.next_ts()
            now = time.monotonic()
            need_ts = 0
            need_lsn = 0
            gating: "dict[int, int]" = {}
            for slot, (fts, flsn) in fronts.items():
                if slot == self._slot:
                    continue
                if self._breaker.get(slot, 0.0) > now:
                    self._note_stale(
                        f"origin slot {slot} freshness breaker open")
                    continue
                need_ts = max(need_ts, fts)
                need_lsn = max(need_lsn, flsn)
                gating[slot] = flsn
            if need_ts:
                # ts fence: never issue a snapshot ts at-or-below a
                # peer's acked durable commit
                self.tso.advance_to(need_ts)
            ts = self.tso.next_ts()
            if self._applied_lsn >= need_lsn:
                return ts
            # LSN fence: block until the tail is applied through every
            # gating origin's frontier
            from ..errors import BackoffExhaustedError, FreshnessWaitError
            from ..fabric import state as fabric_state
            from ..utils.backoff import Backoffer
            waited = True
            with contextlib.suppress(Exception):
                fabric_state.bump("freshness_waits")
            bo = Backoffer(budget_ms=FRESHNESS_BUDGET_MS, wall_clock=True)
            while True:
                try:
                    self.catch_up()
                except Exception as e:  # noqa: BLE001 — a tail hiccup
                    #   retries inside the budget like any other lag
                    log.debug("freshness catch-up failed: %s", e)
                if self._applied_lsn >= need_lsn:
                    return ts
                try:
                    bo.backoff("freshnessWait")
                except BackoffExhaustedError as e:
                    lagging = sorted(s for s, lsn in gating.items()
                                     if lsn > self._applied_lsn)
                    expiry = time.monotonic() + FRESHNESS_BREAKER_S
                    for s in lagging:
                        self._breaker[s] = expiry
                    with contextlib.suppress(Exception):
                        fabric_state.bump("freshness_timeouts")
                    raise FreshnessWaitError(
                        "snapshot freshness wait exhausted: applied "
                        f"lsn {self._applied_lsn} < fleet frontier "
                        f"{need_lsn} (lagging origin slots {lagging}); "
                        "refusing stale read") from e
        finally:
            hook = self.on_freshness_wait
            if hook is not None:
                with contextlib.suppress(Exception):
                    hook(time.monotonic() - t0 if waited else 0.0)

    # -- the shared lock table ------------------------------------------------

    def _claim_shared(self, keys, start_ts: int):
        from ..errors import LockedError
        if self._coord is None:
            return
        keys = list(keys)
        hashes = [key_hash(k) for k in keys]
        try:
            holder, idx = self._coord.lock_claim(hashes, start_ts,
                                                 max(self._slot, 0))
        except Exception as e:  # noqa: BLE001 — segment gone: local-only
            log.debug("shared lock claim failed (%s); local-only", e)
            return
        if holder == -1:
            self._lock_degrades += 1
            return
        if holder:
            raise LockedError(
                f"key locked by fleet txn {holder}",
                key=keys[idx], lock_ts=holder)
        with self._claim_mu:
            self._claimed.add(start_ts)

    def _release_shared(self, start_ts: int):
        if self._coord is None:
            return
        with self._claim_mu:
            if start_ts not in self._claimed:
                return
            self._claimed.discard(start_ts)
        with contextlib.suppress(Exception):
            self._coord.lock_release(start_ts)

    # -- transactional overrides ----------------------------------------------

    def read_view_seq(self) -> int:
        """Anchor for a new read view (captured by kv/store.Snapshot):
        the count of foreign commits this replica has applied.  A write
        conflicts when any of its keys carries a HIGHER per-key stamp —
        the statement computed from values older than an already-applied
        peer commit, the lost-update window that commit_ts comparison
        cannot close (a peer's cts may be below a later-minted local ts
        while its apply trails both)."""
        return self._foreign_apply_seq

    def _view_conflict(self, keys, view_seq, start_ts=None):
        """Raise WriteConflictError when a foreign commit touching one
        of ``keys`` was applied AFTER the writing statement's read view
        was captured.  Keys the txn already holds its OWN pessimistic
        lock on are exempt: their conflict was checked at lock time and
        the held claim has excluded foreign applies since (mirrors the
        base prewrite's DoPessimisticCheck skip)."""
        if view_seq is None:
            return
        with self._lock:
            for key in keys:
                stamp = self._key_apply_seq.get(key, 0)
                if stamp <= view_seq:
                    continue
                lk = self.locks.get(key)
                if (start_ts is not None and lk is not None
                        and lk.start_ts == start_ts
                        and lk.op == OP_LOCK):
                    continue
                raise WriteConflictError(
                    "write conflict: key rewritten by a peer commit "
                    f"applied after this statement's read view "
                    f"(view seq {view_seq} < key stamp {stamp})")

    def prewrite(self, mutations, primary: bytes, start_ts: int,
                 view_seq: "int | None" = None):
        self._claim_shared([m[0] for m in mutations], start_ts)
        try:
            self.catch_up()  # conflicts committed on peers must be seen
            self._view_conflict([m[0] for m in mutations], view_seq,
                                start_ts=start_ts)
            super().prewrite(mutations, primary, start_ts)
        except BaseException:
            self._release_shared(start_ts)
            raise
        # the prewrite record makes foreign locks visible to peers and
        # gives recovery its orphan inventory; its durability rides the
        # commit record's fsync (same file)
        self.wal.append(("prewrite", self._slot, start_ts, primary,
                         [(k, op, v) for k, op, v in mutations]))

    def commit(self, keys, start_ts: int, commit_ts: int):
        keys = list(keys)
        tids = sorted({t for t in (_table_id_of(k) for k in keys)
                       if t is not None})
        schema_ver = self._pending_schema_version(keys, start_ts)
        try:
            # WAL discipline: the commit record lands (and fsyncs under
            # policy `commit`) BEFORE the local apply — an acked commit
            # is always recoverable
            self.wal.append(("commit", self._slot, start_ts, commit_ts,
                             keys, tids), sync=True, commit_ts=commit_ts)
        except BaseException:
            # the commit never reached its durability point: roll back
            # (recovery honors the LAST disposition per start_ts, so a
            # half-appended commit record is overridden)
            with contextlib.suppress(Exception):
                super().rollback(keys, start_ts)
            with contextlib.suppress(Exception):
                self.wal.append(("rollback", self._slot, start_ts, keys))
            self._release_shared(start_ts)
            raise
        try:
            super().commit(keys, start_ts, commit_ts)
        finally:
            self._release_shared(start_ts)
        # the Transaction layer bumps table versions right after this
        # returns (kv/store.py); noting first lets those bumps consume
        # the ts instead of poisoning the fold ring
        self._note_delta(commit_ts, keys)
        if schema_ver and self._coord is not None:
            with contextlib.suppress(Exception):
                self._coord.publish_schema_version(schema_ver)

    def _pending_schema_version(self, keys, start_ts: int) -> int:
        """The schema version this commit publishes (0 = not a DDL)."""
        if self._coord is None or SCHEMA_VERSION_KEY not in keys:
            return 0
        with self._lock:
            lk = self.locks.get(SCHEMA_VERSION_KEY)
            if lk is None or lk.start_ts != start_ts or lk.value is None:
                return 0
            with contextlib.suppress(Exception):
                return int(json.loads(lk.value))
        return 0

    def rollback(self, keys, start_ts: int):
        keys = list(keys)
        try:
            super().rollback(keys, start_ts)
            self.wal.append(("rollback", self._slot, start_ts, keys))
        finally:
            self._release_shared(start_ts)

    def acquire_pessimistic_lock(self, keys, primary: bytes,
                                 start_ts: int, for_update_ts: int,
                                 view_seq: "int | None" = None):
        keys = list(keys)
        self._claim_shared(keys, start_ts)
        try:
            self.catch_up()
            self._view_conflict(keys, view_seq, start_ts=start_ts)
            super().acquire_pessimistic_lock(keys, primary, start_ts,
                                             for_update_ts)
        except BaseException:
            # free only THIS batch's claims: earlier statements of the
            # txn still hold theirs until commit/rollback
            if self._coord is not None:
                with contextlib.suppress(Exception):
                    self._coord.lock_release(
                        start_ts, [key_hash(k) for k in keys])
            raise

    def resolve_lock(self, key: bytes, committed: bool, commit_ts: int = 0):
        with self._lock:
            lk = self.locks.get(key)
        if lk is None:
            return
        super().resolve_lock(key, committed, commit_ts)
        # the resolution must be fleet-visible: peers holding the same
        # tailed lock converge on the same fate
        rec = (("commit", self._slot, lk.start_ts, commit_ts, [key],
                [t for t in (_table_id_of(key),) if t is not None])
               if committed else
               ("rollback", self._slot, lk.start_ts, [key]))
        with contextlib.suppress(Exception):
            self.wal.append(rec)

    # -- raw overrides --------------------------------------------------------

    def raw_put(self, key: bytes, value: bytes, commit_ts: int | None = None):
        ts = commit_ts if commit_ts is not None else self.tso.next_ts()
        super().raw_put(key, value, commit_ts=ts)
        self._note_delta(ts, [key])
        tid = _table_id_of(key)
        self.wal.append(("raw", self._slot, ts, [(key, value)],
                         [tid] if tid is not None else []),
                        commit_ts=ts)

    def raw_batch_put(self, pairs, commit_ts: int | None = None):
        pairs = list(pairs)
        if not pairs:
            return
        ts = commit_ts if commit_ts is not None else self.tso.next_ts()
        super().raw_batch_put(pairs, commit_ts=ts)
        self._note_delta(ts, [k for k, _v in pairs])
        tids = sorted({t for t in (_table_id_of(k) for k, _v in pairs)
                       if t is not None})
        self.wal.append(("raw", self._slot, ts, pairs, tids),
                        commit_ts=ts)

    def raw_delete_range(self, start: bytes, end: bytes):
        super().raw_delete_range(start, end)
        self._poison_range(start, end)
        # ts-stamped so BR's backup-ts tail filter excludes a delete
        # that raced PAST the backup snapshot (its rows are in the
        # backup; replaying the delete would erase backed-up data)
        self.wal.append(("rawdel", self._slot, self.tso.next_ts(),
                         start, end))

    # -- committed-delta ring (versioned result cache fold source) ------------

    def _note_delta(self, commit_ts: int, keys):
        """Record which row keys a committed mutation touched, per
        table.  Only record keys are kept (index keys re-derive from
        the row); every tid seen in ``keys`` marks ``commit_ts`` noted
        so the matching :meth:`bump_table_version` knows the ring
        covers that advance."""
        if not commit_ts:
            return
        from .. import tablecodec
        by_tid: "dict[int, list]" = {}
        for k in keys:
            tid = _table_id_of(k)
            if tid is None:
                continue
            lst = by_tid.setdefault(tid, [])
            if len(k) >= 19 and k[9:11] == tablecodec.RECORD_SEP:
                lst.append(k)
        if not by_tid:
            return
        with self._delta_mu:
            for tid, ks in by_tid.items():
                ring = self._delta_ring.get(tid)
                if ring is None:
                    ring = self._delta_ring[tid] = deque()
                    # completeness starts here: commit timestamps are
                    # unique, so (commit_ts - 1, commit_ts] holds only
                    # this commit — but never lift an earlier poison
                    self._delta_floor[tid] = max(
                        commit_ts - 1, self._delta_floor.get(tid, 0))
                ring.append((commit_ts, tuple(ks)))
                while len(ring) > DELTA_RING_CAP:
                    old_ts, _old = ring.popleft()
                    self._delta_floor[tid] = max(
                        self._delta_floor[tid], old_ts)
                noted = self._delta_noted.setdefault(tid, set())
                noted.add(commit_ts)
                if len(noted) > 1024:
                    # unconsumed ts leak only from bump-less raw writes;
                    # clearing risks one spurious poison, which merely
                    # costs a full recompute
                    noted.clear()

    def bump_table_version(self, table_id: int, commit_ts: int = 0) -> int:
        """Local watermark bump + fleet publication: every advance lands
        in the segment's table-version vector so stamped cache pages on
        EVERY worker invalidate.  An advance the delta ring never noted
        (DDL reorg, BR restore, a rollback unwind) poisons folds across
        it — the data changed through a path the ring cannot replay."""
        v = super().bump_table_version(table_id, commit_ts)
        if table_id is None or table_id <= 0:
            return v
        noted = False
        with self._delta_mu:
            s = self._delta_noted.get(table_id)
            if s is not None and commit_ts in s:
                s.discard(commit_ts)
                noted = True
        ts = commit_ts
        if not ts:
            with contextlib.suppress(Exception):
                ts = self.tso.next_ts()
        if not noted and ts:
            with self._delta_mu:
                self._delta_floor[table_id] = max(
                    self._delta_floor.get(table_id, 0), ts)
        if ts and self._coord is not None:
            with contextlib.suppress(Exception):
                self._coord.table_version_advance([(table_id, ts)])
        return v

    def _poison_range(self, start: bytes, end: bytes):
        """A range delete cannot say which committed rows it removed:
        kill fold eligibility for whatever it may cover (one ring for a
        same-table range, everything for a cross-table one)."""
        tid_a, tid_b = _table_id_of(start), _table_id_of(end)
        ts = 0
        with contextlib.suppress(Exception):
            ts = self.tso.next_ts()
        if not ts:
            return
        with self._delta_mu:
            if tid_a is not None and tid_a == tid_b:
                self._delta_floor[tid_a] = max(
                    self._delta_floor.get(tid_a, 0), ts)
            else:
                self._delta_min_after = max(self._delta_min_after, ts)

    def _unwindable(self, keys, start_ts: int) -> "list[bytes]":
        """Keys holding a COMMITTED version stamped ``start_ts`` — the
        set a commit-then-rollback unwind will actually regress."""
        out = []
        with self._lock:
            for key in keys:
                chain = self.map.vals.get(key)
                if chain and any(v[1] == start_ts and v[2] != OP_ROLLBACK
                                 for v in chain):
                    out.append(key)
        return out

    def delta_keys_since(self, table_id: int, after_ts: int,
                         upto_ts: int) -> "list[bytes] | None":
        """Row keys committed to ``table_id`` in (after_ts, upto_ts] —
        the fold set for a versioned cache hit at a newer version — or
        None when the ring cannot PROVE completeness for that range:
        entries evicted past ``after_ts``, an un-noted advance poisoned
        the table, the range predates this boot's replay, or our
        replica has not applied through ``upto_ts`` yet.  None always
        means "recompute from scratch", never "no delta rows"."""
        if after_ts >= upto_ts:
            return []
        with self._lock:
            applied_ts = self.table_version_ts.get(table_id, 0)
        if applied_ts < upto_ts:
            return None
        with self._delta_mu:
            if after_ts < self._delta_min_after:
                return None
            ring = self._delta_ring.get(table_id)
            if ring is None:
                return None
            if after_ts < self._delta_floor.get(table_id, 1 << 62):
                return None
            out: "list[bytes]" = []
            for ts, ks in ring:
                if after_ts < ts <= upto_ts:
                    out.extend(ks)
            return out

    # -- introspection --------------------------------------------------------

    def wal_status(self) -> dict:
        return {"applied_lsn": self._applied_lsn,
                "end_lsn": self.wal.end_lsn(),
                "base_lsn": self.wal.base_lsn,
                "slot": self._slot,
                "fleet": self._coord is not None,
                "lock_degrades": self._lock_degrades,
                "fsync_policy": self.wal.fsync_policy(),
                "frontier_ts": self._frontier_ts,
                "frontier_lsn": self._frontier_lsn,
                "stale_reads": self._stale_reads,
                "last_stale_reason": self._last_stale_reason}


# -- construction -------------------------------------------------------------

def open_durable_mvcc(wal_dir: str) -> DurableMVCCStore:
    """Build (and recover) the durable engine for this process.  Fleet
    context (coordinator + slot) is taken from fabric/state when a
    worker activated it; otherwise the store is solo-durable."""
    from ..fabric import state as fabric_state
    coordinator = fabric_state.coordinator()
    slot = fabric_state.slot() if coordinator is not None else -1
    w = wal_mod.WAL(wal_dir, coordinator=coordinator)
    oracle = (SegmentTSOracle(coordinator)
              if coordinator is not None else None)
    eng = DurableMVCCStore(w, coordinator=coordinator, slot=slot,
                           oracle=oracle)
    eng.recover()
    if coordinator is not None:
        eng.start_tailer()
    return eng


@contextlib.contextmanager
def store_init_lock(wal_dir: str):
    """Cross-process serialization of [open store → recover → bootstrap
    → seed]: the first worker in pays the genesis writes, later workers
    replay them from the log and skip (fabric/worker.py)."""
    os.makedirs(wal_dir, exist_ok=True)
    f = open(os.path.join(wal_dir, "init.lock"), "a+b")  # noqa: SIM115
    try:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)
    finally:
        f.close()
