"""Multi-table UPDATE/DELETE over joins (reference: executor/update.go +
delete.go multi-table forms)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table emp (id int primary key, dept int, sal int)")
    tk.must_exec("create table dept (id int primary key, bonus int)")
    tk.must_exec("insert into emp values (1,10,100),(2,10,200),(3,20,300)")
    tk.must_exec("insert into dept values (10, 5), (20, 7)")
    return tk


class TestMultiUpdate:
    def test_join_update(self, tk):
        tk.must_exec("update emp e, dept d set e.sal = e.sal + d.bonus "
                     "where e.dept = d.id")
        tk.must_query("select id, sal from emp order by id").check(
            [("1", "105"), ("2", "205"), ("3", "307")])

    def test_updates_both_tables(self, tk):
        tk.must_exec("update emp e join dept d on e.dept = d.id "
                     "set e.sal = 0, d.bonus = d.bonus * 10 where e.id = 1")
        tk.must_query("select sal from emp where id = 1").check([("0",)])
        tk.must_query("select bonus from dept where id = 10").check(
            [("50",)])

    def test_each_row_updated_once(self, tk):
        """A target row matched by several join rows updates exactly once
        (MySQL multi-table semantics)."""
        tk.must_exec("update dept d, emp e set d.bonus = d.bonus + 1 "
                     "where e.dept = d.id")
        tk.must_query("select bonus from dept order by id").check(
            [("6",), ("8",)])

    def test_unqualified_column_resolves_uniquely(self, tk):
        tk.must_exec("update emp e, dept d set sal = 1 where e.dept = d.id")
        tk.must_query("select distinct sal from emp where dept in (10, 20)"
                      ).check([("1",)])
        # 'id' exists in both tables: ambiguous
        e = tk.exec_error(
            "update emp e, dept d set id = 1 where e.dept = d.id")
        assert "ambiguous" in str(e)

    def test_requires_pk_handle(self, tk):
        tk.must_exec("create table nopk (a int)")
        e = tk.exec_error("update nopk n, dept d set n.a = 1")
        assert "primary key" in str(e)


class TestMultiDelete:
    def test_delete_target_from_join(self, tk):
        tk.must_exec("delete e from emp e join dept d on e.dept = d.id "
                     "where d.id = 10")
        tk.must_query("select id from emp").check([("3",)])
        tk.must_query("select count(*) from dept").check([("2",)])

    def test_delete_from_using(self, tk):
        tk.must_exec("delete from emp using emp, dept "
                     "where emp.dept = dept.id and dept.bonus = 7")
        tk.must_query("select id from emp order by id").check(
            [("1",), ("2",)])

    def test_delete_two_targets(self, tk):
        tk.must_exec("delete e, d from emp e join dept d on e.dept = d.id "
                     "where d.id = 20")
        tk.must_query("select count(*) from emp").check([("2",)])
        tk.must_query("select count(*) from dept").check([("1",)])

    def test_rollback_covers_multi_dml(self, tk):
        tk.must_exec("begin")
        tk.must_exec("delete e, d from emp e join dept d on e.dept = d.id")
        tk.must_query("select count(*) from emp").check([("0",)])
        tk.must_exec("rollback")
        tk.must_query("select count(*) from emp").check([("3",)])
        tk.must_query("select count(*) from dept").check([("2",)])


class TestMultiDMLLocksAndPrivs:
    def test_multi_update_respects_foreign_read_lock(self, tk):
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("lock tables emp read")
        e = tk.exec_error(
            "update emp e, dept d set e.sal = 1 where e.dept = d.id")
        assert e.code == 8020
        tk2.must_exec("unlock tables")

    def test_multi_delete_respects_foreign_read_lock(self, tk):
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("lock tables emp read")
        e = tk.exec_error(
            "delete e from emp e join dept d on e.dept = d.id")
        assert e.code == 8020
        tk2.must_exec("unlock tables")

    def test_aliased_delete_target_requires_delete_priv(self, tk):
        tk.must_exec("create user 'ro'@'%'")
        tk.must_exec("grant select on test.* to 'ro'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "ro@%"
        e = tk2.exec_error(
            "delete a from emp as a join dept d on a.dept = d.id")
        assert "denied" in str(e).lower()
        tk.must_query("select count(*) from emp").check([("3",)])

    def test_multi_update_needs_update_only_on_set_targets(self, tk):
        tk.must_exec("create user 'half'@'%'")
        tk.must_exec("grant select on test.* to 'half'@'%'")
        tk.must_exec("grant update on test.emp to 'half'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "half@%"
        # only emp is a set-target: allowed despite no UPDATE on dept
        tk2.must_exec("update emp e join dept d on e.dept = d.id "
                      "set e.sal = 2 where d.id = 10")
        e = tk2.exec_error("update emp e join dept d on e.dept = d.id "
                           "set d.bonus = 0")
        assert "denied" in str(e).lower()

    def test_unqualified_set_needs_priv_on_owning_table_only(self, tk):
        tk.must_exec("create user 'u2'@'%'")
        tk.must_exec("grant select on test.* to 'u2'@'%'")
        tk.must_exec("grant update on test.emp to 'u2'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "u2@%"
        # sal exists only in emp: update priv on dept must not be needed
        tk2.must_exec("update emp e, dept d set sal = 3 where e.dept = d.id")

    def test_order_by_limit_rejected_in_multi_update(self, tk):
        e = tk.exec_error("update emp e join dept d on e.dept = d.id "
                          "set e.sal = 0 limit 1")
        assert "Incorrect usage" in str(e)

    def test_set_default_in_multi_update(self, tk):
        tk.must_exec("create table wd (id int primary key, v int default 9)")
        tk.must_exec("insert into wd values (1, 1)")
        tk.must_exec("update wd w, dept d set w.v = default where w.id = 1")
        tk.must_query("select v from wd").check([("9",)])
