"""Backup / restore + logical dump + checkpointed import — the BR,
Dumpling and Lightning roles (reference: br/pkg/task/backup.go:221,
restore.go:216, dumpling/export/dump.go, br/pkg/lightning/checkpoints/).

Backup format (one directory per run):
    backupmeta.json                 run metadata + per-table stats
    {db}.{table}.schema.json       TableInfo (exact catalog state)
    {db}.{table}.data.jsonl        rows as {"h": handle, "v": hex(rowcodec)}
Row payloads reuse the engine's row codec, so restore is bit-exact —
decimals, dates and binary collations round-trip without re-parsing.

Dump format (mydumper-style, reference dumpling/export):
    {db}.{table}-schema.sql        CREATE TABLE
    {db}.{table}.sql | .csv        INSERT statements / CSV rows

Import reads a dump directory with a progress checkpoint
(_import_checkpoint.json) updated after every committed batch: a crashed
import resumes at the first unfinished table/offset instead of redoing or
duplicating work (reference: lightning checkpoints)."""

from __future__ import annotations

import json
import os
import time

from . import tablecodec
from .errors import TiDBError
from .model import TableInfo
from .table import Table

BATCH = 2048


# -- backup (reference: br/pkg/task/backup.go) -------------------------------

def backup_database(session, db_name: str, dest: str) -> dict:
    infos = session.infoschema()
    if infos.schema_by_name(db_name) is None:
        raise TiDBError(f"Unknown database '{db_name}'")
    os.makedirs(dest, exist_ok=True)
    txn = session.store.begin()  # one snapshot: a consistent backup
    meta = {"db": db_name, "ts": txn.start_ts,
            "created": time.strftime("%Y-%m-%d %H:%M:%S"), "tables": []}
    try:
        for info in infos.tables_in_schema(db_name):
            base = os.path.join(dest, f"{db_name}.{info.name}")
            with open(base + ".schema.json", "w") as f:
                payload = info.to_json()
                f.write(payload if isinstance(payload, str)
                        else json.dumps(payload))
            n = 0
            phys_ids = [info.id]
            if info.partition is not None:
                # rows live under partition physical ids; restore re-routes
                # by value so the dump is just (handle, row) pairs
                phys_ids = [d.id for d in info.partition.defs]
            with open(base + ".data.jsonl", "w") as f:
                for pid in phys_ids:
                    rec_end = tablecodec.record_prefix(pid) + b"\xff" * 9
                    for key, value in txn.scan(
                            tablecodec.record_prefix(pid), rec_end):
                        _tid, h = tablecodec.decode_record_key(key)
                        f.write(json.dumps({"h": h, "v": value.hex()}) + "\n")
                        n += 1
            meta["tables"].append({"name": info.name, "rows": n})
    finally:
        txn.rollback()
    with open(os.path.join(dest, "backupmeta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return meta


# -- restore (reference: br/pkg/task/restore.go) -----------------------------

def restore_database(session, src: str, db_name: str | None = None) -> dict:
    with open(os.path.join(src, "backupmeta.json")) as f:
        meta = json.load(f)
    target_db = db_name or meta["db"]
    if session.infoschema().schema_by_name(target_db) is None:
        session.execute(f"create database `{target_db}`")
    restored = []
    for t in meta["tables"]:
        base = os.path.join(src, f"{meta['db']}.{t['name']}")
        with open(base + ".schema.json") as f:
            raw = f.read()
        info = TableInfo.from_json(json.loads(raw)
                                   if raw.lstrip().startswith("{")
                                   else raw)
        if session.infoschema().has_table(target_db, info.name):
            raise TiDBError(f"table '{target_db}.{info.name}' already "
                            f"exists; drop it before RESTORE")
        _create_from_info(session, target_db, info)
        new_info = session.infoschema().table_by_name(target_db, info.name)
        n = _restore_rows(session, new_info, base + ".data.jsonl")
        restored.append({"name": info.name, "rows": n})
    return {"db": target_db, "tables": restored}


def _create_from_info(session, db_name: str, info: TableInfo):
    """Recreate the table from the backed-up TableInfo via the catalog
    (new table id; column/index ids preserved from the source)."""
    from .meta import Meta
    ddl = session.ddl
    with session.domain.ddl_lock:
        txn = session.store.begin()
        try:
            m = Meta(txn)
            db = next(d for d in m.list_databases()
                      if d.name.lower() == db_name.lower())
            clone = TableInfo.from_json(info.to_json())
            clone.id = m.gen_global_id()
            if clone.partition is not None:
                # fresh physical ids: the source table may still exist
                for d in clone.partition.defs:
                    d.id = m.gen_global_id()
            m.create_table(db.id, clone)
            m.bump_schema_version()
            txn.commit()
        except Exception:
            txn.rollback()
            raise
    session.domain.reload_schema()


def _restore_rows(session, info: TableInfo, path: str) -> int:
    n = 0
    batch = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            batch.append((rec["h"], bytes.fromhex(rec["v"])))
            if len(batch) >= BATCH:
                _write_batch(session, info, batch)
                n += len(batch)
                batch = []
    if batch:
        _write_batch(session, info, batch)
        n += len(batch)
    return n


def _write_batch(session, info, batch):
    txn = session.store.begin()
    try:
        tbl = Table(info, txn)
        for handle, value in batch:
            row = tablecodec.decode_row(value)
            tbl.add_record(row, handle, check_dup=False)
        txn.commit()
    except Exception:
        txn.rollback()
        raise
    session.domain.columnar_cache.invalidate(info.id)


# -- logical dump (reference: dumpling/export/dump.go) ------------------------

def dump_database(session, db_name: str, dest: str, fmt: str = "sql") -> dict:
    if fmt not in ("sql", "csv"):
        raise TiDBError("dump format must be 'sql' or 'csv'")
    infos = session.infoschema()
    if infos.schema_by_name(db_name) is None:
        raise TiDBError(f"Unknown database '{db_name}'")
    os.makedirs(dest, exist_ok=True)
    out = {"db": db_name, "tables": []}
    # base tables first, then views in dependency order, so view DDL
    # (which plans its select) can resolve its sources on import; views
    # carry schema only, never INSERT data
    all_infos = _dump_order(infos.tables_in_schema(db_name))
    for info in all_infos:
        base = os.path.join(dest, f"{db_name}.{info.name}")
        create = session.execute(
            f"show create table `{db_name}`.`{info.name}`")[-1].rows[0][1]
        with open(base + "-schema.sql", "w") as f:
            f.write(create + ";\n")
        if info.is_view:
            out["tables"].append({"name": info.name, "rows": 0,
                                  "is_view": True})
            continue
        res = session.execute(
            f"select * from `{db_name}`.`{info.name}`")[-1]
        rows = res.rows  # display strings (None = NULL)
        if fmt == "sql":
            with open(base + ".sql", "w") as f:
                for i in range(0, len(rows), 256):
                    chunk = rows[i:i + 256]
                    vals = ",\n".join(
                        "(" + ", ".join(_sql_lit(v) for v in r) + ")"
                        for r in chunk)
                    f.write(f"INSERT INTO `{info.name}` VALUES\n{vals};\n")
        else:
            import csv
            with open(base + ".csv", "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(res.names)
                for r in rows:
                    # NULL sentinel is \N; a LITERAL leading backslash is
                    # escaped by doubling so the reader can tell them apart
                    # (mydumper-style)
                    w.writerow([
                        "\\N" if v is None
                        else ("\\" + v if isinstance(v, str)
                              and v.startswith("\\") else v)
                        for v in r])
        out["tables"].append({"name": info.name, "rows": len(rows)})
    with open(os.path.join(dest, "metadata.json"), "w") as f:
        json.dump(out, f, indent=1)
    return out


def _dump_order(tables):
    """Base tables (by name), then views topologically sorted so every view
    precedes views defined over it (cycles fall back to name order)."""
    base = sorted((t for t in tables if not t.is_view), key=lambda t: t.name)
    views = sorted((t for t in tables if t.is_view), key=lambda t: t.name)
    by_name = {v.name.lower(): v for v in views}
    deps = {}
    for v in views:
        names = set()
        try:
            from .parser import parse
            from .priv_check import _collect_tables
            tabs = []
            _collect_tables(parse(v.view["select"])[0], tabs)
            names = {tn.name.lower() for tn in tabs if tn.name.lower()
                     in by_name and tn.name.lower() != v.name.lower()}
        except Exception:
            pass
        deps[v.name.lower()] = names
    ordered, done = [], set()

    def visit(name, seen):
        if name in done or name in seen:
            return
        seen.add(name)
        for d in sorted(deps.get(name, ())):
            visit(d, seen)
        done.add(name)
        ordered.append(by_name[name])
    for v in views:
        visit(v.name.lower(), set())
    return base + ordered


_NUMERIC_RE = None


def _sql_lit(v) -> str:
    if v is None:
        return "NULL"
    global _NUMERIC_RE
    if _NUMERIC_RE is None:
        import re
        # canonical numerics only: a float() probe would unquote 'nan',
        # '12_3' (python underscore literals) and strip '0010' — display
        # values of NUMERIC columns always match this shape, so anything
        # else is string data and must be quoted
        _NUMERIC_RE = re.compile(r"-?(0|[1-9]\d*)(\.\d+)?([eE][+-]?\d+)?$")
    s = str(v)
    if _NUMERIC_RE.fullmatch(s):
        return s
    # newlines must be escaped or the ';\n' statement splitter would break
    s = (s.replace("\\", "\\\\").replace("'", "\\'")
         .replace("\n", "\\n").replace("\r", "\\r"))
    return "'" + s + "'"


def _str_lit(s: str) -> str:
    """Always-quoted literal: CSV fields are untyped strings; the INSERT
    cast converts them into numeric/date columns, so quoting everything is
    both safe and type-faithful."""
    s = (s.replace("\\", "\\\\").replace("'", "\\'")
         .replace("\n", "\\n").replace("\r", "\\r"))
    return "'" + s + "'"


# -- import with checkpoint/resume (reference: lightning checkpoints) ---------

def import_dump(session, src: str, db_name: str | None = None,
                crash_after_batches: int | None = None) -> dict:
    """Load a dump directory produced by dump_database (sql format).
    Progress is checkpointed per committed batch; re-running after a crash
    resumes from the checkpoint. `crash_after_batches` is a test hook that
    aborts mid-import (reference: failpoint-style injection)."""
    with open(os.path.join(src, "metadata.json")) as f:
        meta = json.load(f)
    target_db = db_name or meta["db"]
    if session.infoschema().schema_by_name(target_db) is None:
        session.execute(f"create database `{target_db}`")
    ckpt_path = os.path.join(src, "_import_checkpoint.json")
    ckpt = {"done_tables": [], "table": None, "stmts_done": 0}
    if os.path.exists(ckpt_path):
        with open(ckpt_path) as f:
            ckpt = json.load(f)
    session.execute(f"use `{target_db}`")
    batches = 0
    for t in meta["tables"]:
        name = t["name"]
        if name in ckpt["done_tables"]:
            continue
        schema_file = os.path.join(src, f"{meta['db']}.{name}-schema.sql")
        data_file = os.path.join(src, f"{meta['db']}.{name}.sql")
        skip = ckpt["stmts_done"] if ckpt.get("table") == name else 0
        if skip == 0 and not session.infoschema().has_table(target_db, name):
            with open(schema_file) as f:
                session.execute(f.read())
        if t.get("is_view"):
            ckpt["done_tables"].append(name)
            _write_ckpt(ckpt_path, ckpt)
            continue
        csv_file = os.path.join(src, f"{meta['db']}.{name}.csv")
        if not os.path.exists(data_file) and os.path.exists(csv_file):
            stmts = _csv_to_inserts(csv_file, name)
        else:
            with open(data_file) as f:
                stmts = _split_sql(f.read())
        done = 0
        for stmt in stmts:
            done += 1
            if done <= skip:
                continue
            session.execute(stmt)
            batches += 1
            ckpt.update({"table": name, "stmts_done": done})
            _write_ckpt(ckpt_path, ckpt)
            if (crash_after_batches is not None
                    and batches >= crash_after_batches):
                raise TiDBError("import aborted (injected crash)")
        ckpt["done_tables"].append(name)
        ckpt.update({"table": None, "stmts_done": 0})
        _write_ckpt(ckpt_path, ckpt)
    os.unlink(ckpt_path)
    return {"db": target_db,
            "tables": [t["name"] for t in meta["tables"]]}


def _csv_to_inserts(path: str, table: str, batch: int = 256):
    """CSV dump (header row; \\N = NULL) → INSERT statement batches — the
    csv-format twin of the sql loader (reference: lightning/mydump csv
    parser)."""
    import csv
    with open(path, newline="") as f:
        rdr = csv.reader(f)
        try:
            next(rdr)  # header
        except StopIteration:
            return
        def lit(v: str) -> str:
            if v == "\\N":
                return "NULL"
            if v.startswith("\\\\"):
                v = v[1:]  # un-escape the doubled leading backslash
            return _str_lit(v)

        rows = []
        for r in rdr:
            rows.append("(" + ", ".join(lit(v) for v in r) + ")")
            if len(rows) >= batch:
                yield f"INSERT INTO `{table}` VALUES " + ",".join(rows)
                rows = []
        if rows:
            yield f"INSERT INTO `{table}` VALUES " + ",".join(rows)


def _write_ckpt(path: str, ckpt: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ckpt, f)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint


def _split_sql(text: str):
    """Split dump files on ';\n' statement boundaries (values never contain
    that sequence: _sql_lit escapes newlines are impossible in display
    strings, and the writer ends every statement with ';\\n')."""
    for part in text.split(";\n"):
        if part.strip():
            yield part
