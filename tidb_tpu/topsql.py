"""TopSQL: per-SQL CPU-time attribution by sampling live sessions
(reference: util/topsql/topsql.go:54 + collector/cpu.go — pprof-label
sampling of running statements, aggregated per SQL digest and exported;
here the sampler walks the domain's live sessions and charges each
running statement one tick, which converges on wall-CPU attribution the
same way the reference's 1s pprof profiles do).

Gated by the GLOBAL `tidb_enable_top_sql` (reference sysvar, default
OFF). Queryable via `information_schema.tidb_top_sql`; the collector
keeps only the top entries by accumulated time (the reference reports
top-N per window for the same reason: unbounded digests are a leak)."""

from __future__ import annotations

import threading
import time

from .parser import digest as sql_digest

#: keep this many digests; evict the coldest beyond it
TOP_CAP = 200


class TopSQLEntry:
    __slots__ = ("digest", "sample_sql", "cpu_ms", "samples", "last_seen")

    def __init__(self, digest, sample_sql):
        self.digest = digest
        self.sample_sql = sample_sql
        self.cpu_ms = 0.0
        self.samples = 0
        self.last_seen = 0.0


class TopSQL:
    """Sampling collector over domain.sessions (start() for the server
    loop; tests drive sample_once())."""

    def __init__(self, domain, interval_s: float = 0.02):
        self.domain = domain
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self.entries: dict[str, TopSQLEntry] = {}
        self._thread = None
        self._stop = threading.Event()

    def enabled(self) -> bool:
        return str(self.domain.global_vars.get(
            "tidb_enable_top_sql", "OFF")).upper() in ("ON", "1")

    def sample_once(self, now: float | None = None,
                    tick_ms: float | None = None):
        """One sampling sweep: every session currently inside a statement
        is charged one tick for its digest."""
        if not self.enabled():
            return
        now = time.time() if now is None else now
        tick = self.interval_s * 1000.0 if tick_ms is None else tick_ms
        for sess in list(self.domain.sessions.values()):
            sql = sess.current_sql
            if not sql:
                continue
            dig = sql_digest(sql)
            with self._lock:
                e = self.entries.get(dig)
                if e is None:
                    e = self.entries[dig] = TopSQLEntry(dig, sql[:256])
                e.cpu_ms += tick
                e.samples += 1
                e.last_seen = now
                if len(self.entries) > TOP_CAP:
                    # evict the coldest OTHER entry — the just-charged one
                    # is the current heavy hitter, not the eviction victim
                    cold = min((x for x in self.entries.values()
                                if x is not e), key=lambda x: x.cpu_ms)
                    self.entries.pop(cold.digest, None)

    def top(self, n: int = TOP_CAP):
        with self._lock:
            return sorted(self.entries.values(),
                          key=lambda e: -e.cpu_ms)[:n]

    def reset(self):
        with self._lock:
            self.entries.clear()

    # -- server-loop lifecycle ----------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception as e:
                    # sampling must never hurt the server, but a sampler
                    # that dies every tick must be diagnosable
                    import logging
                    from .utils.backoff import classify
                    logging.getLogger("tidb_tpu.topsql").warning(
                        "top-sql sample failed (%s): %s", classify(e), e)

        self._thread = threading.Thread(target=loop, name="topsql",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
