"""SHOW statement execution (reference: executor/show.go)."""

from __future__ import annotations

from ..errors import TiDBError, ErrCode, SchemaError
from ..expression import like_to_regex
from ..model import SchemaState
from ..parser import ast
from ..sqltypes import TYPE_LONGLONG, TYPE_VARCHAR, FieldType
from ..utils.chunk import Chunk
from . import sysvars as sv

_S = FieldType(tp=TYPE_VARCHAR)
_I = FieldType(tp=TYPE_LONGLONG)


def _match(like_pat, s: str) -> bool:
    if like_pat is None:
        return True
    return like_to_regex(like_pat).match(s.encode()) is not None


def exec_show(session, stmt: ast.ShowStmt):
    from .session import Result
    like = None
    if stmt.like is not None:
        from ..expression import ExprBuilder, Schema
        v = ExprBuilder(Schema([]), session.expr_ctx()).build(stmt.like).eval_scalar()
        like = v if isinstance(v, bytes) else str(v).encode()

    if stmt.kind == "databases":
        names = session.infoschema().schema_names()
        names = [n for n in names if _match(like, n)]
        names.append("information_schema") if "information_schema" not in names else None
        names.sort()
        rows = [(n.encode(),) for n in names if _match(like, n)]
        return Result(names=["Database"], chunk=Chunk.from_rows([_S], rows))

    if stmt.kind == "tables":
        db = stmt.db or session.current_db()
        infos = session.infoschema()
        if infos.schema_by_name(db) is None:
            raise SchemaError(f"Unknown database '{db}'", code=ErrCode.BadDB)
        tbls = sorted(infos.tables_in_schema(db), key=lambda t: t.name)
        if stmt.full:
            rows = [(t.name.encode(),
                     b"VIEW" if t.is_view else b"BASE TABLE")
                    for t in tbls if _match(like, t.name)]
            return Result(names=[f"Tables_in_{db}", "Table_type"],
                          chunk=Chunk.from_rows([_S, _S], rows))
        rows = [(t.name.encode(),) for t in tbls if _match(like, t.name)]
        return Result(names=[f"Tables_in_{db}"], chunk=Chunk.from_rows([_S], rows))

    if stmt.kind == "columns":
        tn = stmt.target
        db = tn.schema or stmt.db or session.current_db()
        info = session.infoschema().table_by_name(db, tn.name)
        rows = []
        for c in info.public_columns():
            null = b"NO" if c.ftype.not_null else b"YES"
            key = b""
            if session  and info.pk_is_handle and c.id == info.pk_col_id:
                key = b"PRI"
            else:
                for idx in info.indexes:
                    if idx.columns and idx.columns[0].name.lower() == c.name.lower():
                        key = b"PRI" if idx.primary else (b"UNI" if idx.unique else b"MUL")
                        break
            from ..sqltypes import format_value
            default = (format_value(c.default_value, c.ftype) or "").encode() \
                if c.has_default and c.default_value is not None else None
            rows.append((c.name.encode(), c.ftype.sql_string().encode(),
                         null, key, default, b""))
        return Result(names=["Field", "Type", "Null", "Key", "Default", "Extra"],
                      chunk=Chunk.from_rows([_S] * 6, rows))

    if stmt.kind == "index":
        tn = stmt.target
        db = tn.schema or session.current_db()
        info = session.infoschema().table_by_name(db, tn.name)
        rows = []
        if info.pk_is_handle:
            pk = info.find_column_by_id(info.pk_col_id) if hasattr(info, 'find_column_by_id') else None
            pkname = next((c.name for c in info.columns if c.id == info.pk_col_id), "")
            rows.append((info.name.encode(), 0, b"PRIMARY", 1, pkname.encode()))
        for idx in info.indexes:
            for seq, ic in enumerate(idx.columns, 1):
                rows.append((info.name.encode(), 0 if idx.unique else 1,
                             idx.name.encode(), seq, ic.name.encode()))
        return Result(names=["Table", "Non_unique", "Key_name", "Seq_in_index",
                             "Column_name"],
                      chunk=Chunk.from_rows([_S, _I, _S, _I, _S], rows))

    if stmt.kind == "create_table":
        tn = stmt.target
        db = tn.schema or session.current_db()
        info = session.infoschema().table_by_name(db, tn.name)
        if info.is_sequence:
            s = info.sequence
            ddl = (f"CREATE SEQUENCE `{info.name}` START WITH {s['start']} "
                   f"INCREMENT BY {s['increment']} MINVALUE {s['min']} "
                   f"MAXVALUE {s['max']} "
                   + (f"CACHE {s['cache']}" if s.get("cache") else "NOCACHE")
                   + (" CYCLE" if s.get("cycle") else " NOCYCLE"))
            return Result(names=["Sequence", "Create Sequence"],
                          chunk=Chunk.from_rows(
                              [_S, _S], [(info.name.encode(), ddl.encode())]))
        if info.is_view:
            cols = ", ".join(f"`{c}`" for c in info.view["cols"])
            ddl = (f"CREATE VIEW `{info.name}` ({cols}) AS "
                   + info.view["select"])
            return Result(names=["View", "Create View"],
                          chunk=Chunk.from_rows(
                              [_S, _S], [(info.name.encode(), ddl.encode())]))
        ddl = render_create_table(info)
        return Result(names=["Table", "Create Table"],
                      chunk=Chunk.from_rows([_S, _S],
                                            [(info.name.encode(), ddl.encode())]))

    if stmt.kind == "variables":
        rows = []
        reg = sv.get_registry()
        for name in sorted(reg):
            if not _match(like, name):
                continue
            scope = "global" if stmt.global_scope else "session"
            try:
                v = session.get_sysvar(name, scope)
            except TiDBError:
                v = reg[name].default
            rows.append((name.encode(), str(v).encode()))
        return Result(names=["Variable_name", "Value"],
                      chunk=Chunk.from_rows([_S, _S], rows))

    if stmt.kind == "status":
        return Result(names=["Variable_name", "Value"],
                      chunk=Chunk.from_rows([_S, _S], []))

    if stmt.kind == "warnings":
        rows = [(b"Warning", 1105, w.encode()) for w in session.warnings]
        return Result(names=["Level", "Code", "Message"],
                      chunk=Chunk.from_rows([_S, _I, _S], rows))

    if stmt.kind == "errors":
        return Result(names=["Level", "Code", "Message"],
                      chunk=Chunk.from_rows([_S, _I, _S], []))

    if stmt.kind == "engines":
        rows = [(b"tpu-htap", b"DEFAULT",
                 b"TPU-native HTAP storage engine", b"YES", b"YES", b"YES")]
        return Result(names=["Engine", "Support", "Comment", "Transactions",
                             "XA", "Savepoints"],
                      chunk=Chunk.from_rows([_S] * 6, rows))

    if stmt.kind == "charset":
        from ..utils.collate import CHARSETS
        return Result(names=["Charset", "Description", "Default collation",
                             "Maxlen"],
                      chunk=Chunk.from_rows([_S, _S, _S, _I],
                                            list(CHARSETS)))

    if stmt.kind == "collation":
        from ..utils.collate import COLLATIONS
        return Result(names=["Collation", "Charset", "Id", "Default",
                             "Compiled", "Sortlen"],
                      chunk=Chunk.from_rows([_S, _S, _I, _S, _S, _I],
                                            list(COLLATIONS)))

    if stmt.kind == "processlist":
        # same row source as information_schema.processlist
        from .memtables import processlist_rows
        rows = processlist_rows(
            session, max_info=0 if getattr(stmt, "full", False) else 100)
        return Result(names=["Id", "User", "Host", "db", "Command", "Time",
                             "State", "Info"],
                      chunk=Chunk.from_rows([_I, _S, _S, _S, _S, _I, _S, _S],
                                            rows))

    if stmt.kind == "grants":
        cur_user, _, cur_host = session.user.partition("@")
        if stmt.target is not None:
            user, host = stmt.target
            if (user, host) != (cur_user, cur_host or "%"):
                # another account's grants: requires read access to the
                # grant tables (reference: ShowGrants SELECT on mysql.*)
                session.domain.priv.verify(session.user, "mysql", "user",
                                           "select")
        else:
            user, host = cur_user, cur_host or "%"
        lines = session.domain.priv.grants_for(user, host)
        if not lines:
            lines = [f"GRANT USAGE ON *.* TO '{user}'@'{host}'"]
        rows = [(ln.encode(),) for ln in lines]
        return Result(names=[f"Grants for {user}@{host}"],
                      chunk=Chunk.from_rows([_S], rows))

    if stmt.kind == "bindings":
        recs = (session.domain.bind_handle.list() if stmt.global_scope
                else session.session_bindings)
        rows = []
        for norm in sorted(recs):
            r = recs[norm]
            rows.append((r["original"].encode(), r["bind"].encode(),
                         r.get("db", "").encode(),
                         r.get("status", "enabled").encode(),
                         r.get("created", "").encode()))
        return Result(names=["Original_sql", "Bind_sql", "Default_db",
                             "Status", "Create_time"],
                      chunk=Chunk.from_rows([_S] * 5, rows))

    if stmt.kind == "plugins":
        rows = [(p.name.encode(), b"ACTIVE", p.kind.encode(),
                 str(p.version).encode(), b"")
                for p in session.domain.plugins.list()]
        return Result(names=["Name", "Status", "Type", "Library", "License"],
                      chunk=Chunk.from_rows([_S] * 5, rows))

    if stmt.kind == "table_status":
        db = stmt.db or session.current_db()
        infos = session.infoschema()
        rows = []
        for t in infos.tables_in_schema(db):
            rows.append((t.name.encode(), b"tpu-htap", 10, b"Fixed"))
        return Result(names=["Name", "Engine", "Version", "Row_format"],
                      chunk=Chunk.from_rows([_S, _S, _I, _S], rows))

    if stmt.kind == "create_database":
        name = stmt.db
        return Result(names=["Database", "Create Database"],
                      chunk=Chunk.from_rows([_S, _S],
                                            [(name.encode(),
                                              f"CREATE DATABASE `{name}`".encode())]))

    raise TiDBError(f"unsupported SHOW {stmt.kind}")


def render_create_table(info) -> str:
    """reference: executor/show.go ConstructResultOfShowCreateTable."""
    lines = []
    for c in info.public_columns():
        l = f"  `{c.name}` {c.ftype.sql_string()}"
        if c.ftype.not_null:
            l += " NOT NULL"
        if c.has_default and c.default_value is not None:
            from ..sqltypes import format_value, STRING_TYPES
            v = format_value(c.default_value, c.ftype)
            if c.ftype.tp in STRING_TYPES or not str(v).lstrip("-").isdigit():
                l += f" DEFAULT '{v}'"
            else:
                l += f" DEFAULT {v}"
        if (info.auto_random_bits and info.pk_is_handle
                and c.id == info.pk_col_id):
            l += f" /*T![auto_rand] AUTO_RANDOM({info.auto_random_bits}) */"
        lines.append(l)
    if info.pk_is_handle:
        pkname = next((c.name for c in info.columns if c.id == info.pk_col_id), None)
        if pkname:
            lines.append(f"  PRIMARY KEY (`{pkname}`)")
    for idx in info.indexes:
        cols = ", ".join(f"`{ic.name}`" for ic in idx.columns)
        if idx.primary:
            lines.append(f"  PRIMARY KEY ({cols})")
        elif idx.unique:
            lines.append(f"  UNIQUE KEY `{idx.name}` ({cols})")
        else:
            lines.append(f"  KEY `{idx.name}` ({cols})")
    for fk in info.foreign_keys:
        cols = ", ".join(f"`{c}`" for c in fk["cols"])
        rcols = ", ".join(f"`{c}`" for c in fk["ref_cols"])
        l = (f"  CONSTRAINT `{fk['name']}` FOREIGN KEY ({cols}) "
             f"REFERENCES `{fk['ref_table']}` ({rcols})")
        if fk.get("on_delete"):
            l += f" ON DELETE {fk['on_delete'].upper()}"
        if fk.get("on_update"):
            l += f" ON UPDATE {fk['on_update'].upper()}"
        lines.append(l)
    body = ",\n".join(lines)
    s = (f"CREATE TABLE `{info.name}` (\n{body}\n) "
         "ENGINE=tpu-htap DEFAULT CHARSET=utf8mb4")
    if info.partition is not None:
        s += "\n" + render_partition_clause(info)
    return s


def render_partition_clause(info) -> str:
    """reference: show.go ConstructResultOfShowCreateTable partition tail."""
    from ..partition import MAXVALUE
    p = info.partition
    if p.type == "hash":
        return f"PARTITION BY HASH ({p.expr}) PARTITIONS {p.num}"
    col = info.find_column(p.col_name)

    def _fmt(v):
        if v == MAXVALUE:
            return "MAXVALUE"
        if v is None:
            return "NULL"
        if p.func:
            return str(v)
        from ..sqltypes import format_value, STRING_TYPES
        txt = format_value(v, col.ftype)
        if isinstance(txt, bytes):
            txt = txt.decode("utf-8", "replace")
        if col.ftype.tp in STRING_TYPES or not str(txt).lstrip("-").isdigit():
            return f"'{txt}'"
        return str(txt)

    parts = []
    for d in p.defs:
        if p.type == "range":
            b = ("MAXVALUE" if d.less_than == MAXVALUE
                 else f"({_fmt(d.less_than)})")
            parts.append(f" PARTITION `{d.name}` VALUES LESS THAN {b}")
        else:
            vs = ", ".join(_fmt(v) for v in d.in_values)
            parts.append(f" PARTITION `{d.name}` VALUES IN ({vs})")
    return (f"PARTITION BY {p.type.upper()} ({p.expr})\n(" +
            ",\n".join(parts) + ")")
