"""Device (JAX) kernel parity vs host kernels — runs on the 8-device virtual
CPU platform in tests, same code path as TPU."""

import numpy as np
import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    t = TestKit()
    t.must_exec("set @@tidb_executor_engine = 'tpu'")
    return t


@pytest.fixture()
def tk_host():
    t = TestKit()
    t.must_exec("set @@tidb_executor_engine = 'host'")
    return t


def _setup(tk):
    tk.must_exec("create table t (g varchar(5), h int, v int, d decimal(8,2), "
                 "f double, dt date)")
    rows = []
    rng = np.random.RandomState(42)
    for i in range(500):
        g = ["aa", "bb", "cc"][i % 3]
        h = i % 7
        v = int(rng.randint(-100, 100))
        d = int(rng.randint(-10000, 10000))
        f = float(rng.randn())
        day = 9000 + i % 50
        rows.append(f"('{g}', {h}, {v}, {d/100:.2f}, {f!r}, "
                    f"'{(np.datetime64('1970-01-01') + day).astype(str)}')")
    # some NULLs
    rows.append("(null, null, null, null, null, null)")
    rows.append("('aa', 1, null, null, null, null)")
    tk.must_exec("insert into t values " + ",".join(rows))


QUERIES = [
    "select g, count(*), sum(v), min(v), max(v) from t group by g order by g",
    "select g, h, sum(d), avg(d), count(v) from t group by g, h order by g, h",
    "select count(*), sum(v), avg(v), min(d), max(d) from t",
    "select g, sum(f), avg(f) from t group by g order by g",
    "select g, count(*) from t where v > 0 and d < 50 group by g order by g",
    "select h, sum(v) from t where g = 'aa' group by h order by h",
    "select h, count(*) from t where g in ('aa', 'cc') group by h order by h",
    "select g, min(dt), max(dt) from t group by g order by g",
    "select g, sum(d * 2 + 1), sum(v + h) from t group by g order by g",
    "select g, count(*) from t where dt >= '1994-09-01' group by g order by g",
    "select g, sum(case when v > 0 then v else 0 end) from t group by g order by g",
    "select year(dt), count(*) from t where dt is not null group by year(dt) order by 1",
    "select g, min(g), max(g) from t group by g order by g",
]


def _rows_equal(a, b):
    """Exact match except float cells compare with 1e-9 relative tolerance
    (device sums in sorted order; IEEE addition is order-sensitive —
    decimals stay bit-exact, doubles are approximate by SQL semantics)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if va == vb:
                continue
            try:
                fa, fb = float(va), float(vb)
            except (TypeError, ValueError):
                return False
            if not np.isclose(fa, fb, rtol=1e-9, atol=1e-12):
                return False
    return True


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_agg_parity(tk, tk_host, qi):
    _setup(tk)
    _setup(tk_host)
    q = QUERIES[qi]
    dev_rows = tk.must_query(q).rows
    host_rows = tk_host.must_query(q).rows
    assert _rows_equal(dev_rows, host_rows), \
        f"device != host for: {q}\n{dev_rows}\n{host_rows}"


def test_join_parity(tk, tk_host):
    for k in (tk, tk_host):
        k.must_exec("create table a (x int, s varchar(5))")
        k.must_exec("create table b (x int, t varchar(5))")
        rows_a = ",".join(f"({i % 37}, 'a{i % 11}')" for i in range(300))
        rows_b = ",".join(f"({i % 23}, 'b{i % 7}')" for i in range(200))
        k.must_exec(f"insert into a values {rows_a}, (null, 'an')")
        k.must_exec(f"insert into b values {rows_b}, (null, 'bn')")
    q = ("select a.x, count(*) from a join b on a.x = b.x "
         "group by a.x order by a.x")
    assert tk.must_query(q).rows == tk_host.must_query(q).rows
    q2 = ("select a.s, b.t from a join b on a.x = b.x and a.s = concat('a', b.x) "
          "order by a.s, b.t limit 20")
    assert tk.must_query(q2).rows == tk_host.must_query(q2).rows
    q3 = "select count(*) from a left join b on a.x = b.x"
    assert tk.must_query(q3).rows == tk_host.must_query(q3).rows


def test_group_capacity_overflow_retry(tk, tk_host):
    """More groups than the initial capacity estimate: retry must produce
    complete results (the estimate is 64/key; use >4096 groups for 1 key)."""
    for k in (tk, tk_host):
        k.must_exec("create table big (k int, v int)")
        rows = ",".join(f"({i}, {i % 10})" for i in range(5000))
        k.must_exec(f"insert into big values {rows}")
    q = "select count(*) from (select k, sum(v) s from big group by k) z"
    assert tk.must_query(q).rows == [("5000",)]
    q2 = "select sum(s) from (select k, sum(v) s from big group by k) z"
    assert tk.must_query(q2).rows == tk_host.must_query(q2).rows


def test_decimal_exactness_on_device(tk):
    tk.must_exec("create table p (d decimal(12,2))")
    rows = ",".join(f"({v}.{c:02d})" for v, c in
                    [(10**9, 1), (10**9, 2), (-10**9, 3), (7, 99)])
    tk.must_exec(f"insert into p values {rows}")
    tk.must_query("select sum(d), avg(d) from p").check([
        ("1000000007.99", "250000001.997500")])


class TestTopNPushdown:
    """TopN over a grouped aggregate fetches only candidate groups from
    the device (planner/optimizer.py push_topn_into_agg + AggFetch topn)."""

    @pytest.fixture()
    def ttk(self):
        t = TestKit()
        t.must_exec("create table g (k bigint, d date, v int, s varchar(8))")
        rows = []
        for i in range(4000):
            rows.append(f"({i % 1900}, '19{90 + i % 9}-01-0{i % 9 + 1}', "
                        f"{(i * 37) % 1000}, 'x{i % 5}')")
        t.must_exec("insert into g values " + ",".join(rows))
        return t

    def _parity(self, t, q):
        t.must_exec("set tidb_executor_engine = 'tpu'")
        dev = t.must_query(q).rows
        t.must_exec("set tidb_executor_engine = 'host'")
        host = t.must_query(q).rows
        t.must_exec("set tidb_executor_engine = 'auto'")
        assert dev == host, (dev[:5], host[:5])

    def test_annotation_set(self, ttk):
        from tidb_tpu.parser import parse
        plan = ttk.session.plan_query(parse(
            "select k, sum(v) sv from g group by k order by sv desc, k "
            "limit 10")[0])
        # Sort+Limit becomes TopN; the agg under it must carry the bound
        def find_agg(p):
            from tidb_tpu.planner.logical import Aggregation
            if isinstance(p, Aggregation):
                return p
            for c in p.children:
                a = find_agg(c)
                if a is not None:
                    return a
        agg = find_agg(plan)
        assert agg is not None and agg.topn_fetch is not None
        assert agg.topn_fetch[1] >= 10

    def test_sum_desc_key_asc(self, ttk):
        self._parity(ttk, "select k, sum(v) sv from g group by k "
                          "order by sv desc, k limit 10")

    def test_key_only_order(self, ttk):
        self._parity(ttk, "select k, count(*) from g group by k "
                          "order by k desc limit 7")

    def test_date_key_order(self, ttk):
        self._parity(ttk, "select d, k, sum(v) from g group by d, k "
                          "order by d, k limit 25")

    def test_offset(self, ttk):
        self._parity(ttk, "select k, sum(v) sv from g group by k "
                          "order by sv desc, k limit 5, 10")

    def test_min_max_order(self, ttk):
        self._parity(ttk, "select k, min(v) mv, max(v) xv from g group by k "
                          "order by mv, xv desc, k limit 12")

    def test_avg_not_pushed_but_correct(self, ttk):
        self._parity(ttk, "select k, avg(v) av from g group by k "
                          "order by av desc, k limit 10")
