"""Device-runtime supervisor: hang detection, backend fencing, and
abandoned-call accounting for every device entry point.

Why this exists (ROADMAP "Open items", BENCH_TPU_LIVE.json): the round-5
live-TPU run died mid-bench when the PJRT tunnel hung before Q5.  Nothing
in-process could interrupt it — the axon client blocks inside a C call
HOLDING THE GIL, so SIGALRM never fires and `KILL` is never polled; one
stuck backend cost the whole run.  PR 1 made device *failures* survivable
(classified errors → circuit breaker → host fallback); this module makes
device *hangs* survivable too.

Model (deadline → classify → fence → breaker → degrade):

1. **Supervised dispatch** — `supervised_call` runs the device call on a
   reusable daemon WORKER thread while the calling thread waits on an
   event with a hard wall-clock deadline, polling the session's
   ``check_killed`` every ~20ms.  A GIL-blocked backend call can no
   longer freeze the session: the *waiter* holds no C frames, so
   `KILL` / `max_execution_time` / the deadline all stay live.
2. **Classify** — deadline expiry raises :class:`DeviceHangError`
   (errno 9008, taxonomy class ``hang`` in ``utils/backoff.classify``)
   into the query.  ``executor/device_exec.run_device`` records it
   against the per-(Domain, fragment shape) circuit breaker, so repeated
   hangs trip degradation to the host engine exactly like repeated
   classified failures.
3. **Fence** — the abandoned call keeps its worker thread (Python cannot
   kill a thread blocked in C); the supervisor marks the backend
   QUARANTINED.  Before the next device fragment dispatches,
   `_maybe_reinit` drops every compiled-executable cache that pins the
   suspect backend (the fused-pipeline cache, the topk kernel cache, the
   MPP placement cache, jax's own jit caches) and — on a non-CPU
   backend, where the arrays behind those caches are dead anyway —
   attempts a full PJRT client teardown so the next dispatch re-dials.
4. **Account** — "abandoned calls outstanding" is an explicit gauge:
   surfaced in EXPLAIN ANALYZE (``device_abandoned_calls``),
   ``session/observe.py`` gauges (``device_abandoned_calls``) and the
   HTTP status API (``/status`` + ``/metrics``).  A worker whose
   abandoned call eventually unblocks decrements the gauge and rejoins
   the pool.

Deadline sources (`effective_deadline`): the ``tidb_device_call_timeout``
sysvar (seconds, 0 = unsupervised inline dispatch — the default, so the
hot path pays nothing) and the remaining ``max_execution_time`` window of
the current statement; the tighter one wins.

Thread-local bridging: the compiled-fragment stats
(``device_exec._PIPE_TLS``) and paged-stage stats
(``device_join.LAST_PAGED_STATS``) are thread-local so concurrent
sessions don't cross-charge compiles.  A supervised call runs `fn` on a
worker thread, so the worker captures its own deltas and the waiter
merges them back into the calling thread — EXPLAIN ANALYZE and bench
compile attribution survive supervision.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
import weakref

from ..errors import DeviceHangError

log = logging.getLogger("tidb_tpu.supervisor")

#: waiter poll period — bounds KILL / deadline detection latency
_POLL_S = 0.02

_LOCK = threading.Lock()
_REINIT_LOCK = threading.Lock()
_IDLE: list["_Worker"] = []
_WORKER_SEQ = itertools.count()

#: abandoned calls still blocked on their worker threads (the gauge)
_ABANDONED = [0]
#: backend suspect: fence before the next supervised/inline dispatch.
#: The generation counter bumps on every NEW quarantine so a reinit in
#: flight never clears a fence requested concurrently (by a second hang
#: against the freshly re-dialed client) — that fence gets its own reinit
_QUARANTINED = [False]
_QUAR_GEN = [0]

STATS = {
    "supervised": 0,   # calls dispatched through a worker thread
    "coproc": 0,       # hybrid-join host passes run via submit_coproc
    "hangs": 0,        # deadline expiries (DeviceHangError raised)
    "kills": 0,        # waits abandoned by KILL/external interrupt
    "abandoned": 0,    # total calls ever abandoned (hangs + kills)
    "reclaimed": 0,    # abandoned calls that eventually completed
    "fences": 0,       # backend quarantine → reinit cycles performed
    "workers": 0,      # worker threads ever spawned
}

#: Observability sinks (session/observe.py) that mirror the gauge —
#: auto-registered from the contexts supervised calls run under
_SINKS: "weakref.WeakSet" = weakref.WeakSet()


class _Job:
    __slots__ = ("fn", "args", "kw", "done", "result", "exc", "orphaned",
                 "tls", "label", "group", "trace")

    def __init__(self, fn, args, kw, label):
        self.fn = fn
        self.args = args
        self.kw = kw
        self.done = threading.Event()
        self.result = None
        self.exc = None
        self.orphaned = False  # waiter gave up: discard result, re-pool
        self.tls = None        # worker-thread stats bridged to the waiter
        self.label = label
        # the dispatching session's resource group: bridged onto the
        # worker thread so residency charges supervised uploads to the
        # right tenant (ops/residency per-group shares), not "default"
        self.group = "default"
        # the dispatching thread's (trace, span) — adopted by the worker
        # so spans/events recorded inside the supervised call still nest
        # under the statement's supervisor.call span (session/tracing.py)
        self.trace = None


class _Worker(threading.Thread):
    """One reusable supervised-dispatch thread.  A worker abandoned
    mid-hang stays blocked until the backend call returns (or never);
    when it does return it decrements the abandoned gauge and rejoins
    the idle pool — worker threads are lost only to PERMANENT hangs."""

    def __init__(self):
        super().__init__(daemon=True,
                         name=f"device-supervisor-{next(_WORKER_SEQ)}")
        self.inbox: "queue.SimpleQueue[_Job]" = queue.SimpleQueue()
        with _LOCK:
            STATS["workers"] += 1
        self.start()

    def run(self):
        while True:
            job = self.inbox.get()
            # the supervisor's own bookkeeping must never prevent
            # job.done from flipping — a stats-capture failure here would
            # otherwise strand the waiter into a FALSE hang (fence, gauge
            # stuck >0) for a perfectly healthy call
            try:
                st0 = _tls_begin()
            except Exception:
                st0 = None
            try:
                from ..ops import residency
                residency.set_group(job.group)
            except Exception:
                pass
            try:
                if job.trace is not None:
                    from ..session import tracing
                    with tracing.adopt(*job.trace):
                        job.result = job.fn(*job.args, **job.kw)
                else:
                    job.result = job.fn(*job.args, **job.kw)
            except BaseException as e:  # noqa: BLE001 — re-raised in waiter
                job.exc = e
            if st0 is not None:
                try:
                    job.tls = _tls_end(st0)
                except Exception:
                    pass
            # done must flip inside the SAME lock hold that reads the
            # orphaned flag: _abandon checks done.is_set() under _LOCK, so
            # a completion racing the deadline is seen by exactly one side
            # — otherwise a call finishing at the deadline double-accounts
            # (gauge leaks, healthy backend fenced)
            with _LOCK:
                orphaned = job.orphaned
                if orphaned:
                    _ABANDONED[0] -= 1
                    STATS["reclaimed"] += 1
                job.done.set()
            if orphaned:
                _publish()
                log.info("abandoned device call %s completed after the "
                         "deadline (result discarded)", job.label)
            with _LOCK:
                _IDLE.append(self)


def _get_worker() -> _Worker:
    with _LOCK:
        if _IDLE:
            return _IDLE.pop()
    return _Worker()


# -- thread-local stats bridging --------------------------------------------

def _tls_begin():
    from .device_exec import pipe_cache_stats
    from .device_join import LAST_PAGED_STATS
    LAST_PAGED_STATS.clear()  # this worker's stale stats from a prior job
    return pipe_cache_stats(thread_local=True)


def _tls_end(st0):
    from .device_exec import pipe_cache_stats
    from .device_join import LAST_PAGED_STATS
    st1 = pipe_cache_stats(thread_local=True)
    return ({k: st1[k] - st0[k] for k in st1},
            dict(LAST_PAGED_STATS.items()))


def _tls_apply(tls):
    """Merge the worker's per-call stats deltas into the CALLING thread's
    thread-locals (process-wide totals were already bumped by the worker —
    only the attribution view moves)."""
    if tls is None:
        return
    delta, paged = tls
    from .device_exec import _tls_stats
    st = _tls_stats()
    for k, v in delta.items():
        st[k] += v
    if paged:
        from .device_join import LAST_PAGED_STATS
        LAST_PAGED_STATS.clear()
        LAST_PAGED_STATS.update(paged)


# -- gauge / observability ---------------------------------------------------

def abandoned_calls() -> int:
    """Device calls abandoned by the supervisor and still blocked on
    their worker threads (the "abandoned calls outstanding" gauge)."""
    with _LOCK:
        return _ABANDONED[0]


def snapshot() -> dict:
    with _LOCK:
        return {"abandoned_outstanding": _ABANDONED[0],
                "quarantined": _QUARANTINED[0], **STATS}


def _publish():
    n = abandoned_calls()
    with _LOCK:
        # materialize under the registration lock: a WeakSet being added
        # to concurrently raises mid-iteration (GC-driven discards are
        # already deferred by WeakSet's own iteration guard)
        sinks = list(_SINKS)
    for obs in sinks:
        try:
            obs.set_gauge("device_abandoned_calls", n)
        except Exception:
            pass


def _register_sink(ctx):
    dom = getattr(ctx, "domain", None)
    obs = getattr(dom, "observe", None)
    if obs is not None and hasattr(obs, "set_gauge"):
        with _LOCK:
            _SINKS.add(obs)


# -- backend fencing ---------------------------------------------------------

def _quarantine_locked():
    """Mark the backend suspect (caller holds _LOCK) — the ONE mutation
    both fence() and the hang-abandon path share.  Bumping the DEVICE
    EPOCH here (ops/residency.py) invalidates every cached HBM upload
    (`Column._device`, join-leaf dcols) at the same instant the backend
    becomes suspect: a restarted PJRT client can never serve a stale
    pre-fence buffer (ROADMAP "device-epoch on Column caches" — DONE).
    Lock order is supervisor._LOCK → residency._LOCK; residency never
    calls back into the supervisor."""
    _QUARANTINED[0] = True
    _QUAR_GEN[0] += 1
    try:
        from ..ops import residency
        residency.bump_epoch("backend quarantined")
    except Exception:
        log.warning("device-epoch bump failed during quarantine",
                    exc_info=True)


def fence(reason: str = ""):
    """Mark the JAX backend suspect: the next device dispatch (supervised
    or inline — run_device checks too) reinitializes before running."""
    with _LOCK:
        _quarantine_locked()
    if reason:
        log.warning("device backend fenced: %s", reason)


def quarantined() -> bool:
    return _QUARANTINED[0]


def fence_generation() -> int:
    """Monotonic count of backend quarantines (bumps on every NEW fence
    request).  The compile service stamps each background job with the
    generation at submit time: a job whose build straddled an off-CPU
    fence produced an executable pinning the DEAD client — comparing
    generations at landing time makes that discard exact."""
    with _LOCK:
        return _QUAR_GEN[0]


def _maybe_reinit():
    """If the backend is quarantined, drop every cache pinning compiled
    executables / placements of the suspect client and reinitialize.
    Never raises — a failed fence must not take down the query that
    merely came next."""
    if not _QUARANTINED[0]:
        return
    with _REINIT_LOCK:
        with _LOCK:
            if not _QUARANTINED[0]:
                return
            gen = _QUAR_GEN[0]
        try:
            _reinit_backend()
        except Exception as e:
            log.warning("backend reinit failed (continuing): %s", e)
        with _LOCK:
            if _QUAR_GEN[0] == gen:
                # no NEW quarantine arrived while reinitializing — clear;
                # otherwise leave the flag set so the fresh fence request
                # gets its own reinit on the next dispatch
                _QUARANTINED[0] = False
            STATS["fences"] += 1


def _reinit_backend():
    import jax
    if jax.default_backend() == "cpu":
        # the in-process XLA-CPU client has no tunnel to die: its
        # compiled executables stay valid through any stall (test hangs
        # are injected sleeps), so flushing them would only force cold
        # recompiles — and a deadline shorter than compile time would
        # livelock on hang→flush→cold-compile→hang. The fence is pure
        # accounting here; real reinit work is the off-CPU path below.
        return
    # compiled-executable caches first: they pin jitted programs (and the
    # dictionaries/arrays they close over) against the suspect client
    from ..utils.backoff import classify
    try:
        from . import device_exec
        # under the pipe-stats lock: _pipe_cache_get's locked
        # get/move_to_end pair must never interleave with this clear,
        # and a _topk_indices install racing it unlocked could
        # re-publish a kernel pinning the dead client
        with device_exec._PIPE_LOCK:
            device_exec._PIPE_CACHE.clear()
            device_exec._TOPK_CACHE.clear()
    except Exception as e:
        # best-effort: the fence proceeds, but a cache that would not
        # clear may still pin dead-client executables — log it
        log.warning("fence: pipe-cache clear failed (%s): %s",
                    classify(e), e)
    try:
        from . import mpp_exec
        # under the placement lock: _place_col's locked check/popitem
        # pair must never interleave with this clear
        with mpp_exec._PLACE_LOCK:
            mpp_exec._MPP_PLACE_CACHE.clear()
    except Exception as e:
        log.warning("fence: mpp placement-cache clear failed (%s): %s",
                    classify(e), e)
    try:
        # the compile service's origin map described entries of the pipe
        # cache just cleared above; its RECIPES survive — they are how
        # the prewarm ladder rebuilds against the fresh client
        from . import compile_service
        compile_service.on_backend_reinit()
    except Exception as e:
        log.warning("fence: compile-service reinit hook failed (%s): %s",
                    classify(e), e)
    try:
        jax.clear_caches()
    except Exception as e:
        log.warning("fence: jax.clear_caches failed (%s): %s",
                    classify(e), e)
    # hard teardown: a hung PJRT tunnel's arrays are dead anyway, so
    # re-dialing the client is the only road back
    for clear in ("clear_backends",):
        fn = getattr(getattr(getattr(jax, "extend", None), "backend",
                             None), clear, None) or getattr(
                                 jax, clear, None)
        if fn is not None:
            try:
                fn()
                log.warning("JAX backend torn down after hang; next "
                            "dispatch re-initializes the PJRT client")
                break
            except Exception:
                continue


# -- deadlines ---------------------------------------------------------------

def deadline_for(ctx) -> tuple:
    """(deadline_s, fence_on_expiry) for one device call.

    deadline_s is min(`tidb_device_call_timeout`, remaining
    `max_execution_time` window); 0 = unsupervised (inline dispatch,
    today's default).  fence_on_expiry is False when the BINDING
    constraint is the user's max_execution_time: its expiry is a
    statement-time limit, not evidence the backend hung — the call is
    abandoned but the backend is neither fenced nor charged to the
    breaker (expiry surfaces as QueryInterrupted, the same answer the
    racing kill Timer gives)."""
    if ctx is None:
        return 0.0, True
    t = 0.0
    try:
        t = float(ctx.get_sysvar("tidb_device_call_timeout"))
    except Exception:
        pass
    met_ms = 0.0
    try:
        met_ms = float(ctx.get_sysvar("max_execution_time"))
    except Exception:
        pass
    if met_ms > 0:
        rem = met_ms / 1000.0
        start = getattr(ctx, "stmt_start", None)
        if start:
            # floor, not zero: the kill Timer is the authority on expiry;
            # the supervisor just needs the wait to stay interruptible
            rem = max(rem - (time.time() - start), 0.05)
        if t <= 0 or rem < t:
            return rem, False
    return max(t, 0.0), True


def effective_deadline(ctx) -> float:
    """Seconds of wall clock a device call may take before it is declared
    hung (see :func:`deadline_for` for the expiry semantics)."""
    return deadline_for(ctx)[0]


# -- the supervised dispatch -------------------------------------------------

class _DeadlineExpired(Exception):
    pass


def supervised_call(fn, /, *args, deadline_s: float = 0.0, ctx=None,
                    shape: str = "", label: str = "", **kw):
    """Convenience form of :func:`call_supervised` — safe only when `fn`
    takes no keyword that collides with the supervisor's own parameters
    (run_device dispatches fragments whose kwargs include ``ctx=``, so it
    uses the explicit core instead)."""
    return call_supervised(fn, args, kw, deadline_s=deadline_s, ctx=ctx,
                           shape=shape, label=label)


def call_supervised(fn, args=(), kw=None, *, deadline_s: float = 0.0,
                    ctx=None, shape: str = "", label: str = "",
                    fence_on_expiry: bool = True):
    """Run ``fn(*args, **kw)`` under the supervisor.

    deadline_s <= 0: inline call (after the fence check) — zero overhead,
    the default when no timeout sysvar is set.  Otherwise the call runs
    on a worker thread; the waiter polls ``ctx.check_killed`` and the
    deadline.  Raises :class:`DeviceHangError` on expiry (call abandoned,
    backend fenced); a KILL raises the session's QueryInterruptedError
    with the call abandoned but the backend NOT fenced (no evidence it is
    unhealthy — its verdict simply stopped mattering)."""
    kw = kw or {}
    _maybe_reinit()
    from ..session import tracing
    if deadline_s is None or deadline_s <= 0:
        # the unsupervised hot path stays a bool check + plain call —
        # sink registration only matters once supervision can fire
        # (tracing off adds exactly the one active() branch)
        if tracing.active() is None:
            return fn(*args, **kw)
        with tracing.span("supervisor.call", inline=True, shape=shape):
            return fn(*args, **kw)
    with tracing.span("supervisor.call", deadline_s=round(deadline_s, 3),
                      shape=shape):
        return _call_on_worker(fn, args, kw, deadline_s, ctx, shape,
                               label, fence_on_expiry)


def _call_on_worker(fn, args, kw, deadline_s, ctx, shape, label,
                    fence_on_expiry):
    from ..session import tracing
    _register_sink(ctx)
    label = label or getattr(fn, "__name__", "device call")
    job = _Job(fn, args, kw, label)
    job.trace = tracing.capture()
    try:
        from ..ops import residency
        job.group = residency.current_group()
    except Exception:
        pass
    with _LOCK:
        STATS["supervised"] += 1
    _get_worker().inbox.put(job)
    check = getattr(ctx, "check_killed", None)
    deadline = time.monotonic() + deadline_s
    try:
        while not job.done.wait(_POLL_S):
            if check is not None:
                check()
            if time.monotonic() >= deadline:
                raise _DeadlineExpired()
    except _DeadlineExpired:
        if not _abandon(job, hang=fence_on_expiry):
            # the call completed inside the deadline race window (one
            # poll tick): nothing was abandoned or fenced — use the
            # finished result instead of raising a hang that the
            # gauges/stats would contradict
            _tls_apply(job.tls)
            if job.exc is not None:
                raise job.exc
            return job.result
        tracing.event("supervisor.abandoned", label=label,
                      deadline_s=round(deadline_s, 3),
                      fenced=fence_on_expiry)
        if not fence_on_expiry:
            # the binding deadline was the user's max_execution_time: a
            # statement-time limit, not a backend-health verdict — no
            # fence, no breaker charge, same answer as the kill Timer
            from ..errors import QueryInterruptedError
            raise QueryInterruptedError(
                "Query execution was interrupted, maximum statement "
                f"execution time exceeded (device call '{label}' "
                "abandoned)") from None
        exc = DeviceHangError(
            f"device call '{label}' exceeded its {deadline_s:.3f}s "
            "deadline (tidb_device_call_timeout/max_execution_time); "
            "call abandoned on its worker thread, backend fenced for "
            "reinit before the next fragment")
        exc.shape = shape
        exc.deadline_s = deadline_s
        raise exc from None  # the internal deadline marker is noise
    except BaseException:
        # KILL (check_killed), SIGALRM-driven timeouts in the waiter,
        # Ctrl-C: the in-flight call is orphaned but the backend earned
        # no hang verdict — account, don't fence
        _abandon(job, hang=False)
        raise
    _tls_apply(job.tls)
    if job.exc is not None:
        raise job.exc
    return job.result


def submit_coproc(fn, args=(), kw=None, *, label: str = ""):
    """Dispatch ``fn`` on a pooled supervisor worker WITHOUT blocking the
    caller — the host half of a hybrid-join co-processing pass
    (executor/hybrid_join.py): the calling thread keeps driving the
    device partitions while the worker joins the spilled partitions in
    numpy.  The pair runs under the caller's ONE admission ticket (the
    WFQ already governs the dispatch this pass belongs to — this is one
    admitted fragment using host and device at once, not a second
    dispatch, so no new ticket and no breaker interaction here).

    Trace context and residency tenant group bridge onto the worker like
    any supervised call.  Returns ``join(ctx=None)``: wait for
    completion (KILL-interruptible through ``ctx.check_killed``),
    re-raise the worker's exception, or return its result.  A waiter
    that gives up (kill/exception) abandons the job kill-style: no fence
    — the worker is running numpy, not a suspect backend."""
    kw = kw or {}
    job = _Job(fn, args, kw, label or getattr(fn, "__name__", "coproc"))
    from ..session import tracing
    job.trace = tracing.capture()
    try:
        from ..ops import residency
        job.group = residency.current_group()
    except Exception:
        pass
    with _LOCK:
        STATS["supervised"] += 1
        STATS["coproc"] += 1
    _get_worker().inbox.put(job)

    def join(ctx=None):
        check = getattr(ctx, "check_killed", None)
        try:
            while not job.done.wait(_POLL_S):
                if check is not None:
                    check()
        except BaseException:
            _abandon(job, hang=False)
            raise
        _tls_apply(job.tls)
        if job.exc is not None:
            raise job.exc
        return job.result

    return join


def _abandon(job: _Job, hang: bool) -> bool:
    """Mark the job orphaned; returns False when it actually COMPLETED in
    the race window (nothing outstanding — the caller should use the
    result instead of reporting an abandonment)."""
    with _LOCK:
        if job.done.is_set():
            return False  # completed in the race window
        job.orphaned = True
        _ABANDONED[0] += 1
        STATS["abandoned"] += 1
        if hang:
            STATS["hangs"] += 1
            _quarantine_locked()
        else:
            STATS["kills"] += 1
    if hang:
        log.warning("device call '%s' abandoned after deadline; backend "
                    "quarantined (%d abandoned calls outstanding)",
                    job.label, abandoned_calls())
    _publish()
    return True
