"""Streamed device aggregation: batched host→HBM transfers with on-device
partial-state merge (the cop-iterator overlap analog, reference:
store/copr/coprocessor.go:399; long-operand scaling per SURVEY §5)."""

import random

import pytest

from tidb_tpu.testkit import TestKit

N_ROWS = 20_000
BATCH = 3_000  # forces 7 blocks


def _rows_equal(a, b, float_cols=()):
    """Row-set equality with ulp-tolerance on float columns (partial-sum
    order legitimately changes the last digits)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        for i, (va, vb) in enumerate(zip(ra, rb)):
            if i in float_cols:
                if va is None or vb is None:
                    if va != vb:
                        return False
                elif abs(float(va) - float(vb)) > 1e-9 * max(
                        1.0, abs(float(va))):
                    return False
            elif va != vb:
                return False
    return True


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table s (grp int, cat varchar(8), amount int, "
                 "price double, d date)")
    random.seed(7)
    rows = []
    for i in range(N_ROWS):
        rows.append(f"({i % 13}, 'c{i % 5}', {i % 97}, "
                    f"{round(random.random() * 10, 3)}, "
                    f"'202{i % 3}-0{i % 9 + 1}-15')")
    for lo in range(0, len(rows), 2000):
        tk.must_exec("insert into s values " + ",".join(rows[lo:lo + 2000]))
    return tk


QUERY = ("select grp, cat, count(*), sum(amount), min(amount), max(amount), "
         "avg(price) from s where amount > 10 group by grp, cat "
         "order by grp, cat")


class TestStreamedCountDistinct:
    """Streamed COUNT(DISTINCT x): per-block (group, x) pair dedup +
    one final cnt_dist over the concatenated pairs (the two-phase
    distinct agg, reference executor/aggregate.go)."""

    def _both(self, tk, sql):
        import tidb_tpu.executor.device_exec as de
        calls = []
        orig = de._stream_count_distinct

        def spy(*a, **k):
            r = orig(*a, **k)
            calls.append(1)
            return r

        de._stream_count_distinct = spy
        try:
            tk.must_exec("set tidb_executor_engine = 'tpu'")
            tk.must_exec(f"set tidb_device_stream_rows = {BATCH}")
            stream = tk.must_query(sql).rows
        finally:
            de._stream_count_distinct = orig
            tk.must_exec("set tidb_device_stream_rows = 0")
        assert calls, "streamed count-distinct path did not run"
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(sql).rows
        assert stream == host, sql
        return stream

    def test_grouped(self, tk):
        self._both(tk, "select grp, count(distinct amount) from s "
                       "group by grp order by grp")

    def test_global(self, tk):
        rows = self._both(tk, "select count(distinct amount) from s")
        assert rows[0][0] == "97"

    def test_nulls_ignored(self, tk):
        tk.must_exec("create table cdn (g bigint, v bigint)")
        tk.must_exec("insert into cdn values (1,1),(1,null),(1,1),(1,2),"
                     "(2,null),(2,null)")
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec("set tidb_device_stream_rows = 2")
        rows = tk.must_query("select g, count(distinct v) from cdn "
                             "group by g order by g").rows
        tk.must_exec("set tidb_device_stream_rows = 0")
        assert rows == [("1", "2"), ("2", "0")]


class TestStreamedAgg:
    def test_parity_stream_vs_whole_vs_host(self, tk):
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {BATCH}")
        stream = tk.must_query(QUERY).rows
        tk.must_exec("set tidb_device_stream_rows = 0")
        whole = tk.must_query(QUERY).rows
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(QUERY).rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert _rows_equal(stream, whole, float_cols={6})
        assert _rows_equal(stream, host, float_cols={6})
        assert len(stream) == 13 * 5

    def test_stream_fragment_annotated(self, tk):
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {BATCH}")
        txt = "\n".join(" ".join(map(str, r)) for r in
                        tk.must_query("explain analyze " + QUERY).rows)
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert "tpu-stream" in txt

    def test_global_agg_streams(self, tk):
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {BATCH}")
        got = tk.must_query("select count(*), sum(amount) from s").rows
        tk.must_exec("set tidb_executor_engine = 'host'")
        want = tk.must_query("select count(*), sum(amount) from s").rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert got == want

    def test_date_group_key_streams(self, tk):
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {BATCH}")
        got = tk.must_query("select d, count(*) from s group by d "
                            "order by d").rows
        tk.must_exec("set tidb_executor_engine = 'host'")
        want = tk.must_query("select d, count(*) from s group by d "
                             "order by d").rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert got == want

    def test_tail_batch_smaller_than_block(self, tk):
        """N_ROWS % BATCH != 0: the tail block retraces and still merges."""
        assert N_ROWS % BATCH != 0
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        tk.must_exec(f"set tidb_device_stream_rows = {BATCH}")
        got = tk.must_query("select grp, count(*) from s group by grp "
                            "order by grp").rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert sum(int(r[1]) for r in got) == N_ROWS
