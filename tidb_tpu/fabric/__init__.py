"""Cross-process serving fabric: an N-process fleet over shared devices.

Why this exists (ROADMAP open item 3, ISSUE 14): everything through PR 13
serves from ONE Python process, so the GIL — not the device — is the
ceiling on concurrent sessions.  The paper's reference architecture runs
many tidb-server instances over one store (PAPER.md layer map); this
package is that layer for the reproduction: a parent supervisor forks N
worker processes, each with its own Domain and MySQL wire listener
behind one advertised port (``SO_REUSEPORT``), in front of the shared
device and the shared compile artifacts.

The pieces:

* :mod:`~tidb_tpu.fabric.fleet` — the parent supervisor: spawn N
  workers, restart-on-crash with backoff, drain-on-shutdown.
* :mod:`~tidb_tpu.fabric.worker` — one serving process: Domain + wire
  listener + fleet-unique connection ids + lease heartbeat.
* :mod:`~tidb_tpu.fabric.coord` — the shared-memory coordination
  segment (``multiprocessing.shared_memory`` + a lease-stamped
  coordinator file): fleet-wide WFQ virtual clocks, per-tenant running
  caps and HBM charges, fragment-dedup slots, crash-lease reclaim.
* :mod:`~tidb_tpu.fabric.dedup` — result-identical fragment dedup:
  identical concurrent ``(plan sig, data sig, bucket shape)`` fragments
  anywhere in the fleet dispatch ONE device call; the result ships back
  through a per-fragment mmap page.
* :mod:`~tidb_tpu.fabric.compile_server` / ``compile_client`` — the
  separated compile service: one subprocess per host owns the expensive
  XLA compiles behind a length-prefixed socket protocol; workers trace
  locally (cheap), the server compiles into the shared host-fingerprinted
  AOT cache, and serialized ``jax.export`` artifacts let a SECOND worker
  serve the fragment with zero new local traces.
* :mod:`~tidb_tpu.fabric.state` — this process's fabric identity (slot,
  coordinator handle, compile-server address) + the ``fabric_*`` gauges.
* :mod:`~tidb_tpu.fabric.region` / :mod:`~tidb_tpu.fabric.blob` /
  :mod:`~tidb_tpu.fabric.coord_net` — the multi-host region layer
  (ISSUE 16): the keyspace sharded into N regions, each with its own
  WAL, epoch-fenced lease cells, and blob-store replication so a HOST
  loss is a region failover (a survivor restores checkpoint + tail and
  replays) instead of data loss; ``coord_net`` puts the segment's
  lease/epoch/claim/TSO surface behind a TCP service for cross-host
  callers.

The seven-layer resilience stack a fragment now passes: REGION (epoch-
fenced keyspace shards + blob failover) → FABRIC (process fleet +
dedup) → ADMISSION (fleet-coordinated WFQ) → COMPILE SERVICE →
SUPERVISOR deadline → BREAKER → RESIDENCY (fleet-aware tenant shares).

Confinement: direct ``multiprocessing.shared_memory`` use is lint-pinned
to this package (tidb_tpu/lint/rules/confinement.py), and so is raw
``socket`` use for coordination (the MySQL wire protocol in ``server/``
is the one other legitimate socket owner) — every other layer
coordinates through :mod:`state`'s typed hooks.
"""

from __future__ import annotations

#: fleet-unique connection ids: worker slot k mints ids with this base —
#: ``conn_id = ((slot + 1) << CONN_SLOT_SHIFT) + seq`` — so two workers
#: can never allocate the same id (KILL and slow-log attribution resolve
#: by conn id), and the slot is recoverable from any id for per-process
#: latency attribution in bench_serve's fleet mode.  24 bits keeps the
#: full id inside the MySQL handshake's u32 connection-id field (255
#: slots x 16M connections per incarnation).
CONN_SLOT_SHIFT = 24


def conn_id_base(slot: int) -> int:
    """The conn-id allocation base for worker ``slot`` (0-based)."""
    return (int(slot) + 1) << CONN_SLOT_SHIFT


def slot_of_conn_id(conn_id: int) -> "int | None":
    """The worker slot that minted ``conn_id``, or None for a
    non-fabric (single-process) id."""
    hi = int(conn_id) >> CONN_SLOT_SHIFT
    return hi - 1 if hi > 0 else None
